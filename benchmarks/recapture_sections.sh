#!/bin/bash
# Re-run chip_session sections listed in $SECTIONS (default: all) one at a
# time, each gated on its own healthy probe — the per-section process
# means a mid-list tunnel death costs only the sections not yet run, and
# sections already measured don't repeat. Probes every 4 min; if the
# tunnel stays dead through one section's full probe budget (~13h), the
# remaining sections are logged as skipped rather than each restarting
# their own probe loop.
cd /root/repo
LOG=${LOG:-.scratch/capture/recapture.log}
SECTIONS=${SECTIONS:-"step-xla step-fusednorm trace mbs-4 mbs-8 mbs-16 long-8192 long-16384 long-32768 1b decode"}
mkdir -p "$(dirname "$LOG")"
echo "=== recapture $(date): $SECTIONS ===" >> "$LOG"
for sec in $SECTIONS; do
  ran=0
  for i in $(seq 1 200); do
    if bash benchmarks/probe_tunnel.sh > /dev/null; then
      echo "-- $(date +%H:%M:%S) tunnel alive; running $sec" >> "$LOG"
      timeout 1500 python benchmarks/chip_session.py "$sec" >> "$LOG" 2>&1 \
        || echo "-- section $sec: exited rc=$?" >> "$LOG"
      ran=1
      break
    fi
    sleep 240
  done
  if [[ $ran == 0 ]]; then
    echo "-- gave up: tunnel dead through $sec's whole probe budget;" \
         "skipping remaining sections" >> "$LOG"
    break
  fi
done
echo "=== recapture done $(date) ===" >> "$LOG"
