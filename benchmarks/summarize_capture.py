"""Turn a capture session's logs into the PERF.md evidence table.

Reads the files `capture_on_tunnel.sh` writes (or any directory holding
bench/chip-session output) and prints one markdown block: the bench JSON
rows, every chip-session measurement, and the tuned-pass winners — so a
healthy-tunnel window turns into committed evidence in one paste.

Usage: python benchmarks/summarize_capture.py [capture_dir]
       (default .scratch/capture)
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

SECTION_RE = re.compile(r"^([0-9]+(?:/[0-9]+)?)\. (.+?):\s*(.+)$")


def bench_rows(capture: Path) -> list:
    rows = []
    for name in ("bench_05b", "bench_1b", "bench_tuned"):
        f = capture / f"{name}.log"
        if not f.is_file():
            continue
        rec = None
        for line in f.read_text().splitlines():
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
        rc = re.search(r"rc=(\d+)", f.read_text())
        rows.append((name, rec, int(rc.group(1)) if rc else None))
    return rows


def session_lines(capture: Path) -> list:
    f = capture / "chip_session.log"
    if not f.is_file():
        return []
    out = []
    for line in f.read_text().splitlines():
        m = SECTION_RE.match(line.strip())
        if m:
            out.append((m.group(1), m.group(2), m.group(3)))
    return out


def main() -> None:
    capture = Path(sys.argv[1] if len(sys.argv) > 1 else ".scratch/capture")
    if not capture.is_dir():
        sys.exit(f"no capture directory at {capture}")

    print("### Captured on-chip evidence\n")
    rows = bench_rows(capture)
    if rows:
        print("| bench arm | tokens/s | MFU | vs measured peak | mbs | kernel | rc |")
        print("|---|---|---|---|---|---|---|")
        for name, rec, rc in rows:
            if rec is None:
                print(f"| {name} | — | — | — | — | — | {rc} |")
                continue
            print(
                f"| {name} ({rec.get('model', '?')}) | {rec['value']} "
                f"| {rec.get('mfu')} | {rec.get('mfu_vs_measured_peak')} "
                f"| {rec.get('micro_batch_size')} | {rec.get('kernel')} | {rc} |"
            )
        print()
    lines = session_lines(capture)
    if lines:
        print("| session arm | measurement |")
        print("|---|---|")
        for _num, name, value in lines:
            print(f"| {name} | {value} |")
        print()
    if not rows and not lines:
        print("(capture directory holds no parseable results)")


if __name__ == "__main__":
    main()
