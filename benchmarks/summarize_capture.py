"""Turn a capture session's logs into the PERF.md evidence table.

Reads the files `capture_on_tunnel.sh` writes (or any directory holding
bench/chip-session output) and prints one markdown block: the bench JSON
rows, every chip-session measurement, and the tuned-pass winners — so a
healthy-tunnel window turns into committed evidence in one paste.

Usage: python benchmarks/summarize_capture.py [capture_dir]
       (default .scratch/capture)
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

SECTION_RE = re.compile(r"^([0-9]+(?:/[0-9]+)?)\. (.+?):\s*(.+)$")


def bench_rows(capture: Path) -> list:
    rows = []
    for name in ("bench_05b", "bench_1b", "bench_tuned",
                 "bench_final_05b", "bench_final_1b"):
        f = capture / f"{name}.log"
        if not f.is_file():
            continue
        rec = None
        for line in f.read_text().splitlines():
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
        rc = re.search(r"rc=(\d+)", f.read_text())
        rows.append((name, rec, int(rc.group(1)) if rc else None))
    return rows


def session_lines(capture: Path) -> list:
    """Section measurements from every session/recapture log, later files
    winning on duplicate labels (a recaptured section supersedes the
    original run's FAIL)."""
    seen: dict = {}
    order: list = []
    for fname in ("chip_session.log", "chip_session2.log", "recapture.log"):
        f = capture / fname
        if not f.is_file():
            continue
        for line in f.read_text().splitlines():
            m = SECTION_RE.match(line.strip())
            if m:
                key = (m.group(1), m.group(2))
                if key not in seen:
                    order.append(key)
                elif m.group(3).startswith("FAIL") and not seen[key].startswith("FAIL"):
                    # a failed re-run must not clobber a real measurement
                    continue
                seen[key] = m.group(3)
    return [(num, name, seen[(num, name)]) for num, name in order]


def main() -> None:
    capture = Path(sys.argv[1] if len(sys.argv) > 1 else ".scratch/capture")
    if not capture.is_dir():
        sys.exit(f"no capture directory at {capture}")

    print("### Captured on-chip evidence\n")
    rows = bench_rows(capture)
    if rows:
        print("| bench arm | tokens/s | MFU | vs measured peak | mbs | kernel | rc |")
        print("|---|---|---|---|---|---|---|")
        for name, rec, rc in rows:
            if rec is None:
                print(f"| {name} | — | — | — | — | — | {rc} |")
                continue
            print(
                f"| {name} ({rec.get('model', '?')}) | {rec['value']} "
                f"| {rec.get('mfu')} | {rec.get('mfu_vs_measured_peak')} "
                f"| {rec.get('micro_batch_size')} | {rec.get('kernel')} | {rc} |"
            )
        print()
    lines = session_lines(capture)
    if lines:
        print("| session arm | measurement |")
        print("|---|---|")
        for _num, name, value in lines:
            print(f"| {name} | {value} |")
        print()
    if not rows and not lines:
        print("(capture directory holds no parseable results)")


if __name__ == "__main__":
    main()
