"""Turn a capture session's logs into the PERF.md evidence table.

Reads the files `capture_on_tunnel.sh` writes (or any directory holding
bench/chip-session output) and prints one markdown block: the bench JSON
rows, every chip-session measurement, and the tuned-pass winners — so a
healthy-tunnel window turns into committed evidence in one paste.

Usage: python benchmarks/summarize_capture.py [capture_dir] [--artifacts TAG]
       (default .scratch/capture; --artifacts writes each fresh non-stale
       bench row to benchmarks/artifacts/BENCH_MIDROUND_{TAG}_{arm}.json so
       a capture that completes unattended still lands committed evidence)
"""

from __future__ import annotations

import json
import re
import sys
import time
from pathlib import Path

SECTION_RE = re.compile(r"^([0-9]+(?:/[0-9]+)?)\. (.+?):\s*(.+)$")
HEADER_RE = re.compile(r"^=== bench \S+ (.+?) ===$")

# `$(date)` spellings capture_on_tunnel.sh may have written, with and
# without a timezone token
_DATE_FORMATS = (
    "%a %b %d %H:%M:%S %Z %Y",
    "%a %d %b %H:%M:%S %Z %Y",
    "%a %b %d %H:%M:%S %Y",
    "%Y-%m-%dT%H:%M:%SZ",
)


def _parse_header_date(raw: str):
    """ISO-8601 UTC string for the log header's `$(date)` output, or None.

    Only UTC/GMT (or tz-less) headers get the 'Z' stamp — claiming UTC
    for a 'CEST' wall-clock time would be hours wrong, worse than the
    flagged summarize-time fallback."""
    raw = raw.strip()
    tz_tokens = {t for t in raw.split() if t.isalpha() and t.isupper()
                 and 2 <= len(t) <= 5 and t not in ("AM", "PM")}
    if tz_tokens - {"UTC", "GMT"}:
        return None
    for fmt in _DATE_FORMATS:
        try:
            return time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.strptime(raw, fmt)
            )
        except ValueError:
            continue
    return None


def bench_captured_at(text: str):
    """When the bench actually ran: the `=== bench <label> <date> ===`
    header capture_on_tunnel.sh writes (ADVICE r5 — the summarizer's own
    run time mislabels artifacts when old logs are summarized later)."""
    for line in text.splitlines():
        m = HEADER_RE.match(line.strip())
        if m:
            return _parse_header_date(m.group(1))
    return None


def bench_rows(capture: Path) -> list:
    rows = []
    for name in ("bench_05b", "bench_05b_lora", "bench_1b", "bench_tuned",
                 "bench_final_05b", "bench_final_1b", "bench_final_05b_lora"):
        f = capture / f"{name}.log"
        if not f.is_file():
            continue
        text = f.read_text()
        rec = None
        for line in text.splitlines():
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
        rc = re.search(r"rc=(\d+)", text)
        rows.append(
            (name, rec, int(rc.group(1)) if rc else None,
             bench_captured_at(text))
        )
    return rows


def session_lines(capture: Path) -> list:
    """Section measurements from every session/recapture log, later files
    winning on duplicate labels (a recaptured section supersedes the
    original run's FAIL)."""
    seen: dict = {}
    order: list = []
    for fname in ("chip_session.log", "chip_session2.log", "recapture.log"):
        f = capture / fname
        if not f.is_file():
            continue
        for line in f.read_text().splitlines():
            m = SECTION_RE.match(line.strip())
            if m:
                key = (m.group(1), m.group(2))
                if key not in seen:
                    order.append(key)
                elif m.group(3).startswith("FAIL") and not seen[key].startswith("FAIL"):
                    # a failed re-run must not clobber a real measurement
                    continue
                seen[key] = m.group(3)
    return [(num, name, seen[(num, name)]) for num, name in order]


def write_artifacts(rows: list, tag: str) -> None:
    """One committed artifact per fresh (non-stale, rc=0) bench row,
    stamped with the bench run's own log-header time (falling back to the
    summarizer's clock, flagged, only when no header parsed)."""
    outdir = Path(__file__).resolve().parent / "artifacts"
    outdir.mkdir(exist_ok=True)
    for name, rec, rc, captured in rows:
        if rec is None or rec.get("stale") or rc != 0:
            continue
        arm = name.replace("bench_", "")
        out = outdir / f"BENCH_MIDROUND_{tag}_{arm}.json"
        payload = {
            "captured": captured
            or time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "command": f"capture_on_tunnel.sh arm {name}",
            "result": rec,
        }
        if captured is None:
            payload["captured_is_summarize_time"] = True
        out.write_text(json.dumps(payload, indent=1) + "\n")
        print(f"wrote {out}", file=sys.stderr)


def main() -> None:
    argv = list(sys.argv[1:])
    tag = None
    if "--artifacts" in argv:
        i = argv.index("--artifacts")
        tag = argv[i + 1] if i + 1 < len(argv) else "r0"
        del argv[i : i + 2]  # by index: a capture dir named like the tag survives
    capture = Path(argv[0] if argv else ".scratch/capture")
    if not capture.is_dir():
        sys.exit(f"no capture directory at {capture}")
    rows = bench_rows(capture)
    if tag:
        write_artifacts(rows, tag)

    print("### Captured on-chip evidence\n")
    if rows:
        print("| bench arm | tokens/s | MFU | vs measured peak | mbs | kernel | rc |")
        print("|---|---|---|---|---|---|---|")
        for name, rec, rc, _captured in rows:
            if rec is None:
                print(f"| {name} | — | — | — | — | — | {rc} |")
                continue
            print(
                f"| {name} ({rec.get('model', '?')}) | {rec['value']} "
                f"| {rec.get('mfu')} | {rec.get('mfu_vs_measured_peak')} "
                f"| {rec.get('micro_batch_size')} | {rec.get('kernel')} | {rc} |"
            )
        print()
    lines = session_lines(capture)
    if lines:
        print("| session arm | measurement |")
        print("|---|---|")
        for _num, name, value in lines:
            print(f"| {name} | {value} |")
        print()
    if not rows and not lines:
        print("(capture directory holds no parseable results)")


if __name__ == "__main__":
    main()
