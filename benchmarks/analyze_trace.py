"""Schema-free xplane trace parser -> top ops by self time per trace line.

No external tooling: the installed tensorboard profile plugin's generated
protos are incompatible with the installed protobuf, so this walks the
wire format directly. Field numbers (verified empirically via
``protoc --decode_raw``):
  XSpace.planes=1; XPlane: name=2, lines=3, event_metadata=4 (map k=1 v=2);
  XEventMetadata: id=1, name=2; XLine: id=1, name=2, timestamp=3, events=4;
  XEvent: metadata_id=1, offset_ps=2, duration_ps=3.

Usage: python benchmarks/analyze_trace.py <trace_dir> [line-filter]
"""
import glob
import sys
from collections import defaultdict


def read_varint(buf, i):
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def walk(buf):
    """Yield (field_number, wire_type, value) for one message buffer."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = read_varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = read_varint(buf, i)
        elif wt == 1:
            v, i = buf[i : i + 8], i + 8
        elif wt == 2:
            ln, i = read_varint(buf, i)
            v, i = buf[i : i + ln], i + ln
        elif wt == 5:
            v, i = buf[i : i + 4], i + 4
        else:
            raise ValueError(f"wire type {wt}")
        yield fn, wt, v


def fields(buf, fn_want):
    return [v for fn, _, v in walk(buf) if fn == fn_want]


def first_varint(buf, fn_want, default=0):
    for fn, wt, v in walk(buf):
        if fn == fn_want and wt == 0:
            return v
    return default


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/bench_trace"
    line_filter = sys.argv[2] if len(sys.argv) > 2 else ""
    files = glob.glob(f"{path}/**/*.xplane.pb", recursive=True)
    if not files:
        sys.exit(f"no xplane files under {path}")
    for f in files:
        space = open(f, "rb").read()
        for plane in fields(space, 1):
            pname = b"".join(fields(plane, 2)).decode(errors="replace")
            ev_names = {}
            for entry in fields(plane, 4):
                key = first_varint(entry, 1)
                for meta in fields(entry, 2):
                    nm = b"".join(
                        v for fn, wt, v in walk(meta) if fn == 2 and wt == 2
                    ).decode(errors="replace")
                    ev_names[key] = nm
            for line in fields(plane, 3):
                lname = b"".join(
                    v for fn, wt, v in walk(line) if fn == 2 and wt == 2
                ).decode(errors="replace")
                if line_filter and line_filter not in lname:
                    continue
                totals = defaultdict(int)
                counts = defaultdict(int)
                for ev in fields(line, 4):
                    mid = first_varint(ev, 1)
                    dur = first_varint(ev, 3)
                    totals[ev_names.get(mid, str(mid))] += dur
                    counts[ev_names.get(mid, str(mid))] += 1
                tot = sum(totals.values())
                if tot < 1e6:  # skip sub-microsecond lines
                    continue
                print(f"== {pname} :: {lname}: {tot/1e9:.2f} ms total")
                for name, d in sorted(totals.items(), key=lambda kv: -kv[1])[:25]:
                    print(
                        f"   {d/1e9:9.3f} ms {100*d/tot:5.1f}% "
                        f"x{counts[name]:<5} {name[:100]}"
                    )
                buckets = defaultdict(int)
                for name, d in totals.items():
                    buckets[classify(name)] += d
                summary = "  ".join(
                    f"{b}={100*d/tot:.1f}%"
                    for b, d in sorted(buckets.items(), key=lambda kv: -kv[1])
                )
                print(f"   buckets: {summary}")


_BUCKETS = (
    # substring -> bucket; first match wins, so collectives beat the
    # generic 'fusion' and pallas custom-calls beat 'copy' inside names
    (("all-reduce", "all-gather", "reduce-scatter", "collective-permute",
      "all-to-all"), "collective"),
    (("custom-call", "tpu_custom_call", "splash", "flash", "mosaic"), "pallas"),
    (("dot", "convolution", "cublas", "matmul"), "matmul"),
    (("copy", "transpose", "bitcast", "reshape", "slice",
      "concatenate"), "layout"),
    (("fusion", "loop_"), "fused-elementwise"),
)


def classify(name: str) -> str:
    """Coarse MFU-attribution buckets by op-name substring. 'matmul' +
    'pallas' is the useful-FLOPs share; 'layout' + 'collective' is the
    overhead to attack. XLA names fusions after their root op
    ('loop_dot_fusion', 'loop_slice_fusion'), so a named root wins the
    bucket — that root dominates the fusion's time — and only anonymous
    fusions fall to the catch-all 'fused-elementwise' bucket."""
    low = name.lower()
    for subs, bucket in _BUCKETS:
        if any(s in low for s in subs):
            return bucket
    return "other"


if __name__ == "__main__":
    main()
