#!/bin/bash
# Shared probe: prints "OK" and exits 0 when the TPU tunnel answers within
# 60s, else prints "DEAD <reason>" and exits 1. Sourced-by/called-from
# capture_on_tunnel.sh and recapture_sections.sh so probe semantics can't
# drift between the two capture paths.
out=$(timeout 75 python -c "
from scaling_tpu.devices import probe_devices
devs, err = probe_devices(timeout_s=60)
print('OK' if devs else f'DEAD {err}')
" 2>/dev/null | tail -1)
echo "${out:-DEAD probe subprocess died}"
[[ "$out" == OK* ]]
