"""Scratch: isolate flash vs XLA attention fwd+bwd at the bench shape."""
import functools
import time

import jax
import jax.numpy as jnp

from scaling_tpu.ops.flash_attention import flash_attention_fused

B, S, N, NKV, D = 4, 2048, 16, 4, 128
scale = D ** -0.5


def timeit(fn, *args, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3


key = jax.random.PRNGKey(0)
q = jax.random.normal(key, (B, S, N, D), jnp.bfloat16)
k = jax.random.normal(key, (B, S, NKV, D), jnp.bfloat16)
v = jax.random.normal(key, (B, S, NKV, D), jnp.bfloat16)
seg = jnp.zeros((B, S), jnp.int32)


def flash(q, k, v):
    return flash_attention_fused(q, k, v, segment_ids=seg, sm_scale=scale)


def xla_attn(q, k, v):
    # repeat kv to full heads, causal masked softmax
    rep = N // NKV
    kk = jnp.repeat(k, rep, axis=2)
    vv = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bsnd,btnd->bnst", q, kk) * scale
    mask = jnp.tril(jnp.ones((S, S), bool))
    logits = jnp.where(mask[None, None], logits.astype(jnp.float32), -1e9)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", p, vv)


def loss_of(fn):
    def f(q, k, v):
        return fn(q, k, v).astype(jnp.float32).sum()
    return jax.jit(jax.grad(f, argnums=(0, 1, 2)))


fwd_flash = jax.jit(flash)
fwd_xla = jax.jit(xla_attn)
print(f"flash fwd : {timeit(fwd_flash, q, k, v):8.2f} ms")
print(f"xla   fwd : {timeit(fwd_xla, q, k, v):8.2f} ms")
print(f"flash f+b : {timeit(loss_of(flash), q, k, v):8.2f} ms")
print(f"xla   f+b : {timeit(loss_of(xla_attn), q, k, v):8.2f} ms")
