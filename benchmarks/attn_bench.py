"""Flash vs XLA attention fwd+bwd at the bench shape (one chip).

Importable by chip_session.py; run directly for just the micro-bench:
    cd /root/repo && python benchmarks/attn_bench.py
"""

import time

import jax
import jax.numpy as jnp

from scaling_tpu.ops.flash_attention import flash_attention_fused

B, S, N, NKV, D = 4, 2048, 16, 4, 128
SCALE = D**-0.5


def timeit(fn, *args, iters=10):
    """Median-of-3 windows (never min: a degraded tunnel can return a block
    early, and min would keep exactly that bogus sample — see PERF.md)."""
    out = fn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) / iters)
    return sorted(times)[1] * 1e3  # ms


def make_qkv(key=None):
    key = key if key is not None else jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, S, N, D), jnp.bfloat16)
    k = jax.random.normal(key, (B, S, NKV, D), jnp.bfloat16)
    v = jax.random.normal(key, (B, S, NKV, D), jnp.bfloat16)
    seg = jnp.zeros((B, S), jnp.int32)
    return q, k, v, seg


def flash(q, k, v, seg):
    return flash_attention_fused(q, k, v, segment_ids=seg, sm_scale=SCALE)


def xla_attn(q, k, v, seg):
    del seg  # single doc: the causal mask below covers it
    rep = N // NKV
    kk = jnp.repeat(k, rep, axis=2)
    vv = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bsnd,btnd->bnst", q, kk) * SCALE
    mask = jnp.tril(jnp.ones((S, S), bool))
    logits = jnp.where(mask[None, None], logits.astype(jnp.float32), -1e9)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", p, vv)


def xla_long(q, k, v, seg):
    """xla_attn with shapes derived from the inputs (the long-context sweep
    feeds arbitrary seq lengths; the fixed-S version above keeps the exact
    program the original A/B measured)."""
    del seg
    b, s, n, d = q.shape
    rep = n // k.shape[2]
    kk = jnp.repeat(k, rep, axis=2)
    vv = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bsnd,btnd->bnst", q, kk) * (d**-0.5)
    mask = jnp.tril(jnp.ones((s, s), bool))
    logits = jnp.where(mask[None, None], logits.astype(jnp.float32), -1e9)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", p, vv)


def fwd_bwd(fn):
    """fwd+bwd closure: grads of sum(fn) wrt q/k/v, jitted."""
    return jax.jit(
        jax.grad(
            lambda q, k, v, seg: fn(q, k, v, seg).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )
    )


def main():
    q, k, v, seg = make_qkv()
    print(f"flash fwd : {timeit(jax.jit(flash), q, k, v, seg):8.2f} ms")
    print(f"xla   fwd : {timeit(jax.jit(xla_attn), q, k, v, seg):8.2f} ms")
    print(f"flash f+b : {timeit(fwd_bwd(flash), q, k, v, seg):8.2f} ms")
    print(f"xla   f+b : {timeit(fwd_bwd(xla_attn), q, k, v, seg):8.2f} ms")


if __name__ == "__main__":
    main()
