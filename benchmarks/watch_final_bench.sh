#!/bin/bash
# After recapture_sections.sh finishes (or if it's not running), wait for
# a healthy tunnel and run the two bench arms once each — the final
# evidence pass. Compiles hit the persistent cache, so a short window
# suffices. Logs under .scratch/capture/.
cd /root/repo
LOG_DIR=.scratch/capture
mkdir -p "$LOG_DIR"
for i in $(seq 1 200); do
  if bash benchmarks/probe_tunnel.sh > /dev/null; then
    # let an in-flight recapture keep the chip to itself
    if pgrep -f recapture_sections.sh > /dev/null; then
      sleep 240
      continue
    fi
    echo "=== final bench 0.5b $(date) ===" > "$LOG_DIR/bench_final_05b.log"
    BENCH_WAIT_S=600 timeout 3600 python bench.py >> "$LOG_DIR/bench_final_05b.log" 2>&1
    echo "rc=$?" >> "$LOG_DIR/bench_final_05b.log"
    echo "=== final bench 1b $(date) ===" > "$LOG_DIR/bench_final_1b.log"
    BENCH_MODEL=1b BENCH_WAIT_S=600 timeout 3600 python bench.py >> "$LOG_DIR/bench_final_1b.log" 2>&1
    echo "rc=$?" >> "$LOG_DIR/bench_final_1b.log"
    echo "FINAL BENCH DONE $(date)"
    exit 0
  fi
  sleep 240
done
echo "tunnel never returned"
exit 1
