#!/bin/bash
# After recapture_sections.sh finishes (or if it's not running), wait for
# a healthy tunnel and run the three bench arms (0.5b, 1b, 0.5b-lora)
# once each — the final evidence pass. Compiles hit the persistent cache,
# so a short window suffices; a dead tunnel costs one BENCH_TOTAL_S
# watchdog window per arm at worst. Logs under .scratch/capture/.
cd /root/repo
LOG_DIR=.scratch/capture
mkdir -p "$LOG_DIR"
for i in $(seq 1 200); do
  if bash benchmarks/probe_tunnel.sh > /dev/null; then
    # let an in-flight recapture keep the chip to itself
    if pgrep -f recapture_sections.sh > /dev/null; then
      sleep 240
      continue
    fi
    for arm in ":05b" "1b:1b" "0.5b-lora:05b_lora"; do
      model="${arm%%:*}"
      label="${arm##*:}"
      echo "=== final bench $label $(date) ===" > "$LOG_DIR/bench_final_$label.log"
      env ${model:+BENCH_MODEL=$model} BENCH_WAIT_S=600 timeout 3600 \
        python bench.py >> "$LOG_DIR/bench_final_$label.log" 2>&1
      echo "rc=$?" >> "$LOG_DIR/bench_final_$label.log"
    done
    echo "FINAL BENCH DONE $(date)"
    exit 0
  fi
  sleep 240
done
echo "FINAL BENCH: tunnel never came up"
exit 1
