"""Scratch: one serial on-chip session for when the tunnel is healthy.

Runs, in order, each timed with block_until_ready (median-of-3):
  1. attention micro-bench: flash vs XLA fwd+bwd at the bench shape
  2. flash block-size sweep (512/512, 1024/1024, 2048/1024, 1024/2048)
  3. full train step A/B: flash vs torch kernel (shared params)
  4. norm A/B: BENCH_NORM fused vs torch with the flash kernel
  5. bench.py equivalent number + trace capture for analyze_trace2.py

Usage: cd /root/repo && python benchmarks/chip_session.py 2>&1 | tee /tmp/chip_session.log
"""
import os
import sys
import time

sys.path.insert(0, "/root/repo")
os.chdir("/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from scaling_tpu.devices import probe_devices

devs, err = probe_devices(timeout_s=60)
if devs is None:
    sys.exit(f"backend unreachable: {err}")
print(f"devices: {[d.device_kind for d in devs]}", flush=True)

import bench  # noqa: E402


def timeit(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) / iters)
    return sorted(times)[1] * 1e3  # median, ms


# ---------------------------------------------------------- 1. micro bench
from scaling_tpu.ops.flash_attention import flash_attention_fused  # noqa: E402

B, S, N, NKV, D = 4, 2048, 16, 4, 128
scale = D**-0.5
key = jax.random.PRNGKey(0)
q = jax.random.normal(key, (B, S, N, D), jnp.bfloat16)
k = jax.random.normal(key, (B, S, NKV, D), jnp.bfloat16)
v = jax.random.normal(key, (B, S, NKV, D), jnp.bfloat16)
seg = jnp.zeros((B, S), jnp.int32)


def flash(q, k, v):
    return flash_attention_fused(q, k, v, segment_ids=seg, sm_scale=scale)


def xla_attn(q, k, v):
    rep = N // NKV
    kk = jnp.repeat(k, rep, axis=2)
    vv = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bsnd,btnd->bnst", q, kk) * scale
    mask = jnp.tril(jnp.ones((S, S), bool))
    logits = jnp.where(mask[None, None], logits.astype(jnp.float32), -1e9)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", p, vv)


def fb(fn):
    return jax.jit(jax.grad(lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(), argnums=(0, 1, 2)))


print(f"1. attn flash f+b: {timeit(fb(flash), q, k, v):8.2f} ms", flush=True)
print(f"1. attn xla   f+b: {timeit(fb(xla_attn), q, k, v):8.2f} ms", flush=True)

# ------------------------------------------------------ 2. block-size sweep
for bq, bkv in ((512, 512), (1024, 1024), (2048, 1024), (1024, 2048)):
    os.environ["SCALING_TPU_FLASH_BLOCK_Q"] = str(bq)
    os.environ["SCALING_TPU_FLASH_BLOCK_KV"] = str(bkv)
    try:
        t = timeit(fb(flash), q, k, v)
        print(f"2. flash blocks q={bq} kv={bkv}: {t:8.2f} ms", flush=True)
    except Exception as e:
        print(f"2. flash blocks q={bq} kv={bkv}: FAIL {type(e).__name__}", flush=True)
os.environ.pop("SCALING_TPU_FLASH_BLOCK_Q", None)
os.environ.pop("SCALING_TPU_FLASH_BLOCK_KV", None)

# ------------------------------------------------- 3. full-step kernel A/B
def build_step(kernel, norm="torch"):
    os.environ["BENCH_KERNEL"] = kernel
    os.environ["BENCH_NORM"] = norm
    config, topology, module, optimizer = bench.build(2048, 4, 2048, 8)
    step = module.build_train_step(optimizer, bench.loss_function, donate=False)
    return config, module, optimizer, step


cfg, module, optimizer, step_f = build_step("flash_attention")
arch = cfg.transformer_architecture
params = module.shard_params(module.init_params(key))
opt_state = optimizer.init_state(params)
rng = np.random.default_rng(0)
batch = module.shard_batch(bench.synth_batch(rng, 4, 2048, arch.vocab_size, 1), stacked=True)
_, _, _, step_x = build_step("torch")
_, _, _, step_fn = build_step("flash_attention", norm="fused")


def run_step(stp):
    def f(params, opt_state):
        p2, o2, loss, _, _ = stp(params, opt_state, batch, key)
        return loss

    return f


for name, stp in (("flash", step_f), ("xla", step_x), ("flash+fusednorm", step_fn)):
    try:
        t = timeit(run_step(stp), params, opt_state, iters=3)
        print(f"3/4. step {name}: {t:8.1f} ms", flush=True)
    except Exception as e:
        print(f"3/4. step {name}: FAIL {type(e).__name__}: {e}", flush=True)

# --------------------------------------------------------- 5. trace capture
os.environ["BENCH_KERNEL"] = "flash_attention"
os.environ.pop("BENCH_NORM", None)
outdir = "/tmp/bench_trace_tpu"
jax.profiler.start_trace(outdir)
for i in range(2):
    loss = run_step(step_f)(params, opt_state)
jax.block_until_ready(loss)
jax.profiler.stop_trace()
print(f"5. trace written to {outdir}; analyze with "
      f"python benchmarks/analyze_trace.py {outdir}", flush=True)
