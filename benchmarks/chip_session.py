"""One serial on-chip measurement session (run when the chip is healthy).

Every section runs in its OWN subprocess. The round-4 capture proved why:
one RESOURCE_EXHAUSTED arm (the XLA full-step A/B duplicating ~9G of
model/optimizer state on a 16G v5e) poisoned the process's device memory
and every later section — mbs sweep, trace, long-context, 1b, decode —
failed with it, and an allocation outside a try block then killed the
session outright. A fresh process per section returns all HBM to the
backend between sections, so an OOM (often an *informative* result, e.g.
XLA attention at seq 32k) costs exactly one measurement.

Sections (labels are stable — summarize_capture.py and the tuned-pass
winner parser in capture_on_tunnel.sh grep them):
  0. achievable-peak probe (amortized dispatch, see bench.py)
  1. attention micro-bench: flash vs XLA fwd+bwd at the bench shape
  2. flash block-size sweep
  3/4. full train step A/B: flash vs XLA kernel vs flash+fused-norm
       (one arm per process; identical params from the same PRNGKey)
  5. trace capture for benchmarks/analyze_trace.py
  6. micro-batch sweep (4/8/16); winner feeds bench.py's BENCH_MBS
  7. long-context attention sweep, seq 8k/16k/32k (splash vs the ring's
     blockwise kernel vs XLA full attention — XLA OOM near 32k expected)
  8. 1B single-chip attempt (BASELINE #3 shape, every-layer remat, mbs 1)
  9. decode throughput (batched KV-cache generate)

Usage: cd /root/repo && python benchmarks/chip_session.py 2>&1 | tee /tmp/chip_session.log
       python benchmarks/chip_session.py <section>   # one section, in-process

CHIP_SESSION_SMOKE=1 shrinks every arm to CPU-rehearsable shapes so the
whole session's plumbing — including the subprocess fan-out — can be
validated without the chip (numbers are then meaningless; sections that
need the TPU print FAIL and move on). Add CHIP_SESSION_CPU=1 to actually
KEEP the rehearsal off the chip: the sitecustomize forces the TPU
platform in every subprocess regardless of JAX_PLATFORMS, so the pin has
to happen via jax.config inside the child (see _init_backend).
"""
import os
import sys

sys.path.insert(0, "/root/repo")
os.chdir("/root/repo")

SMOKE = bool(os.environ.get("CHIP_SESSION_SMOKE"))
# (seq, hidden, layers, mbs) of the full-step arms; long-context seqs;
# 1b-arm layer count
if SMOKE:
    STEP_SHAPE, LONG_SEQS, LAYERS_1B = (256, 256, 2, 2), (512, 1024), 3
    MBS_SWEEP = (2,)
else:
    STEP_SHAPE, LONG_SEQS, LAYERS_1B = (2048, 2048, 8, 4), (8192, 16384, 32768), 20
    MBS_SWEEP = (4, 8, 16)
SEQ, HIDDEN, LAYERS, MBS = STEP_SHAPE


# ------------------------------------------------------------ child plumbing
def _init_backend():
    """First device contact, fail-fast (shared with bench.py/dryrun).

    CHIP_SESSION_CPU=1 pins the section to the host CPU backend — the
    sitecustomize registers the TPU plugin and overrides JAX_PLATFORMS in
    every subprocess, so an env var alone cannot keep a rehearsal off the
    chip (round 4's "SMOKE" run measured the real TPU this way); only
    jax.config, applied before first device use, actually sticks. This is
    what lets the suite exercise the dispatcher without touching hardware."""
    import jax

    cpu_pin = bool(os.environ.get("CHIP_SESSION_CPU"))
    if cpu_pin:
        jax.config.update("jax_platforms", "cpu")
    # share bench.py's persistent executable cache: each section is a
    # fresh process, and without the cache every one re-pays its compiles
    # through the tunnel's remote-compile service. CPU rehearsals get a
    # separate cache — their XLA:CPU AOT entries carry different host
    # feature flags and would pollute capture day's cache with
    # machine-mismatch warnings
    cache = os.environ.get(
        "SCALING_TPU_BENCH_CACHE", "/tmp/scaling_tpu_bench_jaxcache"
    )
    jax.config.update(
        "jax_compilation_cache_dir", cache + "_cpu" if cpu_pin else cache
    )
    from scaling_tpu.devices import probe_devices

    devs, err = probe_devices(timeout_s=60)
    if devs is None:
        sys.exit(f"backend unreachable: {err}")
    return devs


def _build_step(mbs, layers=None, remat=False, kernel="flash_attention",
                norm=None):
    """Fresh model+optimizer+jitted step at the bench shape.

    Each section process builds its own copy from PRNGKey(0), so A/B arms
    in different processes still measure identical parameter values.
    """
    import jax
    import numpy as np

    import bench

    os.environ["BENCH_KERNEL"] = kernel
    if norm is None:
        os.environ.pop("BENCH_NORM", None)
    else:
        os.environ["BENCH_NORM"] = norm
    key = jax.random.PRNGKey(0)
    cfg, _, module, optimizer = bench.build(
        SEQ, mbs, HIDDEN, layers if layers is not None else LAYERS, remat=remat
    )
    step = module.build_train_step(optimizer, bench.loss_function, donate=False)
    params = module.shard_params(module.init_params(key))
    opt_state = optimizer.init_state(params)
    batch = module.shard_batch(
        bench.synth_batch(np.random.default_rng(0), mbs, SEQ,
                          cfg.transformer_architecture.vocab_size, 1),
        stacked=True,
    )

    def f(pp, ss):
        _, _, loss, _, _ = step(pp, ss, batch, key)
        return loss

    return cfg, f, params, opt_state


# ---------------------------------------------------------------- sections
def sec_peak():
    # the achievable-TFLOPs probe with amortized dispatch (bench.py fixed
    # the r1-r4 probe, which timed one 22 ms chain inside a ~90 ms tunnel
    # RTT and read ~50 TF against a step sustaining ~148); this section
    # gives the reading its own fault-isolated slot on capture day
    import jax

    import bench

    if SMOKE or jax.default_backend() != "tpu":
        # SMOKE's contract is plumbing-only (and without the CPU pin it
        # would burn ~850 TFLOP on the live chip); off-TPU the matmuls
        # take an hour on a CPU core and the reading would mean nothing
        print("0. peak probe: SKIP (smoke or non-tpu)", flush=True)
        return
    try:
        t = bench.measure_achievable_tflops()
        print(f"0. peak probe: {t:8.1f} TF (amortized dispatch)", flush=True)
    except Exception as e:
        print(f"0. peak probe: FAIL {type(e).__name__}: {e}", flush=True)


def sec_attn():
    from benchmarks import attn_bench

    q, k, v, seg = attn_bench.make_qkv()
    for name, fn in (("flash", attn_bench.flash), ("xla", attn_bench.xla_attn)):
        try:
            t = attn_bench.timeit(attn_bench.fwd_bwd(fn), q, k, v, seg)
            print(f"1. attn {name} f+b: {t:8.2f} ms", flush=True)
        except Exception as e:
            print(f"1. attn {name} f+b: FAIL {type(e).__name__}", flush=True)


def sec_blocks():
    from benchmarks import attn_bench

    q, k, v, seg = attn_bench.make_qkv()
    for bq, bkv in ((512, 512), (1024, 1024), (2048, 1024), (1024, 2048)):
        os.environ["SCALING_TPU_FLASH_BLOCK_Q"] = str(bq)
        os.environ["SCALING_TPU_FLASH_BLOCK_KV"] = str(bkv)
        try:
            t = attn_bench.timeit(attn_bench.fwd_bwd(attn_bench.flash),
                                  q, k, v, seg)
            print(f"2. flash blocks q={bq} kv={bkv}: {t:8.2f} ms", flush=True)
        except Exception as e:
            print(f"2. flash blocks q={bq} kv={bkv}: FAIL {type(e).__name__}",
                  flush=True)


def sec_step(label, kernel, norm=None):
    from benchmarks import attn_bench

    try:
        _, f, params, opt_state = _build_step(MBS, kernel=kernel, norm=norm)
        t = attn_bench.timeit(f, params, opt_state, iters=3)
        print(f"3/4. step {label}: {t:8.1f} ms", flush=True)
    except Exception as e:
        print(f"3/4. step {label}: FAIL {type(e).__name__}: {e}", flush=True)


def sec_trace():
    import jax

    outdir = "/tmp/bench_trace_tpu"
    _tracing = False
    try:
        _, f, params, opt_state = _build_step(MBS)
        loss = f(params, opt_state)  # compile OUTSIDE the trace window
        jax.block_until_ready(loss)
        jax.profiler.start_trace(outdir)
        _tracing = True
        for _ in range(2):
            loss = f(params, opt_state)
        jax.block_until_ready(loss)
        jax.profiler.stop_trace()
        _tracing = False
        print(
            f"5. trace written to {outdir}; analyze with "
            f"python benchmarks/analyze_trace.py {outdir}",
            flush=True,
        )
    except Exception as e:
        print(f"5. trace capture: FAIL {type(e).__name__}: {e}", flush=True)
    finally:
        if _tracing:
            # a failure mid-trace must not leave the profiler running
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def sec_mbs(mbs):
    # bigger per-step batch amortizes per-step overheads and widens MXU
    # tiles; memory-bound upward (fp32 masters dominate). Winner feeds
    # bench.py's BENCH_MBS. BENCH_NORM stays cleared so the sweep measures
    # the exact configuration bench.py runs.
    from benchmarks import attn_bench

    try:
        _, f, params, opt_state = _build_step(mbs)
        t = attn_bench.timeit(f, params, opt_state, iters=3)
        print(f"6. step mbs={mbs}: {t:8.1f} ms "
              f"({mbs * SEQ / t * 1000:.0f} tok/s)", flush=True)
    except Exception as e:
        print(f"6. step mbs={mbs}: FAIL {type(e).__name__}: {e}", flush=True)


def sec_long(s_long):
    # The no-O(s^2) story at wall-clock (VERDICT r3 #8): splash flash kernel
    # vs the ring's blockwise kernel (cp=1: one ring step IS the blockwise
    # inner loop with its chunked score tiles) vs XLA full attention,
    # fwd+bwd. XLA is EXPECTED to fail near 32k (the 16*s^2 score tensor
    # alone is ~34G) — that failure is the point of the comparison, and the
    # per-section process means it cannot poison the other arms.
    import jax
    import jax.numpy as jnp

    from benchmarks import attn_bench
    from scaling_tpu.ops.ring_attention import ring_attention
    from scaling_tpu.topology import Topology, TopologyConfig

    _topo1 = Topology(TopologyConfig.from_dict({
        "model_parallel_size": 1, "pipe_parallel_size": 1,
        "data_parallel_size": 1, "context_parallel_size": 1,
        "micro_batch_size": 1, "gradient_accumulation_steps": 1,
    }))

    def _ring_op(q, k, v, seg):
        return ring_attention(q, k, v, seg, _topo1.mesh, causal=True,
                              sm_scale=attn_bench.SCALE)

    kq = jax.random.PRNGKey(1)
    q_l = jax.random.normal(kq, (1, s_long, 16, 128), jnp.bfloat16)
    k_l = jax.random.normal(kq, (1, s_long, 4, 128), jnp.bfloat16)
    v_l = jax.random.normal(kq, (1, s_long, 4, 128), jnp.bfloat16)
    seg_l = jnp.zeros((1, s_long), jnp.int32)
    for name, op in (("splash", attn_bench.flash), ("ring-blockwise", _ring_op),
                     ("xla", attn_bench.xla_long)):
        try:
            t = attn_bench.timeit(attn_bench.fwd_bwd(op), q_l, k_l, v_l, seg_l,
                                  iters=3)
            print(f"7. seq={s_long} {name}: {t:8.1f} ms", flush=True)
        except Exception as e:
            print(f"7. seq={s_long} {name}: FAIL {type(e).__name__}", flush=True)


def sec_1b():
    # BASELINE #3's shape with every-layer remat at mbs 1 (bench.py's
    # BENCH_MODEL=1b arm). fp32 master+moments + bf16 params are 15.3G of
    # the 16G v5e, so an OOM here is a legitimate, informative outcome.
    from benchmarks import attn_bench

    try:
        _, f, params, opt_state = _build_step(1, layers=LAYERS_1B, remat=True)
        t = attn_bench.timeit(f, params, opt_state, iters=3)
        print(f"8. 1b step mbs=1: {t:8.1f} ms ({SEQ / t * 1000:.0f} tok/s)",
              flush=True)
    except Exception as e:
        print(f"8. 1b step: FAIL {type(e).__name__}: {e}", flush=True)


def sec_decode():
    # Batched KV-cache generate at the bench model size: decode is
    # HBM-bandwidth-bound (each new token re-reads the weights), so this
    # number tracks a different ceiling than the training MFU.
    try:
        import time as _time

        import jax
        import numpy as np

        import bench
        from scaling_tpu.models.transformer.inference import (
            TransformerInferenceModule,
        )

        os.environ["BENCH_KERNEL"] = "flash_attention"
        os.environ.pop("BENCH_NORM", None)  # measure the bench-default norm
        cfg_i, _, mod_i, _ = bench.build(SEQ, 1, HIDDEN, LAYERS)
        p_i = mod_i.shard_params(mod_i.init_params(jax.random.PRNGKey(0)))
        im = TransformerInferenceModule(cfg_i, mod_i, p_i)
        gen_b, prompt_len = 8, 128
        gen_tokens = 8 if SMOKE else 128
        prompt = np.random.default_rng(0).integers(
            1, 1000, size=(gen_b, prompt_len)
        )
        # warm-up at the MEASURED length: the fused decode loop's compile
        # is keyed on the step count (and prefill on cache length), so a
        # shorter warm-up would leave the real compile inside the window
        im.generate(prompt, max_tokens=gen_tokens)
        t0 = _time.perf_counter()
        im.generate(prompt, max_tokens=gen_tokens)
        dt = _time.perf_counter() - t0
        print(f"9. decode: {gen_b * gen_tokens / dt:8.0f} tok/s "
              f"(batch {gen_b}, {gen_tokens} new tokens, cached)", flush=True)
    except Exception as e:
        print(f"9. decode: FAIL {type(e).__name__}: {e}", flush=True)


def _sections():
    """(name, thunk, timeout_s) in run order. Timeouts bound a wedged
    tunnel per-section instead of letting one hang eat the session."""
    secs = [
        ("peak", sec_peak, 600),
        ("attn", sec_attn, 900),
        ("blocks", sec_blocks, 900),
        ("step-flash", lambda: sec_step("flash", "flash_attention"), 900),
        ("step-xla", lambda: sec_step("xla", "torch"), 900),
        ("step-fusednorm",
         lambda: sec_step("flash+fusednorm", "flash_attention", norm="fused"),
         900),
        ("trace", sec_trace, 900),
    ]
    secs += [(f"mbs-{m}", (lambda m=m: sec_mbs(m)), 900) for m in MBS_SWEEP]
    secs += [(f"long-{s}", (lambda s=s: sec_long(s)), 1200) for s in LONG_SEQS]
    secs += [("1b", sec_1b, 1500), ("decode", sec_decode, 900)]
    return secs


def run_section(name):
    for n, thunk, _ in _sections():
        if n == name:
            _init_backend()
            thunk()
            return
    sys.exit(f"unknown section {name!r}")


def main():
    """Dispatcher: one subprocess per section, output streamed to this
    stdout; crash/timeout/OOM in a section costs only that section."""
    import subprocess

    for name, _, timeout_s in _sections():
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), name],
                timeout=timeout_s,
            )
            if p.returncode != 0:
                print(f"-- section {name}: exited rc={p.returncode}",
                      flush=True)
        except subprocess.TimeoutExpired:
            print(f"-- section {name}: FAIL timeout after {timeout_s}s",
                  flush=True)
    print("session complete", flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1:
        run_section(sys.argv[1])
    else:
        # child processes re-read these; the parent never touches jax
        main()
