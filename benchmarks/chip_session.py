"""One serial on-chip measurement session (run when the chip is healthy).

Runs, in order, each timed with block_until_ready (median-of-3 via
attn_bench.timeit):
  1. attention micro-bench: flash vs XLA fwd+bwd at the bench shape
  2. flash block-size sweep
  3. full train step A/B: flash vs torch kernel (shared params)
  4. norm A/B: BENCH_NORM fused vs torch with the flash kernel
  5. trace capture for benchmarks/analyze_trace.py
  6. micro-batch sweep (4/8/16) after freeing earlier state; winner
     feeds bench.py's BENCH_MBS

Usage: cd /root/repo && python benchmarks/chip_session.py 2>&1 | tee /tmp/chip_session.log

CHIP_SESSION_SMOKE=1 shrinks every arm to CPU-rehearsable shapes so the
whole session's plumbing can be validated without the chip (numbers are
then meaningless; sections that need the TPU print FAIL and move on).
"""
import os
import sys

sys.path.insert(0, "/root/repo")
os.chdir("/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from scaling_tpu.devices import probe_devices

devs, err = probe_devices(timeout_s=60)
if devs is None:
    sys.exit(f"backend unreachable: {err}")
print(f"devices: {[d.device_kind for d in devs]}", flush=True)

import bench  # noqa: E402
from benchmarks import attn_bench  # noqa: E402

SMOKE = bool(os.environ.get("CHIP_SESSION_SMOKE"))
# (seq, hidden, layers, mbs) of the full-step arms; long-context seqs;
# 1b-arm layer count
if SMOKE:
    STEP_SHAPE, LONG_SEQS, LAYERS_1B = (256, 256, 2, 2), (512, 1024), 3
else:
    STEP_SHAPE, LONG_SEQS, LAYERS_1B = (2048, 2048, 8, 4), (8192, 16384, 32768), 20
SEQ, HIDDEN, LAYERS, MBS = STEP_SHAPE

# every section is fault-isolated: a broken arm (or a tunnel hiccup mid-
# session) must not take the remaining sections' measurements with it
# ---------------------------------------------------------- 1. micro bench
q, k, v, seg = attn_bench.make_qkv()
for name, fn in (("flash", attn_bench.flash), ("xla", attn_bench.xla_attn)):
    try:
        t = attn_bench.timeit(attn_bench.fwd_bwd(fn), q, k, v, seg)
        print(f"1. attn {name} f+b: {t:8.2f} ms", flush=True)
    except Exception as e:
        print(f"1. attn {name} f+b: FAIL {type(e).__name__}", flush=True)

# ------------------------------------------------------ 2. block-size sweep
for bq, bkv in ((512, 512), (1024, 1024), (2048, 1024), (1024, 2048)):
    os.environ["SCALING_TPU_FLASH_BLOCK_Q"] = str(bq)
    os.environ["SCALING_TPU_FLASH_BLOCK_KV"] = str(bkv)
    try:
        t = attn_bench.timeit(attn_bench.fwd_bwd(attn_bench.flash), q, k, v, seg)
        print(f"2. flash blocks q={bq} kv={bkv}: {t:8.2f} ms", flush=True)
    except Exception as e:
        print(f"2. flash blocks q={bq} kv={bkv}: FAIL {type(e).__name__}", flush=True)
os.environ.pop("SCALING_TPU_FLASH_BLOCK_Q", None)
os.environ.pop("SCALING_TPU_FLASH_BLOCK_KV", None)


# ------------------------------------------------- 3./4. full-step A/B
def build_step(kernel, norm="torch"):
    os.environ["BENCH_KERNEL"] = kernel
    os.environ["BENCH_NORM"] = norm
    config, topology, module, optimizer = bench.build(SEQ, MBS, HIDDEN, LAYERS)
    step = module.build_train_step(optimizer, bench.loss_function, donate=False)
    return config, module, optimizer, step


key = jax.random.PRNGKey(0)
step_ab_ready = False
try:
    cfg, module, optimizer, step_f = build_step("flash_attention")
    arch = cfg.transformer_architecture
    params = module.shard_params(module.init_params(key))
    opt_state = optimizer.init_state(params)
    rng = np.random.default_rng(0)
    batch = module.shard_batch(
        bench.synth_batch(rng, MBS, SEQ, arch.vocab_size, 1), stacked=True
    )
    _, _, _, step_x = build_step("torch")
    _, _, _, step_fn = build_step("flash_attention", norm="fused")
    step_ab_ready = True
except Exception as e:
    print(f"3/4. setup: FAIL {type(e).__name__}: {e}", flush=True)


def run_step(stp):
    def f(params, opt_state):
        _, _, loss, _, _ = stp(params, opt_state, batch, key)
        return loss

    return f


if step_ab_ready:
    for name, stp in (("flash", step_f), ("xla", step_x),
                      ("flash+fusednorm", step_fn)):
        try:
            t = attn_bench.timeit(run_step(stp), params, opt_state, iters=3)
            print(f"3/4. step {name}: {t:8.1f} ms", flush=True)
        except Exception as e:
            print(f"3/4. step {name}: FAIL {type(e).__name__}: {e}", flush=True)

# --------------------------------------------------------- 5. trace capture
os.environ["BENCH_KERNEL"] = "flash_attention"
os.environ.pop("BENCH_NORM", None)
outdir = "/tmp/bench_trace_tpu"
_tracing = False
try:
    if not step_ab_ready:
        raise RuntimeError("step A/B setup failed; nothing to trace")
    jax.profiler.start_trace(outdir)
    _tracing = True
    for i in range(2):
        loss = run_step(step_f)(params, opt_state)
    jax.block_until_ready(loss)
    jax.profiler.stop_trace()
    _tracing = False
    print(
        f"5. trace written to {outdir}; analyze with "
        f"python benchmarks/analyze_trace.py {outdir}",
        flush=True,
    )
except Exception as e:
    print(f"5. trace capture: FAIL {type(e).__name__}: {e}", flush=True)
finally:
    if _tracing:
        # a failure mid-trace must not leave the profiler running under
        # sections 6-8 (distorted timings, unbounded trace buffers)
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass

# ------------------------------------------- 6. micro-batch size sweep
# bigger per-step batch amortizes per-step overheads and widens MXU tiles;
# memory-bound upward (fp32 masters dominate). Winner feeds bench.py's
# BENCH_MBS. Runs LAST so the earlier sections' ~9G of model/optimizer
# state can be freed first (a duplicate resident model would OOM the
# larger arms on a 16G v5e), and with BENCH_NORM cleared so the sweep
# measures the exact configuration bench.py runs.
for _n in ("params", "opt_state", "batch", "step_f", "step_x", "step_fn"):
    globals().pop(_n, None)
os.environ["BENCH_KERNEL"] = "flash_attention"
os.environ.pop("BENCH_NORM", None)
for mbs in ((2,) if SMOKE else (4, 8, 16)):
    try:
        cfg_m, _, mod_m, opt_m = bench.build(SEQ, mbs, HIDDEN, LAYERS)
        step_m = mod_m.build_train_step(opt_m, bench.loss_function, donate=False)
        p_m = mod_m.shard_params(mod_m.init_params(key))
        s_m = opt_m.init_state(p_m)
        b_m = mod_m.shard_batch(
            bench.synth_batch(np.random.default_rng(0), mbs, SEQ,
                              cfg_m.transformer_architecture.vocab_size, 1),
            stacked=True,
        )

        def f_m(pp, ss, _step=step_m, _b=b_m):
            _, _, loss, _, _ = _step(pp, ss, _b, key)
            return loss

        t = attn_bench.timeit(f_m, p_m, s_m, iters=3)
        print(f"6. step mbs={mbs}: {t:8.1f} ms "
              f"({mbs * SEQ / t * 1000:.0f} tok/s)", flush=True)
        del p_m, s_m, b_m, step_m
    except Exception as e:
        print(f"6. step mbs={mbs}: FAIL {type(e).__name__}: {e}", flush=True)

# ------------------------------- 7. long-context attention sweep (one chip)
# The no-O(s^2) story at wall-clock (VERDICT r3 #8): splash flash kernel vs
# the ring's blockwise kernel (cp=1: one ring step IS the blockwise inner
# loop with its chunked score tiles) vs XLA full attention, fwd+bwd at
# seq 8k/16k/32k. XLA is EXPECTED to fail near 32k (the 16*s^2 score tensor
# alone is ~34G) — that failure is the point of the comparison.
from scaling_tpu.ops.ring_attention import ring_attention
from scaling_tpu.topology import Topology, TopologyConfig

_topo1 = Topology(TopologyConfig.from_dict({
    "model_parallel_size": 1, "pipe_parallel_size": 1,
    "data_parallel_size": 1, "context_parallel_size": 1,
    "micro_batch_size": 1, "gradient_accumulation_steps": 1,
}))


def _ring_op(q, k, v, seg):
    return ring_attention(q, k, v, seg, _topo1.mesh, causal=True,
                          sm_scale=attn_bench.SCALE)


for s_long in LONG_SEQS:
    kq = jax.random.PRNGKey(1)
    q_l = jax.random.normal(kq, (1, s_long, 16, 128), jnp.bfloat16)
    k_l = jax.random.normal(kq, (1, s_long, 4, 128), jnp.bfloat16)
    v_l = jax.random.normal(kq, (1, s_long, 4, 128), jnp.bfloat16)
    seg_l = jnp.zeros((1, s_long), jnp.int32)
    for name, op in (("splash", attn_bench.flash), ("ring-blockwise", _ring_op),
                     ("xla", attn_bench.xla_long)):
        try:
            t = attn_bench.timeit(attn_bench.fwd_bwd(op), q_l, k_l, v_l, seg_l,
                                  iters=3)
            print(f"7. seq={s_long} {name}: {t:8.1f} ms", flush=True)
        except Exception as e:
            print(f"7. seq={s_long} {name}: FAIL {type(e).__name__}", flush=True)
    del q_l, k_l, v_l, seg_l

# ----------------------------------------- 8. 1B single-chip attempt
# BASELINE #3's shape with every-layer remat at mbs 1 (bench.py's
# BENCH_MODEL=1b arm). fp32 master+moments + bf16 params are 15.3G of the
# 16G v5e, so an OOM here is a legitimate, informative outcome — record it.
os.environ["BENCH_KERNEL"] = "flash_attention"
try:
    cfg_b, _, mod_b, opt_b = bench.build(SEQ, 1, HIDDEN, LAYERS_1B, remat=True)
    step_b = mod_b.build_train_step(opt_b, bench.loss_function, donate=False)
    p_b = mod_b.shard_params(mod_b.init_params(key))
    s_b = opt_b.init_state(p_b)
    b_b = mod_b.shard_batch(
        bench.synth_batch(np.random.default_rng(0), 1, SEQ,
                          cfg_b.transformer_architecture.vocab_size, 1),
        stacked=True,
    )

    def f_b(pp, ss):
        _, _, loss, _, _ = step_b(pp, ss, b_b, key)
        return loss

    t = attn_bench.timeit(f_b, p_b, s_b, iters=3)
    print(f"8. 1b step mbs=1: {t:8.1f} ms ({SEQ / t * 1000:.0f} tok/s)",
          flush=True)
    del p_b, s_b, b_b, step_b
except Exception as e:
    print(f"8. 1b step: FAIL {type(e).__name__}: {e}", flush=True)

# ------------------------------------------- 9. decode throughput
# Batched KV-cache generate at the bench model size: decode is
# HBM-bandwidth-bound (each new token re-reads the weights), so this
# number tracks a different ceiling than the training MFU.
try:
    import time as _time

    from scaling_tpu.models.transformer.inference import (
        TransformerInferenceModule,
    )

    cfg_i, _, mod_i, _ = bench.build(SEQ, 1, HIDDEN, LAYERS)
    p_i = mod_i.shard_params(mod_i.init_params(key))
    im = TransformerInferenceModule(cfg_i, mod_i, p_i)
    gen_b, prompt_len = 8, 128
    gen_tokens = 8 if SMOKE else 128
    prompt = np.random.default_rng(0).integers(
        1, 1000, size=(gen_b, prompt_len)
    )
    im.generate(prompt, max_tokens=2)  # compile prefill + decode
    t0 = _time.perf_counter()
    im.generate(prompt, max_tokens=gen_tokens)
    dt = _time.perf_counter() - t0
    print(f"9. decode: {gen_b * gen_tokens / dt:8.0f} tok/s "
          f"(batch {gen_b}, {gen_tokens} new tokens, cached)", flush=True)
    del p_i, im
except Exception as e:
    print(f"9. decode: FAIL {type(e).__name__}: {e}", flush=True)
