"""AOT-compile BASELINE #4's 7B layout and print its cost/memory pins.

BASELINE.md #4: "7B transformer, TP=4 PP=2 DP=8 + ZeRO-1 + activation
checkpointing; >=45% MFU on v5p-128". The hardware doesn't exist in this
environment, but the compiled program does: 64 virtual CPU devices, the
real jitted train step lowered from ShapeDtypeStructs (no parameter
materialization — the 7B optimizer state alone would be ~84G), and XLA's
cost analysis + buffer assignment give per-partition FLOPs, collective
bytes and per-chip memory. Prints one JSON line; the suite re-runs the
same pin at a scaled-down layout (tests/transformer/test_hlo_cost_pins).

Usage: python benchmarks/compile_pin_7b.py          # ~7B, 64 devices
       python benchmarks/compile_pin_7b.py --small  # CI-sized proxy
       python benchmarks/compile_pin_7b.py --peft   # BASELINE #5: 7B+LoRA, TP=4 x DP=16
"""

import json
import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=64"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

V5P_HBM = 95e9  # bytes per chip
V5P_PEAK_TFLOPS = 459  # bf16


def build_abstract(small: bool, peft: bool = False):
    from scaling_tpu.models.transformer import TransformerConfig
    from scaling_tpu.models.transformer.model import (
        init_model,
        init_optimizer,
        loss_function,
    )
    from scaling_tpu.nn.param import ParamMeta
    from scaling_tpu.topology import Topology

    if small:
        hidden, layers, heads, kv, vocab, seq, mbs, gas = 256, 4, 4, 2, 2048, 256, 1, 2
    else:
        # ~7B: 12.6·h²·L body + 2·V·h edges at h=4096, L=32
        hidden, layers, heads, kv, vocab, seq, mbs, gas = (
            4096, 32, 32, 8, 32768, 2048, 1, 8,
        )
    if peft:
        # BASELINE #5: PEFT finetune layout — TP=4 x DP=16, no pipeline
        topo_d = {
            "model_parallel_size": 4, "pipe_parallel_size": 1,
            "data_parallel_size": 16, "micro_batch_size": mbs,
            "gradient_accumulation_steps": gas,
            "activation_checkpointing_type": "every_layer",
        }
    else:
        # BASELINE #4: pretraining layout — TP=4 x PP=2 x DP=8
        topo_d = {
            "model_parallel_size": 4, "pipe_parallel_size": 2,
            "data_parallel_size": 8, "micro_batch_size": mbs,
            "gradient_accumulation_steps": gas,
            "activation_checkpointing_type": "every_layer",
        }
    d = {
        "topology": topo_d,
        "transformer_architecture": {
            "vocab_size": vocab, "hidden_size": hidden, "num_layers": layers,
            "num_attention_heads": heads, "attention_num_kv_heads": kv,
            "sequence_length": seq, "precision": "bfloat16",
            "mlp_type": "swiglu", "mlp_factor": 2.75, "norm_type": "rms",
            "relative_position_embedding_type": "rotary", "causal": True,
            "masked_softmax": {"kernel": "torch"},
            "weight_tying": False, "attention_qkv_in_one": False,
            "dropout_embedding": 0.0, "dropout_attention_probs": 0.0,
            "dropout_after_attention": 0.0, "dropout_after_mlp": 0.0,
        },
        "optimizer": {"gradient_clipping": 1.0, "zero": True,
                      "loss_scaler": {"enable": False}},
        "learning_rate_scheduler": {"learning_rate": 3e-4,
                                    "learning_rate_warmup_steps": 10,
                                    "learning_rate_decay_iters": 1000},
        "trainer": {"train_iterations": 10, "seed": 0},
        "data": {}, "logger": {"log_dir": None},
    }
    if peft:
        d["transformer_architecture"]["lora_config"] = {
            "name": "lo", "rank": 16, "alpha": 32,
        }
        d["training"] = {"finetune": True, "finetunable_parameters": []}
    config = TransformerConfig.from_dict(d)
    topology = Topology(config.topology)
    module = init_model(config, topology)
    optimizer = init_optimizer(config, module, topology)
    mesh = topology.mesh

    shapes = jax.eval_shape(module.init_params, jax.random.PRNGKey(0))
    metas = module.param_metas()
    abstract_params = jax.tree.map(
        lambda s, m: jax.ShapeDtypeStruct(
            s.shape, s.dtype,
            sharding=NamedSharding(mesh, P(*m.partition_spec)),
        ),
        shapes, metas, is_leaf=lambda x: isinstance(x, ParamMeta),
    )
    abstract_opt = optimizer.abstract_state(abstract_params)

    arch, topo = config.transformer_architecture, config.topology
    b = topo.micro_batch_size * topo.data_parallel_size

    def bspec(shape, dt):
        return jax.ShapeDtypeStruct(
            shape, dt,
            sharding=NamedSharding(mesh, P(None, "data", "context")),
        )

    batch = {
        "token_ids": bspec((gas, b, seq), jnp.int32),
        "target_token_ids": bspec((gas, b, seq), jnp.int32),
        "position_ids": bspec((gas, b, seq), jnp.int32),
        "segment_ids": bspec((gas, b, seq), jnp.int32),
        "loss_weights": bspec((gas, b, seq), jnp.float32),
    }
    step = module.build_train_step(optimizer, loss_function)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    return config, step, (abstract_params, abstract_opt, batch, key)


def main():
    small = "--small" in sys.argv
    peft = "--peft" in sys.argv
    t0 = time.time()
    config, step, args = build_abstract(small, peft)
    lowered = step.lower(*args)
    compiled = lowered.compile()
    compile_s = time.time() - t0

    # NOTE: compiled.cost_analysis() counts each scan/while BODY once, not
    # x trip-count, so compiled-FLOP totals are meaningless for this
    # gas-scan + tick-scan program (measured 0.028x analytic at the 7B).
    # Buffer assignment, in contrast, is exact — loop buffers are
    # allocated once — so the per-chip memory numbers below are real.
    ma = compiled.memory_analysis()
    per_chip_bytes = (
        ma.argument_size_in_bytes + ma.temp_size_in_bytes
        + ma.output_size_in_bytes - ma.alias_size_in_bytes
    )

    from tests.transformer.test_hlo_cost_pins import analytic_step_flops

    from scaling_tpu.analysis.hlo_audit import collective_bytes

    from scaling_tpu.models.transformer.utils.get_tflops import (
        get_model_parameter_count,
    )

    arch, topo = config.transformer_architecture, config.topology
    n_params = get_model_parameter_count(
        arch.hidden_size, arch.num_layers, arch.vocab_size, arch.mlp_factor,
        glu=True,
    )
    n_dev = topo.world_size
    # the MFU gate in analytic terms: 6·N·T + attention FLOPs (the shared
    # helper the suite pins against) split over the chips at the v5p peak
    # is the device-time floor; every_layer remat re-runs the forward once
    # more (~4/3 of fwd work) on top of this
    step_flops_analytic = analytic_step_flops(config)
    floor_ms = step_flops_analytic / n_dev / (V5P_PEAK_TFLOPS * 1e12) * 1e3

    # pipeline economics for this layout (PERF.md "Spatial pipeline vs a
    # 1F1B executor"): n_micro/(n_micro+pp-1) is BOTH the spatial
    # pipeline's useful-FLOP fraction (fill/drain garbage ticks) and a
    # non-interleaved 1F1B's bubble fraction — the same useful-token MFU
    # ceiling either way. The only extra wall-clock the spatial form can
    # pay is the chunked tick-remat's body forward, reported here along
    # with whether the carry budget actually engages it at this layout.
    from scaling_tpu.parallel.pipeline import _tick_carries_exceed_budget
    from scaling_tpu.topology.config import ActivationCheckpointingType

    pp = topo.pipe_parallel_size
    n_micro = topo.gradient_accumulation_steps
    n_ticks = n_micro + pp - 1
    act_bytes = 2 if arch.precision.value == "bfloat16" else 4
    # the SAME gate the runtime evaluates (pipeline.py), on the state's
    # global abstract shape — a re-implementation here drifted once
    # (missing dp factor + the remat/n_ticks>=4 conditions) and published
    # a pin that disagreed with the compiled program
    state = {
        "activations": jax.ShapeDtypeStruct(
            (pp, topo.micro_batch_size * topo.data_parallel_size,
             arch.sequence_length, arch.hidden_size),
            jnp.bfloat16 if act_bytes == 2 else jnp.float32,
        )
    }
    n_state_shards = pp * topo.data_parallel_size * topo.context_parallel_size
    # the reported MB come from the same leaf-bytes/shards expression the
    # gate divides, so artifact numbers can never disagree with its decision
    carry_mb = sum(
        int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(state)
    ) / n_state_shards / 2**20
    remat_on = (
        topo.activation_checkpointing_type
        != ActivationCheckpointingType.DISABLED
    )
    pipeline_pin = {
        "pp": pp,
        "n_micro": n_micro,
        "ticks": n_ticks,
        "useful_token_mfu_ceiling": round(n_micro / n_ticks, 4),
        "tick_carry_mb_per_device": round(carry_mb, 1),
        "scan_carries_mb_per_device": round(carry_mb * n_ticks, 1),
        "chunked_remat_active": bool(
            remat_on and n_ticks >= 4 and _tick_carries_exceed_budget(
                state, n_ticks, n_state_shards
            )
        ),
    }

    print(json.dumps({
        "layout": (
            "tp4.dp16+lora16+zero1+every_layer_remat" if peft
            else "tp4.pp2.dp8+zero1+every_layer_remat"
        ),
        "model": "small-proxy" if small else "7b",
        "params": int(n_params),
        "devices": n_dev,
        "compile_s": round(compile_s, 1),
        "per_chip_gb": round(per_chip_bytes / 1e9, 2),
        "per_chip_args_gb": round(ma.argument_size_in_bytes / 1e9, 2),
        "per_chip_temp_gb": round(ma.temp_size_in_bytes / 1e9, 2),
        "fits_v5p_95g": bool(per_chip_bytes < V5P_HBM),
        # per-partition bytes per collective, PER SCAN ITERATION (HLO text
        # shows loop bodies once); dominated by TP activation reductions
        "collective_bytes_per_iter": collective_bytes(compiled),
        "analytic_step_flops": step_flops_analytic,
        "device_time_floor_ms_at_v5p_peak": round(floor_ms, 1),
        "step_budget_ms_for_45pct_mfu": round(floor_ms / 0.45, 1),
        "pipeline": pipeline_pin,
    }))


if __name__ == "__main__":
    main()
