#!/bin/bash
# Probe the TPU tunnel every 4 minutes; on the FIRST healthy probe run the
# entire capture sequence unattended (a short window must still yield the
# round's perf evidence), logging everything under .scratch/capture/.
cd /root/repo
mkdir -p .scratch/capture
for i in $(seq 1 200); do
  ts=$(date +%H:%M:%S)
  out=$(bash benchmarks/probe_tunnel.sh)
  echo "$ts $out" >> .scratch/tunnel_status.log
  if [[ "$out" == OK* ]]; then
    echo "TUNNEL ALIVE at $ts (iteration $i) — starting capture"
    # 1. the headline artifact first: a plain bench pass exactly as the
    #    driver runs it (BENCH_WAIT_S default retries cover flaps)
    echo "=== bench 0.5b $(date) ===" > .scratch/capture/bench_05b.log
    timeout 3600 python bench.py >> .scratch/capture/bench_05b.log 2>&1
    echo "bench 0.5b rc=$?" >> .scratch/capture/bench_05b.log
    # 2. the full serial measurement session (A/Bs, sweeps, trace)
    echo "=== chip_session $(date) ===" > .scratch/capture/chip_session.log
    # chip_session bounds each section's subprocess itself; the backstop is
    # derived from the session's own per-section budgets so adding or
    # growing a section can't silently outlive it
    session_budget=$(python - <<'PYB'
from benchmarks import chip_session
print(sum(t for _, _, t in chip_session._sections()) + 600)
PYB
)
    timeout "${session_budget:-14400}" python benchmarks/chip_session.py >> .scratch/capture/chip_session.log 2>&1
    echo "chip_session rc=$?" >> .scratch/capture/chip_session.log
    # 3. trace attribution
    timeout 600 python benchmarks/analyze_trace.py /tmp/bench_trace_tpu \
      > .scratch/capture/trace_analysis.log 2>&1
    # 4. the 1B single-chip attempt (expected tight on HBM; record it)
    echo "=== bench 1b $(date) ===" > .scratch/capture/bench_1b.log
    BENCH_MODEL=1b BENCH_WAIT_S=600 timeout 3600 python bench.py \
      >> .scratch/capture/bench_1b.log 2>&1
    echo "bench 1b rc=$?" >> .scratch/capture/bench_1b.log
    # 5. tuned final pass: pick the fastest mbs and the norm winner out of
    #    the session log, then run bench once more with those knobs
    python - <<'PYEOF' > .scratch/capture/winners.env 2>.scratch/capture/winners.err
import re
txt = open(".scratch/capture/chip_session.log").read()
best_mbs, best_t = None, None
for m in re.finditer(r"6\. step mbs=(\d+):\s+([0-9.]+) ms", txt):
    mbs, t = int(m.group(1)), float(m.group(2))
    tok_s = mbs / t
    if best_t is None or tok_s > best_t:
        best_mbs, best_t = mbs, tok_s
steps = dict(re.findall(r"3/4\. step ([a-z+]+):\s+([0-9.]+) ms", txt))
norm = ""
if "flash" in steps and "flash+fusednorm" in steps:
    if float(steps["flash+fusednorm"]) < float(steps["flash"]):
        norm = "fused"
print(f"BENCH_MBS={best_mbs or ''}")
print(f"BENCH_NORM={norm}")
PYEOF
    set -a; source .scratch/capture/winners.env 2>/dev/null; set +a
    [ -z "$BENCH_MBS" ] && unset BENCH_MBS
    [ -z "$BENCH_NORM" ] && unset BENCH_NORM
    echo "=== bench tuned (BENCH_MBS=$BENCH_MBS BENCH_NORM=$BENCH_NORM) $(date) ===" \
      > .scratch/capture/bench_tuned.log
    BENCH_WAIT_S=600 timeout 3600 python bench.py \
      >> .scratch/capture/bench_tuned.log 2>&1
    echo "bench tuned rc=$?" >> .scratch/capture/bench_tuned.log
    echo "CAPTURE COMPLETE at $(date)"
    exit 0
  fi
  sleep 240
done
echo "TUNNEL never came up"
exit 1
