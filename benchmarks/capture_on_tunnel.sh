#!/bin/bash
# Probe the TPU tunnel every 4 minutes; on the FIRST healthy probe run the
# capture sequence unattended (a short window must still yield the round's
# perf evidence), logging everything under .scratch/capture/.
#
# Round-5 priority order (VERDICT r4): the headline bench refreshes
# LAST_GOOD + the amortized peak probe (#1/#2), then the unmeasured
# capabilities — LoRA finetune tok/s (#6), fused-loop decode (#3), trace
# attribution + long-context sweep + mbs confirmation (#4) — then the 1b
# arm and the remaining A/B sections. Every section is its own probed
# subprocess, so a mid-list tunnel death costs only what hasn't run yet.
cd /root/repo
CAP=.scratch/capture
mkdir -p $CAP

run_bench() {  # run_bench <label> [env VAR=val ...]
  local label=$1; shift
  echo "=== bench $label $(date) ===" > $CAP/bench_$label.log
  if ! bash benchmarks/probe_tunnel.sh > /dev/null; then
    # skip in ~75s instead of burning the bench's whole retry window —
    # a mid-list tunnel death must not starve the later sections
    echo "bench $label skipped: tunnel dead" >> $CAP/bench_$label.log
    return
  fi
  env "$@" BENCH_WAIT_S=600 timeout 1800 python bench.py \
    >> $CAP/bench_$label.log 2>&1
  echo "bench $label rc=$?" >> $CAP/bench_$label.log
}

run_section() {  # one chip_session section, probed first
  local sec=$1
  if bash benchmarks/probe_tunnel.sh > /dev/null; then
    echo "-- $(date +%H:%M:%S) running section $sec" >> $CAP/chip_session.log
    timeout 1800 python benchmarks/chip_session.py "$sec" \
      >> $CAP/chip_session.log 2>&1 \
      || echo "-- section $sec: exited rc=$?" >> $CAP/chip_session.log
  else
    echo "-- $(date +%H:%M:%S) tunnel dead; skipping $sec" >> $CAP/chip_session.log
  fi
}

for i in $(seq 1 200); do
  ts=$(date +%H:%M:%S)
  out=$(bash benchmarks/probe_tunnel.sh)
  echo "$ts $out" >> .scratch/tunnel_status.log
  if [[ "$out" == OK* ]]; then
    echo "TUNNEL ALIVE at $ts (iteration $i) — starting r5 capture"
    # clear previous sessions' logs: the artifacts pass below must only
    # ever see arms run in THIS capture (a leftover round-4 bench_tuned
    # log would otherwise be stamped as fresh round-5 evidence)
    rm -f $CAP/bench_*.log $CAP/summary.md $CAP/summary.err
    : > $CAP/chip_session.log
    # 1. headline artifact exactly as the driver runs it (also refreshes
    #    benchmarks/artifacts/LAST_GOOD.json and runs the amortized-v2
    #    peak probe -> mfu_vs_measured_peak should finally read <= 1)
    run_bench 05b
    # 2. BASELINE #5 on-chip: LoRA finetune step throughput
    run_bench 05b_lora BENCH_MODEL=0.5b-lora
    # 3. fused single-dispatch decode (replaces the RTT-bound 12 tok/s)
    run_section decode
    # 4. trace attribution + long-context wall-clock + mbs confirmation
    run_section trace
    timeout 600 python benchmarks/analyze_trace.py /tmp/bench_trace_tpu \
      > $CAP/trace_analysis.log 2>&1
    for sec in long-8192 long-16384 long-32768 mbs-4 mbs-8 mbs-16; do
      run_section $sec
    done
    # 5. the 1B single-chip arm (BASELINE #3 shape; tight on HBM)
    run_bench 1b BENCH_MODEL=1b
    # 6. remaining A/B sections (peak probe slot, attention kernels,
    #    block sweep, step A/Bs, 1b step probe)
    for sec in peak attn blocks step-flash step-xla step-fusednorm 1b; do
      run_section $sec
    done
    # turn fresh bench rows into committed artifacts + a summary table,
    # so an unattended capture still lands round evidence
    python benchmarks/summarize_capture.py $CAP --artifacts r05 \
      > $CAP/summary.md 2>> $CAP/summary.err || true
    echo "CAPTURE COMPLETE at $(date)"
    exit 0
  fi
  sleep 240
done
echo "TUNNEL never came up"
exit 1
