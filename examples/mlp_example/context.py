"""(reference: examples/mlp_example/context.py)"""

from scaling_tpu.context import BaseContext


class MLPContext(BaseContext):
    pass
