"""Launch the MLP example: ``python -m examples.mlp_example.run [config.yml]``"""

import sys

from .config import MLPConfig
from .train import main

if __name__ == "__main__":
    if len(sys.argv) > 1:
        config = MLPConfig.from_yaml(sys.argv[1])
    else:
        config = MLPConfig()
    main(config)
