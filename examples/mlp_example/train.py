"""MLP example training entry (reference: examples/mlp_example/train.py)."""

from __future__ import annotations

from scaling_tpu.logging import logger
from scaling_tpu.topology import Topology
from scaling_tpu.trainer import BaseTrainer

from .config import MLPConfig
from .context import MLPContext
from .data import MNISTDataset
from .model import init_model, init_optimizer, loss_function


def batch_to_model_input(batch):
    return {"inputs": batch.inputs, "targets": batch.targets}


def main(config: MLPConfig) -> BaseTrainer:
    topology = Topology(config.topology)
    logger.configure(config.logger, name="mlp_example")
    logger.log_config(config)
    context = MLPContext(config=config, topology=topology)
    module = init_model(config, topology)
    optimizer = init_optimizer(config, module, topology)
    dataset = MNISTDataset(train=True, seed=config.trainer.seed)
    trainer = BaseTrainer(
        config=config.trainer,
        context=context,
        parallel_module=module,
        optimizer=optimizer,
        loss_function=loss_function,
        dataset=dataset,
        dataset_evaluation=MNISTDataset(train=False, seed=config.trainer.seed),
        batch_to_model_input=batch_to_model_input,
    )
    trainer.initialize(load_checkpoint=config.trainer.load_dir is not None)
    trainer.run_training()
    return trainer
