"""MNIST (or synthetic fallback) dataset for the MLP example.

(reference: examples/mlp_example/data.py). The reference downloads MNIST via
torchvision; in offline environments a deterministic synthetic "digits"
classification set is generated instead — structured so losses fall under
training (class-dependent gaussian blobs over 784 dims).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from scaling_tpu.data import BaseDataset


class MNISTDatasetBatch:
    def __init__(self, inputs=None, targets=None):
        self.inputs = inputs
        self.targets = targets

    def only_inputs(self):
        return MNISTDatasetBatch(inputs=self.inputs)

    def only_targets(self):
        return MNISTDatasetBatch(targets=self.targets)


def _load_mnist(root: Path, train: bool):
    try:  # pragma: no cover - requires local MNIST
        import torchvision
        from torchvision import transforms

        t = transforms.Compose(
            [transforms.ToTensor(), transforms.Normalize((0.5,), (0.5,))]
        )
        ds = torchvision.datasets.MNIST(
            root=root, train=train, transform=t, download=False
        )
        xs = np.stack([np.asarray(ds[i][0]).reshape(-1) for i in range(len(ds))])
        ys = np.asarray([ds[i][1] for i in range(len(ds))])
        return xs.astype(np.float32), ys.astype(np.int32)
    except Exception:
        return None


def _synthetic_digits(n: int, seed: int):
    # class centers are a fixed property of the "dataset", shared between
    # train and eval splits; only the sample noise differs by seed
    centers = np.random.RandomState(1234).randn(10, 784).astype(np.float32) * 1.5
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 10, size=n).astype(np.int32)
    xs = centers[ys] + rng.randn(n, 784).astype(np.float32)
    return xs, ys


class MNISTDataset(BaseDataset):
    def __init__(self, root: Path = Path("./.data"), train: bool = True, seed: int = 42):
        loaded = _load_mnist(root, train)
        if loaded is None:
            loaded = _synthetic_digits(60000 if train else 10000, seed if train else seed + 1)
        self.xs, self.ys = loaded
        self._order = np.arange(len(self.ys))
        super().__init__(seed=seed)

    def ident(self) -> str:
        return "MNIST"

    def __len__(self) -> int:
        return len(self.ys)

    def __getitem__(self, index: int):
        i = int(self._order[index])
        return (self.xs[i], self.ys[i])

    def set_seed(self, seed: int, shuffle: bool = True) -> None:
        self.seed = seed
        self._order = np.arange(len(getattr(self, "ys", [])))
        if shuffle and len(self._order):
            np.random.RandomState(seed).shuffle(self._order)

    def collate(self, batch: list) -> MNISTDatasetBatch:
        return MNISTDatasetBatch(
            inputs=np.stack([b[0] for b in batch]),
            targets=np.stack([b[1] for b in batch]),
        )
