"""MLP example config tree (reference: examples/mlp_example/config.py)."""

from __future__ import annotations

from pydantic import Field

from scaling_tpu.config import BaseConfig
from scaling_tpu.logging import LoggerConfig
from scaling_tpu.optimizer import (
    LearningRateSchedulerConfig,
    OptimizerConfig,
)
from scaling_tpu.topology import TopologyConfig
from scaling_tpu.trainer import TrainerConfig


class TrainingConfig(BaseConfig):
    weight_decay: float = Field(0.0001, description="")


class MLPArchitectureConfig(BaseConfig):
    n_hidden_layers: int = Field(3, description="number of hidden layers")
    hidden_dim: int = Field(128, description="hidden dimension")
    input_dim: int = Field(784, description="input dimension (28*28)")
    num_classes: int = Field(10, description="")


class RunnerConfig(BaseConfig):
    """Kept for config-file parity; single-controller launch ignores it."""

    runner_type: str = Field("pdsh", description="Type of the runner to be invoked.")
    hostsfile: str | None = Field(None, description="")
    hosts: list | None = Field(None, description="")
    master_port: int = Field(29500, description="")
    master_addr: str | None = Field(None, description="")
    script: str | None = Field(None, description="")
    default_gpu_count: int = Field(8, description="")
    docker_config: dict | None = Field(None, description="")
    use_determined: bool = Field(False, description="")


class MLPConfig(BaseConfig):
    runner: RunnerConfig = Field(RunnerConfig(), description="")
    topology: TopologyConfig = Field(
        TopologyConfig(
            model_parallel_size=1,
            pipe_parallel_size=1,
            data_parallel_size=1,
            micro_batch_size=256,
            gradient_accumulation_steps=1,
        ),
        description="",
    )
    optimizer: OptimizerConfig = Field(OptimizerConfig(), description="")
    learning_rate_scheduler: LearningRateSchedulerConfig = Field(
        LearningRateSchedulerConfig(), description=""
    )
    training: TrainingConfig = Field(TrainingConfig(), description="")
    trainer: TrainerConfig = Field(TrainerConfig(), description="")
    logger: LoggerConfig = Field(LoggerConfig(), description="")
    architecture: MLPArchitectureConfig = Field(MLPArchitectureConfig(), description="")
