"""MLP model assembled from framework layers.

(reference: examples/mlp_example/model.py) — column-parallel input layer,
row-parallel hidden layers, cross-entropy loss; the batch travels as a dict
pytree through the layer stack.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from scaling_tpu.nn import (
    BaseLayer,
    ColumnParallelLinear,
    ForwardContext,
    LayerSpec,
    RowParallelLinear,
    tree_prefix,
)
from scaling_tpu.optimizer import Optimizer, OptimizerParamGroup
from scaling_tpu.parallel.parallel_module import ParallelModule

from .config import MLPConfig


class InputLayer(BaseLayer):
    """Carries (inputs, targets) dict in; emits activations + targets."""

    def __init__(self, input_dim: int, hidden_dim: int):
        self.linear = ColumnParallelLinear(input_dim, hidden_dim, parallel_output=False)

    def init(self, key):
        return {"linear": self.linear.init(key)}

    def param_metas(self):
        return {"linear": tree_prefix(self.linear.param_metas(), "linear")}

    def __call__(self, params, x: dict, ctx: ForwardContext):
        h = self.linear(params["linear"], x["inputs"], ctx)
        return {"activations": jax.nn.relu(h), "targets": x["targets"]}


class HiddenLayer(BaseLayer):
    def __init__(self, hidden_dim: int):
        self.linear = RowParallelLinear(hidden_dim, hidden_dim, parallel_input=False)

    def init(self, key):
        return {"linear": self.linear.init(key)}

    def param_metas(self):
        return {"linear": tree_prefix(self.linear.param_metas(), "linear")}

    def __call__(self, params, x: dict, ctx: ForwardContext):
        h = self.linear(params["linear"], x["activations"], ctx)
        return {"activations": jax.nn.relu(h), "targets": x["targets"]}


class HeadLayer(BaseLayer):
    def __init__(self, hidden_dim: int, num_classes: int):
        self.linear = ColumnParallelLinear(hidden_dim, num_classes, parallel_output=False)

    def init(self, key):
        return {"linear": self.linear.init(key)}

    def param_metas(self):
        return {"linear": tree_prefix(self.linear.param_metas(), "linear")}

    def __call__(self, params, x: dict, ctx: ForwardContext):
        logits = self.linear(params["linear"], x["activations"], ctx)
        return {"logits": logits, "targets": x["targets"]}


def loss_function(output: dict, _batch: Any):
    logits = output["logits"].astype(jnp.float32)
    targets = output["targets"].astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.take_along_axis(logp, targets[:, None], axis=-1).mean()
    accuracy = (logits.argmax(-1) == targets).mean()
    return loss, {"accuracy": accuracy}


def get_layer_specs(config: MLPConfig) -> list[LayerSpec]:
    arch = config.architecture
    specs = [LayerSpec(InputLayer, arch.input_dim, arch.hidden_dim)]
    for _ in range(arch.n_hidden_layers):
        specs.append(LayerSpec(HiddenLayer, arch.hidden_dim))
    specs.append(LayerSpec(HeadLayer, arch.hidden_dim, arch.num_classes))
    return specs


def init_model(config: MLPConfig, topology) -> ParallelModule:
    return ParallelModule(get_layer_specs(config), topology=topology)


def init_optimizer(config: MLPConfig, module: ParallelModule, topology) -> Optimizer:
    metas = module.param_metas()
    from scaling_tpu.nn.param import ParamMeta

    keys = {
        m.key
        for m in jax.tree.leaves(metas, is_leaf=lambda x: isinstance(x, ParamMeta))
    }
    groups = [
        OptimizerParamGroup(
            keys=keys,
            weight_decay=config.training.weight_decay,
            learning_rate_scheduler=config.learning_rate_scheduler,
            name="param_group",
        )
    ]
    return Optimizer(config.optimizer, groups, metas, topology=topology)
