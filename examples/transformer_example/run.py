"""Launch the transformer example:
``python -m examples.transformer_example.run examples/transformer_example/config.yml``

(reference: examples/transformer_example/run.py — config.yml -> runner;
single-host SPMD needs no launcher, so the config feeds main() directly.
For multi-host pods use ``scaling_tpu.runner.runner_main``.)

Generates a tiny synthetic token dataset next to the config on first run.
"""

import sys
from pathlib import Path

import numpy as np

from scaling_tpu.logging import logger
from scaling_tpu.models.transformer import TransformerConfig
from scaling_tpu.models.transformer.train import main


def ensure_example_data(config: TransformerConfig) -> None:
    """Synthesize a zipf-ish token stream if the data prefix is absent."""
    from scaling_tpu.data.memory_map import MemoryMapDatasetBuilder

    prefixes = config.data.data_prefixes or []
    for prefix in prefixes:
        prefix = Path(prefix)
        if prefix.with_suffix(".bin").exists():
            continue
        prefix.parent.mkdir(parents=True, exist_ok=True)
        logger.info(f"generating synthetic example data at {prefix}")
        rng = np.random.default_rng(0)
        vocab = config.transformer_architecture.vocab_size
        with MemoryMapDatasetBuilder(prefix, dtype=np.uint16) as builder:
            for _ in range(512):
                n = int(rng.integers(32, 256))
                doc = (rng.zipf(1.5, size=n) % (vocab - 1)) + 1
                builder.add(np.append(doc, 0).astype(np.uint16))


if __name__ == "__main__":
    config_path = (
        sys.argv[1] if len(sys.argv) > 1 else Path(__file__).parent / "config.yml"
    )
    config = TransformerConfig.from_yaml(config_path)
    ensure_example_data(config)
    main(config)
