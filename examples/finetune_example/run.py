"""LoRA chat-finetune example, end to end on one host:

    python -m examples.finetune_example.run

1. writes the byte-level fallback tokenizer + synthetic data on first run
   (a pretrain token stream and a chat jsonl whose assistant turns carry
   ``has_loss: true`` — the role-masking format of the finetuning chat
   dataset, reference: finetuning_chat_dataset.py);
2. trains the tiny base model if its checkpoint is absent
   (``config_pretrain.yml``);
3. runs the LoRA finetune over it (``config_finetune.yml``): only the
   LoRA matrices train, the base stays frozen.

After it finishes, generate with the tuned adapter:

    python -c "
    from scaling_tpu.models.transformer import TransformerInferenceModule
    m = TransformerInferenceModule.from_checkpoint(
        '.checkpoints/finetune_example/lora')
    print(m.generate('Q: what color is the sky?\\nA:', max_tokens=16).completion)
    "
"""

import json
import sys
from pathlib import Path

import numpy as np

from scaling_tpu.logging import logger
from scaling_tpu.models.transformer import TransformerConfig
from scaling_tpu.models.transformer.tokenizer import Tokenizer
from scaling_tpu.models.transformer.train import main

HERE = Path(__file__).parent
DATA = Path(".data/finetune_example")

QA = [
    ("what color is the sky?", "blue"),
    ("what color is grass?", "green"),
    ("how many legs has a cat?", "four"),
    ("what is 2 plus 2?", "four"),
    ("what is the opposite of hot?", "cold"),
    ("what do bees make?", "honey"),
]


def ensure_data() -> None:
    DATA.mkdir(parents=True, exist_ok=True)
    vocab = DATA / "vocab.json"
    if not vocab.is_file():
        vocab.write_text(Tokenizer.default().tokenizer.to_str())
        logger.info(f"wrote fallback tokenizer to {vocab}")

    pretrain = DATA / "pretrain"
    if not pretrain.with_suffix(".bin").exists():
        from scaling_tpu.models.transformer.data.prepare import prepare

        rng = np.random.default_rng(0)
        words = ["the", "sky", "is", "blue", "grass", "green", "cats", "have",
                 "four", "legs", "bees", "make", "honey", "hot", "cold"]
        docs = DATA / "pretrain_docs.txt"
        docs.write_text("\n".join(
            " ".join(rng.choice(words, size=int(rng.integers(4, 12))))
            for _ in range(256)
        ))
        stats = prepare([docs], vocab, pretrain)  # the dataset-prep CLI path
        logger.info(f"wrote synthetic pretrain stream to {pretrain}: {stats}")

    chat = DATA / "chat.jsonl"
    if not chat.is_file():
        lines = []
        for q, a in QA * 8:
            lines.append(json.dumps([
                {"type": "text", "content": f"Q: {q}\nA:", "has_loss": False},
                {"type": "text", "content": f" {a}<|endoftext|>", "has_loss": True},
            ]))
        chat.write_text("\n".join(lines))
        logger.info(f"wrote chat finetuning data to {chat}")


if __name__ == "__main__":
    ensure_data()
    base_ckpt = Path(".checkpoints/finetune_example/base")
    if not (base_ckpt / "latest").is_file():
        logger.info("phase 1: training the base model")
        main(TransformerConfig.from_yaml(HERE / "config_pretrain.yml"))
    else:
        logger.info(f"phase 1 skipped: base checkpoint at {base_ckpt}")
    logger.info("phase 2: LoRA chat finetune")
    main(TransformerConfig.from_yaml(HERE / "config_finetune.yml"))
    sys.exit(0)
