"""Token embedding input layer.

(reference: src/scaling/transformer/model/layers/embedding.py:29-160) —
VocabParallelEmbedding + embedding dropout, optional softprompt splice.
The batch arrives as the dict the dataset collates
(token_ids/position_ids/segment_ids/loss_weights); this layer turns it into
the transformer IO dict. The image-encoder splice is gated off (config
raises), matching the TPU build's scope.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ....nn import (
    BaseLayer,
    ForwardContext,
    ParamMeta,
    VocabParallelEmbedding,
    tree_prefix,
)
from ..config import SoftpromptConfig, TransformerArchitectureConfig
from .base import make_layer_io


class EmbeddingInput(BaseLayer):
    def __init__(self, architecture: TransformerArchitectureConfig):
        self.architecture = architecture
        self.embedding = VocabParallelEmbedding(
            num_embeddings=architecture.vocab_size,
            embedding_dim=architecture.hidden_size,
            dtype=architecture.dtype,
            finetunable_token_ids=architecture.finetunable_token_ids or None,
        )
        self.dropout_rate = architecture.dropout_embedding
        self.softprompt_config: Optional[SoftpromptConfig] = architecture.softprompt_config
        self.image_encoder = None
        if architecture.image_encoder:
            from ..image_encoder import ImageEncoder

            self.image_encoder = ImageEncoder(
                out_features=architecture.hidden_size,
                width=architecture.image_encoder_width,
                layers=architecture.image_encoder_layers,
                heads=architecture.image_encoder_heads,
                dropout_p=architecture.dropout_image_encoder,
                dtype=architecture.dtype,
                backbone=architecture.image_encoder_backbone,
                resnet_stages=architecture.image_encoder_resnet_stages,
                resnet_channels=architecture.image_encoder_resnet_channels,
            )

    def init(self, key: jax.Array) -> dict:
        params = {"embedding": self.embedding.init(key)}
        if self.image_encoder is not None:
            params["image_encoder"] = self.image_encoder.init(jax.random.fold_in(key, 2))
        if self.softprompt_config is not None:
            sp_key = jax.random.fold_in(key, 1)
            params[f"softprompt_{self.softprompt_config.name}"] = jax.random.normal(
                sp_key,
                (self.softprompt_config.n_tokens, self.architecture.hidden_size),
                dtype=self.architecture.dtype,
            ) * 0.5
        return params

    def param_metas(self) -> dict:
        metas = {"embedding": tree_prefix(self.embedding.param_metas(), "embedding")}
        if self.image_encoder is not None:
            metas["image_encoder"] = self.image_encoder.param_metas()
        if self.softprompt_config is not None:
            name = f"softprompt_{self.softprompt_config.name}"
            metas[name] = ParamMeta(
                parameter_name=name,
                partition_spec=(None, None),
                is_model_parallel_duplicate=True,
            )
        return metas

    def __call__(self, params: dict, batch: dict, ctx: ForwardContext) -> dict:
        token_ids = batch["token_ids"]
        embeddings = self.embedding(params["embedding"], token_ids, ctx)

        if self.image_encoder is not None and batch.get("input_images") is not None:
            # splice 144 encoded prefix tokens per image at its location
            # (reference: embedding.py:53-61,111-144 magma-style)
            imgs = batch["input_images"]  # (b, n_img, H, W, 3)
            locs = batch["input_image_locations"]  # (b, n_img) start positions
            b_, n_img = imgs.shape[:2]
            enc = self.image_encoder(
                params["image_encoder"], imgs.reshape((b_ * n_img,) + imgs.shape[2:]), ctx
            )
            enc = enc.reshape(b_, n_img, enc.shape[-2], enc.shape[-1])
            # (b, n_img) validity mask: collate pads items to the batch's max
            # image count; padded slots must not overwrite real embeddings
            img_mask = batch.get("input_image_mask")
            for j in range(n_img):
                spliced = jax.vmap(
                    lambda e, blk, st: jax.lax.dynamic_update_slice(
                        e, blk.astype(e.dtype), (st, 0)
                    )
                )(embeddings, enc[:, j], locs[:, j].astype(jnp.int32))
                if img_mask is not None:
                    spliced = jnp.where(img_mask[:, j, None, None], spliced, embeddings)
                embeddings = spliced

        if self.softprompt_config is not None:
            # overwrite the first n_tokens positions with the learned prompt
            # (reference: embedding.py:146-160 splices at placeholder ids)
            n = self.softprompt_config.n_tokens
            sp = params[f"softprompt_{self.softprompt_config.name}"]
            sp = jnp.broadcast_to(sp[None], (embeddings.shape[0], n, embeddings.shape[2]))
            embeddings = jax.lax.dynamic_update_slice_in_dim(
                embeddings, sp.astype(embeddings.dtype), 0, axis=1
            )

        embeddings = ctx.dropout(embeddings, self.dropout_rate)

        b, s = token_ids.shape
        position_ids = batch.get("position_ids")
        if position_ids is None:
            position_ids = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
        segment_ids = batch.get("segment_ids")
        if segment_ids is None:
            segment_ids = jnp.zeros((b, s), dtype=jnp.int32)
        from ..config import MLPType

        aux_loss = (
            jnp.zeros((), jnp.float32)
            if self.architecture.mlp_type == MLPType.MOE
            else None
        )
        return make_layer_io(
            activations=embeddings,
            position_ids=position_ids,
            segment_ids=segment_ids,
            loss_weights=batch.get("loss_weights"),
            attention_scores_manipulation=batch.get("attention_scores_manipulation"),
            aux_loss=aux_loss,
        )
