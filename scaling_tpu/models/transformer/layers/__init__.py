from .base import make_layer_io
from .embedding import EmbeddingInput
from .layer import Adapter, TransformerLayer
from .lm_head import (
    LayerNormWrapper,
    TransformerEmbeddingHead,
    TransformerLMHead,
    TransformerLMHeadTied,
)

__all__ = [
    "make_layer_io",
    "EmbeddingInput",
    "Adapter",
    "TransformerLayer",
    "LayerNormWrapper",
    "TransformerEmbeddingHead",
    "TransformerLMHead",
    "TransformerLMHeadTied",
]
