"""The transformer block.

(reference: src/scaling/transformer/model/layers/layer.py:44-291) —
pre-norm attention with residual, pre-norm MLP with residual, dropout after
each block, optional bottleneck adapters after each block. Dropout keys come
from the ForwardContext, which derives them deterministically per call —
that is the whole of the reference's CudaRNGStateTracker on TPU: the same
key is computed on every model-parallel shard, so masks agree by
construction (reference: rng_tracker.py:59-96).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ....nn import (
    BaseLayer,
    ForwardContext,
    ParallelMLP,
    ParallelSelfAttention,
    ParallelSwiGLUMLP,
    ParamMeta,
    get_norm,
    normal_init,
    tree_prefix,
)
from ....nn.rotary import RotaryConfig
from ..config import (
    AdapterConfig,
    MLPType,
    RelativePositionEmbeddingType,
    TransformerArchitectureConfig,
)


class Adapter(BaseLayer):
    """Bottleneck adapter: down-proj -> gelu -> up-proj, residual outside
    (reference: layers/layer.py:140-187). Replicated params (adapters are
    small; sharding them would waste ICI)."""

    def __init__(self, hidden_size: int, downsampling_factor: float, init_std: float, dtype):
        self.hidden_size = hidden_size
        # multiplicative, matching the reference's ParallelMLP factor
        # (layer.py:152): 0.25 -> a 4x bottleneck
        self.bottleneck = max(1, int(hidden_size * downsampling_factor))
        self.init_std = init_std
        self.dtype = dtype

    def init(self, key: jax.Array) -> dict:
        k1, k2 = jax.random.split(key)
        init = normal_init(self.init_std)
        return {
            "down": init(k1, (self.hidden_size, self.bottleneck), self.dtype),
            "up": init(k2, (self.bottleneck, self.hidden_size), self.dtype),
        }

    def param_metas(self) -> dict:
        return {
            "down": ParamMeta(parameter_name="down", partition_spec=(None, None),
                              is_model_parallel_duplicate=True),
            "up": ParamMeta(parameter_name="up", partition_spec=(None, None),
                            is_model_parallel_duplicate=True),
        }

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        h = jax.nn.gelu(x @ params["down"].astype(x.dtype))
        return h @ params["up"].astype(x.dtype)


class TransformerLayer(BaseLayer):
    def __init__(self, architecture: TransformerArchitectureConfig, layer_index: int = 0):
        arch = architecture
        self.architecture = arch
        self.layer_index = layer_index
        dtype = arch.dtype
        bitfit = arch.bitfit_bias_config.name if arch.bitfit_bias_config else None

        self.input_layernorm = get_norm(
            arch.norm_type, arch.hidden_size, arch.layernorm, dtype, bitfit
        )
        rotary_config = None
        if arch.relative_position_embedding_type != RelativePositionEmbeddingType.NONE:
            head_dim = arch.hidden_size // arch.num_attention_heads
            rotary_config = RotaryConfig(
                dimensions=max(2, int(head_dim * arch.rotary_percentage)),
                base=arch.rotary_embedding_base,
                max_seq_length=arch.sequence_length,
            )
        mup_attention_scale = None
        if arch.mup is not None:
            # muP rule: attention logits scale 1/d beyond the base width —
            # sqrt(base_head_dim)/head_dim equals 1/sqrt(head_dim) at the
            # base model and decays like 1/head_dim past it. base_head_dim
            # comes from the base model's own head count: width grown by
            # adding heads keeps head_dim (and this scale) constant
            head_dim = arch.hidden_size // arch.num_attention_heads
            base_heads = (
                arch.mup.base_num_attention_heads or arch.num_attention_heads
            )
            base_head_dim = arch.mup.base_hidden_size / base_heads
            mup_attention_scale = (base_head_dim**0.5) / head_dim
        self.attention = ParallelSelfAttention(
            hidden_size=arch.hidden_size,
            num_attention_heads=arch.num_attention_heads,
            scaling_factor=mup_attention_scale,
            masked_softmax_config=arch.masked_softmax,
            causal=arch.causal,
            num_local_attention_heads=arch.num_local_attention_heads,
            local_attention_window_size=arch.local_attention_window_size,
            dropout_attention_probs=arch.dropout_attention_probs,
            rotary_config=rotary_config,
            relative_position_embedding_type=arch.relative_position_embedding_type.value,
            bias=arch.attention_bias,
            dtype=dtype,
            bitfit_bias_name=bitfit,
            lora_config=arch.lora_config,
            norm_type=arch.norm_type,
            key_query_norm=arch.key_query_norm,
            layernorm_config=arch.layernorm,
            qkv_in_one=arch.attention_qkv_in_one
            and arch.attention_num_kv_heads is None,
            num_kv_heads=arch.attention_num_kv_heads,
        )
        self.post_attention_layernorm = get_norm(
            arch.norm_type, arch.hidden_size, arch.layernorm, dtype, bitfit
        )
        self.is_moe = arch.mlp_type == MLPType.MOE
        if self.is_moe:
            from ....nn.moe import ParallelMoEMLP

            self.mlp: BaseLayer = ParallelMoEMLP(
                io_features=arch.hidden_size,
                intermediate_feature_factor=arch.mlp_factor,
                num_experts=arch.moe_num_experts,
                top_k=arch.moe_top_k,
                capacity_factor=arch.moe_capacity_factor,
                aux_loss_coef=arch.moe_aux_loss_coef,
                glu=True,
                activation=arch.activation_function,
                dtype=dtype,
            )
        elif arch.mlp_type == MLPType.SWIGLU:
            self.mlp = ParallelSwiGLUMLP(
                io_features=arch.hidden_size,
                intermediate_feature_factor=arch.mlp_factor,
                bias=arch.mlp_bias,
                dtype=dtype,
                bitfit_bias_name=bitfit,
            )
        else:
            self.mlp = ParallelMLP(
                io_features=arch.hidden_size,
                intermediate_feature_factor=arch.mlp_factor,
                activation=arch.activation_function,
                bias=arch.mlp_bias,
                dtype=dtype,
                bitfit_bias_name=bitfit,
            )

        self.adapter_attention: Optional[Adapter] = None
        self.adapter_mlp: Optional[Adapter] = None
        self.adapter_name = None
        if arch.adapter_config is not None:
            cfg: AdapterConfig = arch.adapter_config
            self.adapter_name = cfg.name
            if cfg.attention_downsampling_factor:
                self.adapter_attention = Adapter(
                    arch.hidden_size, cfg.attention_downsampling_factor, cfg.init_std, dtype
                )
            if cfg.mlp_downsampling_factor:
                self.adapter_mlp = Adapter(
                    arch.hidden_size, cfg.mlp_downsampling_factor, cfg.init_std, dtype
                )

    # ------------------------------------------------------------------ init
    def init(self, key: jax.Array) -> dict:
        keys = jax.random.split(key, 6)
        params = {
            "input_layernorm": self.input_layernorm.init(keys[0]),
            "attention": self.attention.init(keys[1]),
            "post_attention_layernorm": self.post_attention_layernorm.init(keys[2]),
            "mlp": self.mlp.init(keys[3]),
        }
        if self.adapter_attention is not None:
            params[f"adapter_attention_{self.adapter_name}"] = self.adapter_attention.init(keys[4])
        if self.adapter_mlp is not None:
            params[f"adapter_mlp_{self.adapter_name}"] = self.adapter_mlp.init(keys[5])
        return params

    def param_metas(self) -> dict:
        metas = {
            "input_layernorm": tree_prefix(self.input_layernorm.param_metas(), "input_layernorm"),
            "attention": tree_prefix(self.attention.param_metas(), "attention"),
            "post_attention_layernorm": tree_prefix(
                self.post_attention_layernorm.param_metas(), "post_attention_layernorm"
            ),
            "mlp": tree_prefix(self.mlp.param_metas(), "mlp"),
        }
        if self.adapter_attention is not None:
            name = f"adapter_attention_{self.adapter_name}"
            metas[name] = tree_prefix(self.adapter_attention.param_metas(), name)
        if self.adapter_mlp is not None:
            name = f"adapter_mlp_{self.adapter_name}"
            metas[name] = tree_prefix(self.adapter_mlp.param_metas(), name)
        return metas

    # ----------------------------------------------------------------- merge
    def merge_lora_weights(self, params: dict) -> dict:
        """Fold the attention block's LoRA deltas into its base weights."""
        params = dict(params)
        params["attention"] = self.attention.merge_lora_weights(params["attention"])
        return params

    # ----------------------------------------------------- token slicing
    def init_token_slice_cache(self, params: dict, x: dict,
                               ctx: ForwardContext, capacity: int):
        """Zeroed per-layer KV(+segment-id) cache for TeraPipe token
        slicing (parallel/pipeline.py): k/v buffers at full-sequence
        ``capacity`` on the slot axis, plus the cached slots' segment ids
        so the sliced attention keeps packed-document masking. The shapes
        come from an abstract probe of this layer on one slice, so GQA /
        head-dim / dtype choices never drift from the real attention."""
        import dataclasses as _dc

        probe_ctx = _dc.replace(ctx, dropout_key=None, deterministic=True)

        def probe(p, xx):
            return self(p, xx, probe_ctx, return_kv=True)[1]

        k, v = jax.eval_shape(probe, params, x)

        def grow(aval):
            return jnp.zeros(
                (aval.shape[0], capacity) + aval.shape[2:], aval.dtype
            )

        seg = jnp.zeros((k.shape[0], capacity), jnp.int32)
        return (grow(k), grow(v), seg)

    # --------------------------------------------------------------- forward
    def __call__(self, params: dict, x: dict, ctx: ForwardContext,
                 kv_cache=None, cache_offset=None, return_kv: bool = False):
        arch = self.architecture
        h = x["activations"]

        normed = self.input_layernorm(params["input_layernorm"], h, ctx)
        attn = self.attention(
            params["attention"],
            normed,
            ctx,
            segment_ids=x["segment_ids"],
            position_ids=x["position_ids"],
            kv_cache=kv_cache,
            cache_offset=cache_offset,
            attention_scores_manipulation=x.get("attention_scores_manipulation"),
            # a STATIC python bool (threaded by inference.logits at trace
            # time); never a traced leaf
            attention_scores_manipulation_log_additive=x.get(
                "attention_scores_manipulation_log_additive", True
            ),
            return_kv=return_kv,
        )
        new_kv = None
        if return_kv or kv_cache is not None:
            attn, new_kv = attn
        attn = ctx.dropout(attn, arch.dropout_after_attention)
        if self.adapter_attention is not None:
            attn = attn + self.adapter_attention(
                params[f"adapter_attention_{self.adapter_name}"], attn, ctx
            )
        h = h + attn.astype(h.dtype)

        normed = self.post_attention_layernorm(params["post_attention_layernorm"], h, ctx)
        aux_loss = None
        if self.is_moe:
            mlp_out, aux_loss = self.mlp(params["mlp"], normed, ctx)
        else:
            mlp_out = self.mlp(params["mlp"], normed, ctx)
        mlp_out = ctx.dropout(mlp_out, arch.dropout_after_mlp)
        if self.adapter_mlp is not None:
            mlp_out = mlp_out + self.adapter_mlp(
                params[f"adapter_mlp_{self.adapter_name}"], mlp_out, ctx
            )
        h = h + mlp_out.astype(h.dtype)

        out = dict(x)
        out["activations"] = h
        if aux_loss is not None:
            # router load-balance loss rides the IO dict to the loss function
            out["aux_loss"] = x.get("aux_loss", 0.0) + aux_loss
        if new_kv is not None:
            return out, new_kv
        return out
