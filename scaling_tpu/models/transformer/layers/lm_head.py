"""Final norm + LM heads.

(reference: src/scaling/transformer/model/layers/layernorm.py:13-56,
lm_head.py:16-66, lm_head_tied.py:17-55, embedding_head.py:12-80)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ....nn import (
    BaseLayer,
    ColumnParallelLinear,
    ForwardContext,
    ParamMeta,
    get_norm,
    normal_init,
    tree_prefix,
    xavier_normal_init,
)
from ....parallel.sharding import constrain
from ....topology.topology import MODEL_AXIS
from ..config import EmbeddingHeadConfig, TransformerArchitectureConfig


class LayerNormWrapper(BaseLayer):
    """Final norm; records the normed hidden state into ``embeddings`` for
    downstream embedding heads (reference: layernorm.py:13-56)."""

    def __init__(self, architecture: TransformerArchitectureConfig,
                 record_embeddings: bool = False):
        arch = architecture
        bitfit = arch.bitfit_bias_config.name if arch.bitfit_bias_config else None
        self.norm = get_norm(arch.norm_type, arch.hidden_size, arch.layernorm,
                             arch.dtype, bitfit)
        self.record_embeddings = record_embeddings

    def init(self, key: jax.Array) -> dict:
        return {"norm": self.norm.init(key)}

    def param_metas(self) -> dict:
        return {"norm": tree_prefix(self.norm.param_metas(), "norm")}

    def __call__(self, params: dict, x: dict, ctx: ForwardContext) -> dict:
        out = dict(x)
        out["activations"] = self.norm(params["norm"], x["activations"], ctx)
        if self.record_embeddings:
            out["embeddings"] = out["activations"]
        return out


class TransformerLMHead(BaseLayer):
    """Untied head: column-parallel projection to the vocabulary
    (reference: lm_head.py:16-66). Under muP the readout zero-initializes
    and logits carry the tunable output_mult; the width correction is the
    readout's 1/m learning-rate scale, NOT a logit multiplier — applying
    both (the two equivalent muP output formulations) over-suppresses
    updates by an extra 1/m, which the coordinate check catches."""

    def __init__(self, architecture: TransformerArchitectureConfig):
        arch = architecture
        mup = arch.mup
        init_method = xavier_normal_init
        self.logit_mult = None
        if mup is not None:
            self.logit_mult = mup.output_mult
            if mup.readout_zero_init:
                init_method = lambda key, shape, dtype: jnp.zeros(shape, dtype)  # noqa: E731
        self.linear = ColumnParallelLinear(
            arch.hidden_size,
            arch.vocab_size,
            bias=False,
            dtype=arch.dtype,
            parallel_output=False,
            init_method=init_method,
        )

    def init(self, key: jax.Array) -> dict:
        return {"linear": self.linear.init(key)}

    def param_metas(self) -> dict:
        return {"linear": tree_prefix(self.linear.param_metas(), "linear")}

    def __call__(self, params: dict, x: dict, ctx: ForwardContext) -> dict:
        out = dict(x)
        logits = self.linear(params["linear"], x["activations"], ctx)
        if self.logit_mult is not None:
            logits = logits * jnp.asarray(self.logit_mult, logits.dtype)
        out["activations"] = logits
        return out


class TransformerLMHeadTied(BaseLayer):
    """Weight-tied head reusing the embedding table. Assembled as a
    TiedLayerSpec with key "embedding_lm_head" and tied attribute
    ``embedding.weight``, so the params alias the EmbeddingInput table —
    gradients flow into one array and the reference's tied-grad all-reduce
    (tied_layer_index.py:74-224) has no equivalent to need.
    """

    def __init__(self, architecture: TransformerArchitectureConfig):
        self.architecture = architecture
        self.dtype = architecture.dtype

    def init(self, key: jax.Array) -> dict:
        arch = self.architecture
        return {
            "embedding": {
                "weight": xavier_normal_init(
                    key, (arch.vocab_size, arch.hidden_size), self.dtype
                )
            }
        }

    def param_metas(self) -> dict:
        return {
            "embedding": {
                "weight": ParamMeta(
                    parameter_name="embedding.weight",
                    partition_spec=(MODEL_AXIS, None),
                    is_model_parallel=True,
                    model_parallel_dimension=0,
                    lr_group="embedding",
                )
            }
        }

    def __call__(self, params: dict, x: dict, ctx: ForwardContext) -> dict:
        weight = params["embedding"]["weight"].astype(self.dtype)
        h = x["activations"]
        logits = jnp.einsum("bsh,vh->bsv", h, weight)
        # vocab-sharded matmul output -> gathered full logits (the
        # reference's all-concat, lm_head_tied.py:41-53); XLA emits the
        # all-gather from the sharding constraint
        logits = constrain(logits, ctx.mesh, None, None, None)
        out = dict(x)
        out["activations"] = logits
        return out


class TransformerEmbeddingHead(BaseLayer):
    """Weighted-mean-pool over the sequence + projection stack for
    embedding models (reference: embedding_head.py:12-80)."""

    def __init__(self, architecture: TransformerArchitectureConfig):
        arch = architecture
        assert arch.embedding_head_config is not None
        cfg: EmbeddingHeadConfig = arch.embedding_head_config
        self.name = cfg.name
        self.dims = [arch.hidden_size] + list(cfg.proj_layers)
        self.dtype = arch.dtype

    def init(self, key: jax.Array) -> dict:
        params = {}
        for i, (d_in, d_out) in enumerate(zip(self.dims[:-1], self.dims[1:])):
            params[f"proj_{i}_{self.name}"] = xavier_normal_init(
                jax.random.fold_in(key, i), (d_in, d_out), self.dtype
            )
        return params

    def param_metas(self) -> dict:
        metas = {}
        for i, _ in enumerate(self.dims[:-1]):
            name = f"proj_{i}_{self.name}"
            metas[name] = ParamMeta(
                parameter_name=name,
                partition_spec=(None, None),
                is_model_parallel_duplicate=True,
            )
        return metas

    def __call__(self, params: dict, x: dict, ctx: ForwardContext) -> dict:
        h = x["embeddings"] if x.get("embeddings") is not None else x["activations"]
        weights = x.get("loss_weights")
        if weights is None:
            weights = jnp.ones(h.shape[:2], dtype=jnp.float32)
        weights = weights.astype(jnp.float32)
        denom = jnp.maximum(weights.sum(axis=1, keepdims=True), 1.0)
        pooled = (h.astype(jnp.float32) * weights[..., None]).sum(axis=1) / denom
        pooled = pooled.astype(h.dtype)
        for i, _ in enumerate(self.dims[:-1]):
            pooled = pooled @ params[f"proj_{i}_{self.name}"].astype(pooled.dtype)
            if i < len(self.dims) - 2:
                pooled = jax.nn.gelu(pooled)
        out = dict(x)
        out["embeddings"] = pooled
        return out
