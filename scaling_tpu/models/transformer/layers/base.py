"""Transformer layer IO.

The reference threads a ``TransformerLayerIO`` dataclass through the stack
with tuple-conversion manifests for pipe communication
(reference: src/scaling/transformer/model/layers/base.py:12-124). Under jit
the IO is a plain dict pytree with static treedef — no manifests needed.
Non-tensor inference settings travel as jit-static layer attributes, not as
runtime payload (the reference pickles them through the pipe, a pattern that
cannot exist under XLA's static shapes).

Keys:
  activations     (b, s, hidden)
  position_ids    (b, s) int32
  segment_ids     (b, s) int32 — TPU-native packing representation; the
                  reference's ``cumulative_seq_lengths`` converts to/from
                  this via nn.seq_packing
  loss_weights    (b, s) float32 or None
  embeddings      recorded final hidden state for embedding heads, or None
  attention_scores_manipulation  optional additive mask bias or None
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax


def make_layer_io(
    activations: jax.Array,
    position_ids: jax.Array,
    segment_ids: jax.Array,
    loss_weights: Optional[jax.Array] = None,
    embeddings: Optional[jax.Array] = None,
    attention_scores_manipulation: Optional[jax.Array] = None,
    aux_loss: Optional[jax.Array] = None,
) -> Dict[str, Any]:
    io = {
        "activations": activations,
        "position_ids": position_ids,
        "segment_ids": segment_ids,
        "loss_weights": loss_weights,
        "embeddings": embeddings,
        "attention_scores_manipulation": attention_scores_manipulation,
    }
    if aux_loss is not None:
        # MoE router load-balance term, accumulated layer by layer; present
        # only for MoE models so dense pytrees keep their shape
        io["aux_loss"] = aux_loss
    return io
