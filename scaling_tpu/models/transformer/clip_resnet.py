"""CLIP ModifiedResNet vision trunk — the RN50x16 family.

The reference hard-wires its image encoder to ``ClipRN50x16``
(reference: transformer/model/image_encoder/image_encoder.py:15-29,
clip.py:41-168 — itself the public openai/CLIP ``ModifiedResNet``), and
notably DROPS CLIP's attention-pool head: the final 12x12 spatial grid is
returned as 144 tokens of ``8 * channels * 4`` features (3072 for
RN50x16), magma-style. This module reproduces that trunk so the
reference's actual pretrained vision checkpoints transfer.

TPU-first choices:
- NHWC activations / HWIO kernels — the native TPU conv layout; the
  weight import transposes torch's OIHW once at load time.
- BatchNorm runs in inference mode off the stored statistics, with
  ``stop_gradient`` on mean/var: the pretrained trunk's statistics are
  frozen (matching the magma-style frozen-or-light-finetune usage) while
  the affine terms and conv kernels remain trainable. Batch-statistics
  training is deliberately unsupported — under ``pjit``/DP sharding it
  would need cross-device batch reductions per BN layer, a poor fit the
  ViT backbones avoid entirely.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ...nn import ForwardContext
from ...nn.param import replicated_meta, tree_prefix

_BN_EPS = 1e-5  # torch.nn.BatchNorm2d default, which the checkpoints assume
_EXPANSION = 4
_DOWNSAMPLE = 32  # stem (4x) * three strided stages (2x each)


def _conv(x: jax.Array, w: jax.Array, stride: int = 1, padding: int = 0):
    return jax.lax.conv_general_dilated(
        x, w,
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(p: dict, x: jax.Array) -> jax.Array:
    mean = jax.lax.stop_gradient(p["mean"])
    var = jax.lax.stop_gradient(p["var"])
    scale = p["weight"] * jax.lax.rsqrt(var + _BN_EPS)
    return x * scale + (p["bias"] - mean * scale)


def _avg_pool(x: jax.Array, k: int) -> jax.Array:
    out = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, k, k, 1), (1, k, k, 1), "VALID"
    )
    return out / (k * k)


def _conv_init(key, kh, kw, c_in, c_out, dtype):
    fan_in = kh * kw * c_in
    return jax.random.normal(key, (kh, kw, c_in, c_out), dtype) * jnp.sqrt(
        2.0 / fan_in
    )


def _bn_init(c, dtype):
    return {
        "weight": jnp.ones((c,), dtype),
        "bias": jnp.zeros((c,), dtype),
        "mean": jnp.zeros((c,), dtype),
        "var": jnp.ones((c,), dtype),
    }


def _bn_metas():
    # affine terms train (no decay, like other norms); the frozen running
    # statistics are stop-gradient'd in the forward AND no-decay here, so
    # AdamW leaves them bit-identical
    return {
        k: replicated_meta(1, no_weight_decay=True, parameter_name=k)
        for k in ("weight", "bias", "mean", "var")
    }


class _Bottleneck:
    """conv1x1-bn-relu, conv3x3-bn-relu, avgpool(stride), conv1x1-bn,
    residual add, relu — CLIP's anti-aliased bottleneck where strided
    convs are replaced by stride-1 convs behind an average pool
    (reference clip.py:41-99)."""

    def __init__(self, c_in: int, planes: int, stride: int):
        self.c_in = c_in
        self.planes = planes
        self.c_out = planes * _EXPANSION
        self.stride = stride
        self.has_downsample = stride > 1 or c_in != self.c_out

    def init(self, key, dtype) -> dict:
        ks = jax.random.split(key, 4)
        p = {
            "conv1": {"weight": _conv_init(ks[0], 1, 1, self.c_in, self.planes, dtype)},
            "bn1": _bn_init(self.planes, dtype),
            "conv2": {"weight": _conv_init(ks[1], 3, 3, self.planes, self.planes, dtype)},
            "bn2": _bn_init(self.planes, dtype),
            "conv3": {"weight": _conv_init(ks[2], 1, 1, self.planes, self.c_out, dtype)},
            "bn3": _bn_init(self.c_out, dtype),
        }
        if self.has_downsample:
            p["downsample_conv"] = {
                "weight": _conv_init(ks[3], 1, 1, self.c_in, self.c_out, dtype)
            }
            p["downsample_bn"] = _bn_init(self.c_out, dtype)
        return p

    def param_metas(self) -> dict:
        def conv_metas():
            return {"weight": replicated_meta(4, parameter_name="weight")}

        m = {
            "conv1": conv_metas(), "bn1": _bn_metas(),
            "conv2": conv_metas(), "bn2": _bn_metas(),
            "conv3": conv_metas(), "bn3": _bn_metas(),
        }
        if self.has_downsample:
            m["downsample_conv"] = conv_metas()
            m["downsample_bn"] = _bn_metas()
        return {k: tree_prefix(v, k) for k, v in m.items()}

    def __call__(self, p: dict, x: jax.Array) -> jax.Array:
        out = jax.nn.relu(_bn(p["bn1"], _conv(x, p["conv1"]["weight"])))
        out = jax.nn.relu(_bn(p["bn2"], _conv(out, p["conv2"]["weight"], padding=1)))
        if self.stride > 1:
            out = _avg_pool(out, self.stride)
        out = _bn(p["bn3"], _conv(out, p["conv3"]["weight"]))
        identity = x
        if self.has_downsample:
            if self.stride > 1:
                identity = _avg_pool(identity, self.stride)
            identity = _bn(
                p["downsample_bn"], _conv(identity, p["downsample_conv"]["weight"])
            )
        return jax.nn.relu(out + identity)


class ClipResNetEncoder:
    """(b, image_size, image_size, 3) NHWC -> (b, (image_size/32)^2,
    8 * channels * expansion) spatial tokens."""

    def __init__(
        self,
        stage_blocks: Sequence[int] = (6, 8, 18, 8),  # RN50x16
        channels: int = 96,
        image_size: int = 384,
        dtype=jnp.float32,
    ):
        if len(stage_blocks) != 4:
            # out_dim (channels*8*4) and the 32x downsample both assume the
            # CLIP 4-stage layout; a 3- or 5-stage trunk would silently
            # desynchronize proj sizing and token count
            raise ValueError(
                f"ClipResNetEncoder needs exactly 4 stages (CLIP layout), "
                f"got {tuple(stage_blocks)}"
            )
        assert image_size % _DOWNSAMPLE == 0, image_size
        self.stage_blocks = tuple(stage_blocks)
        self.channels = channels
        self.image_size = image_size
        self.dtype = dtype
        self.out_dim = channels * 8 * _EXPANSION
        self.tokens = (image_size // _DOWNSAMPLE) ** 2

        self.stages: list[list[_Bottleneck]] = []
        c_in = channels
        for i, blocks in enumerate(self.stage_blocks):
            planes = channels * (2 ** i)
            stride = 1 if i == 0 else 2
            stage = [_Bottleneck(c_in, planes, stride)]
            c_in = planes * _EXPANSION
            for _ in range(1, blocks):
                stage.append(_Bottleneck(c_in, planes, 1))
            self.stages.append(stage)

    def init(self, key: jax.Array) -> dict:
        n_blocks = sum(len(s) for s in self.stages)
        ks = iter(jax.random.split(key, 3 + n_blocks))
        half = self.channels // 2
        params: dict = {
            "stem": {
                "conv1": {"weight": _conv_init(next(ks), 3, 3, 3, half, self.dtype)},
                "bn1": _bn_init(half, self.dtype),
                "conv2": {"weight": _conv_init(next(ks), 3, 3, half, half, self.dtype)},
                "bn2": _bn_init(half, self.dtype),
                "conv3": {"weight": _conv_init(next(ks), 3, 3, half, self.channels, self.dtype)},
                "bn3": _bn_init(self.channels, self.dtype),
            }
        }
        for i, stage in enumerate(self.stages):
            params[f"layer{i + 1}"] = {
                f"block_{j}": blk.init(next(ks), self.dtype)
                for j, blk in enumerate(stage)
            }
        return params

    def param_metas(self) -> dict:
        def conv_metas():
            return {"weight": replicated_meta(4, parameter_name="weight")}

        stem = {
            "conv1": conv_metas(), "bn1": _bn_metas(),
            "conv2": conv_metas(), "bn2": _bn_metas(),
            "conv3": conv_metas(), "bn3": _bn_metas(),
        }
        metas: dict = {
            "stem": {k: tree_prefix(v, k) for k, v in stem.items()}
        }
        for i, stage in enumerate(self.stages):
            metas[f"layer{i + 1}"] = {
                f"block_{j}": tree_prefix(blk.param_metas(), f"block_{j}")
                for j, blk in enumerate(stage)
            }
        return {k: tree_prefix(v, k) for k, v in metas.items()}

    def __call__(self, params: dict, images: jax.Array, ctx: ForwardContext) -> jax.Array:
        x = images.astype(self.dtype)
        s = params["stem"]
        x = jax.nn.relu(_bn(s["bn1"], _conv(x, s["conv1"]["weight"], stride=2, padding=1)))
        x = jax.nn.relu(_bn(s["bn2"], _conv(x, s["conv2"]["weight"], padding=1)))
        x = jax.nn.relu(_bn(s["bn3"], _conv(x, s["conv3"]["weight"], padding=1)))
        x = _avg_pool(x, 2)
        for i, stage in enumerate(self.stages):
            sp = params[f"layer{i + 1}"]
            for j, blk in enumerate(stage):
                x = blk(sp[f"block_{j}"], x)
        b, h, w, c = x.shape
        # the reference returns the grid row-major as tokens
        # (clip.py:166 "b d h w -> b (h w) d"; NHWC needs no transpose)
        return x.reshape(b, h * w, c)


def _torch_bn(sd, prefix, dtype):
    import numpy as np

    return {
        "weight": jnp.asarray(np.asarray(sd[f"{prefix}.weight"], dtype=np.float32), dtype),
        "bias": jnp.asarray(np.asarray(sd[f"{prefix}.bias"], dtype=np.float32), dtype),
        "mean": jnp.asarray(np.asarray(sd[f"{prefix}.running_mean"], dtype=np.float32), dtype),
        "var": jnp.asarray(np.asarray(sd[f"{prefix}.running_var"], dtype=np.float32), dtype),
    }


def _torch_conv(sd, key, dtype):
    import numpy as np

    w = np.asarray(sd[key], dtype=np.float32)  # OIHW
    return {"weight": jnp.asarray(w.transpose(2, 3, 1, 0), dtype)}  # HWIO


def import_clip_resnet_weights(encoder: ClipResNetEncoder, state_dict) -> dict:
    """Map an OpenAI-CLIP-format ModifiedResNet state dict onto
    :class:`ClipResNetEncoder` params.

    Accepts the full CLIP model (``visual.conv1.weight`` ...), a
    visual-only dict (``conv1.weight`` ...), or a reference
    ``ImageEncoder`` dump (``input_encoder.conv1.weight`` ...,
    image_encoder.py:22-28). Geometry is validated against ``encoder``;
    tensors convert from torch OIHW to TPU HWIO once, here."""
    import numpy as np  # noqa: F401  (used via helpers)

    sd = {}
    for k, v in state_dict.items():
        stripped = True
        while stripped:  # prefixes stack, e.g. "module.visual.conv1.weight"
            stripped = False
            for prefix in ("visual.", "input_encoder.", "module."):
                if k.startswith(prefix):
                    k = k[len(prefix):]
                    stripped = True
        if hasattr(v, "detach"):
            v = v.detach().cpu().numpy()
        sd[k] = v

    dtype = encoder.dtype
    w1 = sd.get("conv1.weight")
    if w1 is None:
        raise ValueError("state dict has no ModifiedResNet trunk (conv1.weight)")
    if tuple(w1.shape) != (encoder.channels // 2, 3, 3, 3):
        raise ValueError(
            f"channel mismatch: checkpoint stem {tuple(w1.shape)} vs "
            f"encoder channels={encoder.channels} (expected "
            f"{(encoder.channels // 2, 3, 3, 3)})"
        )
    params: dict = {
        "stem": {
            "conv1": _torch_conv(sd, "conv1.weight", dtype),
            "bn1": _torch_bn(sd, "bn1", dtype),
            "conv2": _torch_conv(sd, "conv2.weight", dtype),
            "bn2": _torch_bn(sd, "bn2", dtype),
            "conv3": _torch_conv(sd, "conv3.weight", dtype),
            "bn3": _torch_bn(sd, "bn3", dtype),
        }
    }
    for i, stage in enumerate(encoder.stages):
        name = f"layer{i + 1}"
        n_ckpt = len(
            {k.split(".")[1] for k in sd if k.startswith(f"{name}.")}
        )
        if n_ckpt != len(stage):
            raise ValueError(
                f"stage depth mismatch at {name}: checkpoint has {n_ckpt} "
                f"blocks, encoder expects {len(stage)} "
                f"(stage_blocks={encoder.stage_blocks})"
            )
        blocks = {}
        for j, blk in enumerate(stage):
            base = f"{name}.{j}"
            p = {
                "conv1": _torch_conv(sd, f"{base}.conv1.weight", dtype),
                "bn1": _torch_bn(sd, f"{base}.bn1", dtype),
                "conv2": _torch_conv(sd, f"{base}.conv2.weight", dtype),
                "bn2": _torch_bn(sd, f"{base}.bn2", dtype),
                "conv3": _torch_conv(sd, f"{base}.conv3.weight", dtype),
                "bn3": _torch_bn(sd, f"{base}.bn3", dtype),
            }
            has_ds = f"{base}.downsample.0.weight" in sd
            if has_ds != blk.has_downsample:
                raise ValueError(f"downsample mismatch at {base}")
            if has_ds:
                p["downsample_conv"] = _torch_conv(
                    sd, f"{base}.downsample.0.weight", dtype
                )
                p["downsample_bn"] = _torch_bn(sd, f"{base}.downsample.1", dtype)
            blocks[f"block_{j}"] = p
        params[name] = blocks
    return params
