"""TFLOPs and MFU estimators.

(reference: src/scaling/transformer/utils/get_tflops.py:12-401) — the same
five estimator families, with the hardware peak table swapped from GPUs to
TPU generations (bf16 peak per chip; public cloud.google.com figures).
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class HardwareType(Enum):
    TPU_V4 = "tpu_v4"
    TPU_V5E = "tpu_v5e"
    TPU_V5P = "tpu_v5p"
    TPU_V6E = "tpu_v6e"
    A100 = "a100"
    H100 = "h100"

    @property
    def max_tflops(self) -> float:
        return {
            HardwareType.TPU_V4: 275.0,
            HardwareType.TPU_V5E: 197.0,
            HardwareType.TPU_V5P: 459.0,
            HardwareType.TPU_V6E: 918.0,
            HardwareType.A100: 312.0,
            HardwareType.H100: 989.4,
        }[self]


def get_model_parameter_count(
    hidden_size: int, num_layers: int, vocab_size: int,
    mlp_factor: float = 4.0, glu: bool = False,
) -> int:
    per_layer = 4 * hidden_size * hidden_size + (3 if glu else 2) * int(
        hidden_size * hidden_size * mlp_factor
    )
    return num_layers * per_layer + vocab_size * hidden_size


def get_tflops_megatron(
    parameter_count: int,
    iter_time_s: float,
    global_batch_size: int,
    sequence_length: int,
) -> float:
    """6 * N * tokens (reference: get_tflops.py:319-334)."""
    flops = 6.0 * parameter_count * global_batch_size * sequence_length
    return flops / iter_time_s / 1e12


def get_tflops_bloom(
    hidden_size: int,
    num_layers: int,
    vocab_size: int,
    iter_time_s: float,
    global_batch_size: int,
    sequence_length: int,
    activation_checkpointing: bool = False,
) -> float:
    """Megatron-paper Appendix formula with the 4/3 recompute factor
    (reference: get_tflops.py:245-316)."""
    coeff = 4.0 if activation_checkpointing else 3.0
    flops = (
        24.0 * coeff * global_batch_size * sequence_length * num_layers * hidden_size**2
        * (
            1.0
            + sequence_length / (6.0 * hidden_size)
            + vocab_size / (16.0 * num_layers * hidden_size)
        )
    )
    return flops / iter_time_s / 1e12


def get_tflops_electra(
    hidden_size: int,
    num_layers: int,
    num_attention_heads: int,
    vocab_size: int,
    sequence_length: int,
    iter_time_s: float,
    global_batch_size: int,
    mlp_factor: float = 4.0,
) -> float:
    """Per-op forward count x3 for fwd+bwd (reference: get_tflops.py:128-242)."""
    head_dim = hidden_size // num_attention_heads
    attn = (
        3 * 2 * hidden_size * hidden_size  # qkv
        + 2 * num_attention_heads * sequence_length * head_dim  # scores
        + 2 * num_attention_heads * sequence_length * head_dim  # context
        + 2 * hidden_size * hidden_size  # dense
    )
    mlp = 2 * 2 * int(hidden_size * hidden_size * mlp_factor)
    per_token = num_layers * (attn + mlp) + 2 * hidden_size * vocab_size
    flops = 3.0 * per_token * global_batch_size * sequence_length
    return flops / iter_time_s / 1e12


def get_tflops_aleph_alpha(
    hidden_size: int,
    num_layers: int,
    num_attention_heads: int,
    vocab_size: int,
    sequence_length: int,
    iter_time_s: float,
    global_batch_size: int,
    mlp_factor: float = 4.0,
) -> float:
    """House estimator incl. attention quadratic term
    (reference: get_tflops.py:12-125)."""
    qkv = 6 * hidden_size * hidden_size
    scores = 2 * sequence_length * hidden_size
    ctx = 2 * sequence_length * hidden_size
    dense = 2 * hidden_size * hidden_size
    mlp = 4 * int(hidden_size * hidden_size * mlp_factor)
    lm_head = 2 * hidden_size * vocab_size
    per_token = num_layers * (qkv + scores + ctx + dense + mlp) + lm_head
    flops = 3.0 * per_token * global_batch_size * sequence_length
    return flops / iter_time_s / 1e12


def get_flops_per_token(
    parameter_count: int,
    num_layers: int,
    hidden_size: int,
    sequence_length: int,
) -> float:
    """PaLM appendix-B train FLOPs per token: ``6N`` matmul plus the
    ``12 L H S`` attention quadratic term. This is the single number the
    obs telemetry layer needs from a model to turn step time into
    achieved-TFLOPs/MFU gauges (docs/OBSERVABILITY.md)."""
    return (
        6.0 * parameter_count
        + 12.0 * num_layers * hidden_size * sequence_length
    )


def get_palm_mfu(
    parameter_count: int,
    num_layers: int,
    hidden_size: int,
    sequence_length: int,
    tokens_per_second: float,
    world_size: int,
    hardware: HardwareType = HardwareType.TPU_V5P,
) -> float:
    """PaLM appendix-B MFU: observed tokens/s over peak-flop token rate
    (reference: get_tflops.py:337-401)."""
    flops_per_token = get_flops_per_token(
        parameter_count, num_layers, hidden_size, sequence_length
    )
    peak_tokens_per_second = hardware.max_tflops * 1e12 * world_size / flops_per_token
    return tokens_per_second / peak_tokens_per_second
