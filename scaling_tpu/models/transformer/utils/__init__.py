from .get_tflops import (
    HardwareType,
    get_model_parameter_count,
    get_palm_mfu,
    get_tflops_aleph_alpha,
    get_tflops_bloom,
    get_tflops_electra,
    get_tflops_megatron,
)

__all__ = [
    "HardwareType",
    "get_model_parameter_count",
    "get_palm_mfu",
    "get_tflops_aleph_alpha",
    "get_tflops_bloom",
    "get_tflops_electra",
    "get_tflops_megatron",
]
