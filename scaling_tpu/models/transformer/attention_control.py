"""AtMan-style attention control for inference.

(reference: src/scaling/transformer/data/inference_settings.py:1-54 +
attention.py:105-190) — per-token suppression/amplification factors become
a manipulation on pre-softmax attention scores, flowing through the batch
dict every layer already consumes (``attention_scores_manipulation``).
Both reference variants are supported: log-additive (the default
``control_log_additive=True`` — offsets of ``log(factor)`` added to
scores) and multiplicative (``control_log_additive=False`` — scores are
shifted so the minimum unmasked value is 0, then scaled by the factors;
reference attention.py:166-170).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np
from pydantic import Field

from ...config import BaseConfig


class Control(BaseConfig):
    """Scale attention toward one key position by ``factor``
    (reference: inference_settings.py:8-12)."""

    token_index: int = Field(description="key/token position to control", ge=0)
    factor: float = Field(description="attention factor; <1 suppresses. 0 "
                          "removes the token entirely under log-additive "
                          "application; under multiplicative it pins the "
                          "column at the row's minimum score (weight "
                          "exp(0)/Z, not 0 — reference semantics)", ge=0)


def build_attention_scores_manipulation(
    controls: List[Control],
    seq_len: int,
    batch_size: int = 1,
    dtype=jnp.float32,
    log_additive: bool = True,
) -> Optional[jnp.ndarray]:
    """-> (batch, 1, s_q, s_k) score manipulation, or None if empty.

    ``log_additive=True`` (reference default): every query's score against
    a controlled key position shifts by ``log(factor)`` (-10000 for factor
    0, reference embedding.py:273-276); after softmax that multiplies the
    attention weight by ~``factor``. ``log_additive=False``: an identity-1
    matrix with ``factor`` in controlled columns, MULTIPLIED into
    min-shifted scores by the attention layer (reference
    attention.py:166-170 + embedding.py:188-189).
    """
    if not controls:
        return None
    fill = 0.0 if log_additive else 1.0
    out = np.full((batch_size, 1, seq_len, seq_len), fill, np.float32)
    for c in controls:
        if c.token_index >= seq_len:
            raise ValueError(
                f"control token_index {c.token_index} >= sequence length {seq_len}"
            )
        # ASSIGNMENT, not accumulation, for both variants — duplicate
        # controls are last-wins like the reference (embedding.py:273-278)
        if log_additive:
            out[:, :, :, c.token_index] = (
                -10000.0 if c.factor == 0.0 else float(np.log(c.factor))
            )
        else:
            out[:, :, :, c.token_index] = c.factor
    return jnp.asarray(out, dtype)
