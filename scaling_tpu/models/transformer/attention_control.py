"""AtMan-style attention control for inference.

(reference: src/scaling/transformer/data/inference_settings.py:1-54 +
attention.py:105-190) — per-token suppression/amplification factors become
an additive manipulation on pre-softmax attention scores, flowing through
the batch dict every layer already consumes
(``attention_scores_manipulation``). Log-additive application matches the
reference's default ``control_log_additive=True`` path; the multiplicative
variant operates on a different scale per layer-score distribution and is
intentionally not offered.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np
from pydantic import Field

from ...config import BaseConfig


class Control(BaseConfig):
    """Scale attention toward one key position by ``factor``
    (reference: inference_settings.py:8-12)."""

    token_index: int = Field(description="key/token position to control", ge=0)
    factor: float = Field(description="attention factor; <1 suppresses", gt=0)


def build_attention_scores_manipulation(
    controls: List[Control],
    seq_len: int,
    batch_size: int = 1,
    dtype=jnp.float32,
) -> Optional[jnp.ndarray]:
    """-> (batch, 1, s_q, s_k) additive score offsets, or None if empty.

    Every query's score against a controlled key position shifts by
    ``log(factor)``; after softmax that multiplies the attention weight by
    ~``factor`` (exactly, up to renormalisation) — the reference's
    log-additive semantics.
    """
    if not controls:
        return None
    offsets = np.zeros((batch_size, 1, seq_len, seq_len), np.float32)
    for c in controls:
        if c.token_index >= seq_len:
            raise ValueError(
                f"control token_index {c.token_index} >= sequence length {seq_len}"
            )
        offsets[:, :, :, c.token_index] += float(np.log(c.factor))
    return jnp.asarray(offsets, dtype)
