"""Multimodal image encoder (magma-style prefix tokens).

(reference: src/scaling/transformer/model/image_encoder/image_encoder.py,
clip.py — a CLIP RN50x16 ResNet producing 144 tokens of 3072 features from
a 384x384 image, projected to hidden_size and spliced into the embedding
stream). The TPU-first redesign keeps the exact interface — 384x384 input,
(384/32)^2 = 144 prefix tokens, linear projection + dropout + layernorm —
but replaces the convolutional backbone with a ViT-style patch encoder:

- 32x32 patchify is a reshape + one (3072 -> width) matmul: pure MXU work,
  no BatchNorm state, no conv lowering;
- the backbone is our own bidirectional attention stack
  (ParallelSelfAttention with causal=False), so TP sharding of the vision
  tower comes for free.

Three backbones:
- ``backbone="vit"`` (default): the from-scratch stack above, trained
  jointly with the language model;
- ``backbone="clip"``: a faithful CLIP ViT trunk (``clip_vision.py``)
  that loads pretrained huggingface ``CLIPVisionModel`` weights via
  :meth:`ImageEncoder.load_clip_weights` — the pretrained-vision-prior
  capability re-based onto the ViT family whose weights transfer to a
  TPU-first stack;
- ``backbone="clip_resnet"``: the reference's ACTUAL trunk — the CLIP
  ModifiedResNet (RN50x16 at the defaults, ``clip_resnet.py``) — so
  reference/magma vision checkpoints transfer unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn import (
    BaseLayer,
    ColumnParallelLinear,
    ForwardContext,
    LayerNorm,
    LayerNormConfig,
    ParallelMLP,
    ParallelSelfAttention,
    RowParallelLinear,
    tree_prefix,
)

IMAGE_SIZE = 384
PATCH_SIZE = 32
IMAGE_ENCODER_TOKEN_COUNTS = (IMAGE_SIZE // PATCH_SIZE) ** 2  # 144, as reference


def patchify(images: jax.Array, patch_size: int) -> jax.Array:
    """(b, H, W, 3) -> (b, tokens, p*p*3) via reshape/transpose.

    The flattening order (ph, pw, c) is LAYOUT-CRITICAL: the CLIP weight
    import (clip_vision.import_clip_vision_weights) flattens the pretrained
    conv kernel in exactly this order — both backbones share this one
    definition so they cannot desynchronize."""
    b, h, w, c = images.shape
    p = patch_size
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (b, gh, gw, p, p, c)
    return x.reshape(b, (h // p) * (w // p), p * p * c)


class _VitBlock(BaseLayer):
    def __init__(self, width: int, heads: int, dtype):
        self.norm1 = LayerNorm(width, LayerNormConfig(), dtype)
        self.attention = ParallelSelfAttention(
            hidden_size=width, num_attention_heads=heads, causal=False, dtype=dtype,
            relative_position_embedding_type="none",
        )
        self.norm2 = LayerNorm(width, LayerNormConfig(), dtype)
        self.mlp = ParallelMLP(io_features=width, dtype=dtype)

    def init(self, key: jax.Array) -> dict:
        ks = jax.random.split(key, 4)
        return {
            "norm1": self.norm1.init(ks[0]),
            "attention": self.attention.init(ks[1]),
            "norm2": self.norm2.init(ks[2]),
            "mlp": self.mlp.init(ks[3]),
        }

    def param_metas(self) -> dict:
        return {
            "norm1": tree_prefix(self.norm1.param_metas(), "norm1"),
            "attention": tree_prefix(self.attention.param_metas(), "attention"),
            "norm2": tree_prefix(self.norm2.param_metas(), "norm2"),
            "mlp": tree_prefix(self.mlp.param_metas(), "mlp"),
        }

    def __call__(self, params: dict, x: jax.Array, ctx: ForwardContext) -> jax.Array:
        h = x + self.attention(params["attention"], self.norm1(params["norm1"], x, ctx), ctx)
        return h + self.mlp(params["mlp"], self.norm2(params["norm2"], h, ctx), ctx)


class ImageEncoder(BaseLayer):
    """(b, 384, 384, 3) image -> (b, 144, out_features) prefix tokens."""

    def __init__(
        self,
        out_features: int,
        width: int = 768,
        layers: int = 6,
        heads: int = 12,
        dropout_p: float = 0.0,
        dtype=jnp.float32,
        backbone: str = "vit",
        resnet_stages=(6, 8, 18, 8),
        resnet_channels: int = 96,
    ):
        self.out_features = out_features
        self.width = width
        self.num_layers = layers
        self.dropout_p = dropout_p
        self.dtype = dtype
        assert backbone in ("vit", "clip", "clip_resnet"), backbone
        self.backbone = backbone
        trunk_dim = width
        if backbone == "clip":
            from .clip_vision import ClipVisionEncoder

            self.clip = ClipVisionEncoder(
                width=width, layers=layers, heads=heads,
                patch_size=PATCH_SIZE, image_size=IMAGE_SIZE, dtype=dtype,
            )
        elif backbone == "clip_resnet":
            # the reference's actual trunk, ClipRN50x16 at the defaults
            # (image_encoder.py:15-29): width/layers/heads don't apply —
            # the feature dim is 8 * channels * 4 (3072 for RN50x16)
            from .clip_resnet import ClipResNetEncoder

            self.clip = ClipResNetEncoder(
                stage_blocks=tuple(resnet_stages), channels=resnet_channels,
                image_size=IMAGE_SIZE, dtype=dtype,
            )
            trunk_dim = self.clip.out_dim
        else:
            patch_dim = PATCH_SIZE * PATCH_SIZE * 3  # 3072, the reference's feature dim
            self.patch_proj = ColumnParallelLinear(
                patch_dim, width, bias=True, dtype=dtype, parallel_output=False
            )
            self.blocks = [_VitBlock(width, heads, dtype) for _ in range(layers)]
            self.out_norm = LayerNorm(width, LayerNormConfig(), dtype)
        self.proj = RowParallelLinear(trunk_dim, out_features, bias=True, dtype=dtype)
        self.final_norm = LayerNorm(out_features, LayerNormConfig(), dtype)

    def init(self, key: jax.Array) -> dict:
        ks = jax.random.split(key, self.num_layers + 4)
        params = {
            "proj": self.proj.init(ks[2]),
            "final_norm": self.final_norm.init(ks[3]),
        }
        if self.backbone in ("clip", "clip_resnet"):
            params["clip"] = self.clip.init(ks[0])
            return params
        params["patch_proj"] = self.patch_proj.init(ks[0])
        params["out_norm"] = self.out_norm.init(ks[1])
        for i, blk in enumerate(self.blocks):
            params[f"block_{i}"] = blk.init(ks[4 + i])
        return params

    def param_metas(self) -> dict:
        metas = {
            "proj": tree_prefix(self.proj.param_metas(), "image_encoder.proj"),
            "final_norm": tree_prefix(self.final_norm.param_metas(), "image_encoder.final_norm"),
        }
        if self.backbone in ("clip", "clip_resnet"):
            metas["clip"] = tree_prefix(self.clip.param_metas(), "image_encoder.clip")
            return metas
        metas["patch_proj"] = tree_prefix(self.patch_proj.param_metas(), "image_encoder.patch_proj")
        metas["out_norm"] = tree_prefix(self.out_norm.param_metas(), "image_encoder.out_norm")
        for i, blk in enumerate(self.blocks):
            metas[f"block_{i}"] = tree_prefix(blk.param_metas(), f"image_encoder.block_{i}")
        return metas

    def load_clip_weights(self, params: dict, state_dict) -> dict:
        """Return ``params`` with the CLIP trunk replaced by pretrained
        weights (the projection into the language stream stays
        trainable-fresh): huggingface ``CLIPVisionModel`` weights for the
        ViT backbone, OpenAI-CLIP-format ModifiedResNet weights for
        ``clip_resnet``."""
        if self.backbone == "clip":
            from .clip_vision import import_clip_vision_weights

            return {**params, "clip": import_clip_vision_weights(self.clip, state_dict)}
        if self.backbone == "clip_resnet":
            from .clip_resnet import import_clip_resnet_weights

            return {**params, "clip": import_clip_resnet_weights(self.clip, state_dict)}
        raise AssertionError("load_clip_weights needs a clip backbone")

    def patchify(self, images: jax.Array) -> jax.Array:
        return patchify(images, PATCH_SIZE)

    def __call__(self, params: dict, images: jax.Array, ctx: ForwardContext) -> jax.Array:
        if self.backbone in ("clip", "clip_resnet"):
            x = self.clip(params["clip"], images, ctx)
        else:
            x = self.patchify(images.astype(self.dtype))
            x = self.patch_proj(params["patch_proj"], x, ctx)
            for i, blk in enumerate(self.blocks):
                x = blk(params[f"block_{i}"], x, ctx)
            x = self.out_norm(params["out_norm"], x, ctx)
        x = self.proj(params["proj"], x, ctx)
        x = ctx.dropout(x, self.dropout_p)
        return self.final_norm(params["final_norm"], x, ctx)
