"""Pretrained CLIP ViT vision tower with torch-weight import.

The reference splices a *pretrained* CLIP backbone into the embedding
stream (reference: src/scaling/transformer/model/image_encoder/clip.py,
image_encoder.py:20-27 — RN50x16, 144 tokens from a 384x384 image). Conv
ResNet weights don't transfer to a TPU-first stack, so the pretrained
path here is the ViT family instead: this module is a faithful CLIP
ViT vision tower (CLS token, learned position embeddings, pre-norm
blocks, quick_gelu) whose parameters load from any huggingface
``CLIPVisionModel`` checkpoint via :func:`import_clip_vision_weights`,
reproducing its ``last_hidden_state`` patch tokens bit-for-tolerance.
A patch-32 checkpoint at 384x384 input yields exactly the reference's
144 prefix tokens.

The tower runs replicated (no TP) like the reference's CLIP trunk; the
trainable projection into the language stream stays in
``image_encoder.ImageEncoder``.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ...nn import BaseLayer, ForwardContext
from ...nn.param import replicated_meta, tree_prefix
from .image_encoder import patchify


def _quick_gelu(x: jax.Array) -> jax.Array:
    # CLIP's activation (hidden_act="quick_gelu"): x * sigmoid(1.702 x)
    return x * jax.nn.sigmoid(1.702 * x)


def _layernorm(p: dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * p["weight"].astype(jnp.float32) + p["bias"].astype(jnp.float32)).astype(dtype)


def _linear_init(key, d_in, d_out, dtype):
    scale = 1.0 / np.sqrt(d_in)
    kw, kb = jax.random.split(key)
    return {
        "weight": jax.random.uniform(kw, (d_in, d_out), dtype, -scale, scale),
        "bias": jax.random.uniform(kb, (d_out,), dtype, -scale, scale),
    }


def _linear_metas() -> dict:
    # leaf metas carry their own names: checkpoint keys are built by
    # prefixing, and a nameless leaf would collapse every parameter of a
    # subtree onto the same key (observed: all 16 block leaves colliding
    # to one "block_i" npz entry)
    return {
        "weight": replicated_meta(2, parameter_name="weight"),
        "bias": replicated_meta(1, parameter_name="bias"),
    }


def _norm_init(width, dtype):
    return {"weight": jnp.ones((width,), dtype), "bias": jnp.zeros((width,), dtype)}


class ClipVisionEncoder(BaseLayer):
    """(b, H, W, 3) -> (b, grid*grid, width) patch-token features, equal to
    a huggingface ``CLIPVisionModel``'s ``last_hidden_state[:, 1:]`` once
    weights are imported (the CLS row is computed, used by every attention
    layer, and dropped from the output — magma consumes spatial tokens)."""

    def __init__(
        self,
        width: int = 768,
        layers: int = 12,
        heads: int = 12,
        patch_size: int = 32,
        image_size: int = 384,
        intermediate: int | None = None,
        dtype=jnp.float32,
    ):
        assert image_size % patch_size == 0
        assert width % heads == 0
        self.width = width
        self.num_layers = layers
        self.heads = heads
        self.patch_size = patch_size
        self.image_size = image_size
        self.grid = image_size // patch_size
        self.tokens = self.grid * self.grid
        self.intermediate = intermediate or 4 * width
        self.dtype = dtype

    def init(self, key: jax.Array) -> dict:
        w, inter, dtype = self.width, self.intermediate, self.dtype
        ks = iter(jax.random.split(key, 3 + 6 * self.num_layers))
        patch_dim = self.patch_size * self.patch_size * 3
        params: dict = {
            "class_embedding": jax.random.normal(next(ks), (w,), dtype),
            # flattened conv kernel, (p*p*3, width), matching patchify order
            "patch_embedding": jax.random.normal(next(ks), (patch_dim, w), dtype)
            / np.sqrt(patch_dim),
            "position_embedding": jax.random.normal(next(ks), (1 + self.tokens, w), dtype)
            * 0.02,
            "pre_norm": _norm_init(w, dtype),
        }
        for i in range(self.num_layers):
            params[f"block_{i}"] = {
                "ln1": _norm_init(w, dtype),
                "q": _linear_init(next(ks), w, w, dtype),
                "k": _linear_init(next(ks), w, w, dtype),
                "v": _linear_init(next(ks), w, w, dtype),
                "out": _linear_init(next(ks), w, w, dtype),
                "ln2": _norm_init(w, dtype),
                "fc1": _linear_init(next(ks), w, inter, dtype),
                "fc2": _linear_init(next(ks), inter, w, dtype),
            }
        return params

    def param_metas(self) -> dict:
        def norm_metas():
            return {
                "weight": replicated_meta(
                    1, no_weight_decay=True, parameter_name="weight"
                ),
                "bias": replicated_meta(
                    1, no_weight_decay=True, parameter_name="bias"
                ),
            }

        def named(tree: dict) -> dict:
            return {k: tree_prefix(v, k) for k, v in tree.items()}

        metas: dict = {
            "class_embedding": replicated_meta(1),
            "patch_embedding": replicated_meta(2),
            "position_embedding": replicated_meta(2),
            "pre_norm": norm_metas(),
        }
        for i in range(self.num_layers):
            metas[f"block_{i}"] = named({
                "ln1": norm_metas(), "q": _linear_metas(), "k": _linear_metas(),
                "v": _linear_metas(), "out": _linear_metas(),
                "ln2": norm_metas(),
                "fc1": _linear_metas(), "fc2": _linear_metas(),
            })
        return named(metas)

    def _attn(self, p: dict, x: jax.Array) -> jax.Array:
        b, t, w = x.shape
        hd = w // self.heads

        def proj(pp, y):
            return (y @ pp["weight"] + pp["bias"]).reshape(b, t, self.heads, hd)

        q = proj(p["q"], x) * (hd ** -0.5)
        k = proj(p["k"], x)
        v = proj(p["v"], x)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, t, w)
        return o @ p["out"]["weight"] + p["out"]["bias"]

    def __call__(self, params: dict, images: jax.Array, ctx: ForwardContext) -> jax.Array:
        x = patchify(images.astype(self.dtype), self.patch_size) @ params["patch_embedding"]
        cls = jnp.broadcast_to(
            params["class_embedding"][None, None, :], (x.shape[0], 1, self.width)
        ).astype(x.dtype)
        x = jnp.concatenate([cls, x], axis=1) + params["position_embedding"][None]
        x = _layernorm(params["pre_norm"], x)
        for i in range(self.num_layers):
            p = params[f"block_{i}"]
            x = x + self._attn(p, _layernorm(p["ln1"], x))
            h = _layernorm(p["ln2"], x)
            h = _quick_gelu(h @ p["fc1"]["weight"] + p["fc1"]["bias"])
            x = x + (h @ p["fc2"]["weight"] + p["fc2"]["bias"])
        return x[:, 1:]  # drop CLS: magma consumes the spatial tokens


def import_clip_vision_weights(
    encoder: ClipVisionEncoder, state_dict: Dict[str, Any]
) -> dict:
    """Map a huggingface ``CLIPVisionModel`` state_dict onto ``encoder``'s
    param tree (reference capability: clip.py's pretrained trunk).

    Accepts keys with or without the ``vision_model.`` prefix. The conv
    patch kernel (width, 3, p, p) flattens to the patchify order
    (p, p, 3) x width; position embeddings whose grid differs from the
    encoder's are bicubic-interpolated exactly as HF's
    ``interpolate_pos_encoding`` does (torch, align_corners=False).
    ``post_layernorm`` is not imported — it only feeds CLIP's pooled CLS
    head, which the prefix-token stream never uses."""
    import torch

    sd = {k.removeprefix("vision_model."): v for k, v in state_dict.items()}

    # the encoder must MATCH the checkpoint's geometry — silently importing
    # the first N layers of a deeper tower would train on a truncated trunk
    # the user believes is the full pretrained model
    import re as _re

    ckpt_layers = 1 + max(
        (int(m.group(1)) for k in sd if (m := _re.match(r"encoder\.layers\.(\d+)\.", k))),
        default=-1,
    )
    if ckpt_layers != encoder.num_layers:
        raise ValueError(
            f"checkpoint has {ckpt_layers} encoder layers but the encoder is "
            f"configured for {encoder.num_layers} (set image_encoder_layers "
            "to the checkpoint's depth)"
        )
    ckpt_width = sd["embeddings.class_embedding"].shape[-1]
    if ckpt_width != encoder.width:
        raise ValueError(
            f"checkpoint width {ckpt_width} != encoder width {encoder.width} "
            "(set image_encoder_width to the checkpoint's hidden_size)"
        )
    ckpt_inter = sd["encoder.layers.0.mlp.fc1.weight"].shape[0]
    if ckpt_inter != encoder.intermediate:
        raise ValueError(
            f"checkpoint mlp width {ckpt_inter} != encoder intermediate "
            f"{encoder.intermediate}"
        )

    def arr(key, transpose=False):
        t = sd[key].detach().to(torch.float32)
        if transpose:
            t = t.T
        return jnp.asarray(np.asarray(t.contiguous()), encoder.dtype)

    p = encoder.patch_size
    conv = sd["embeddings.patch_embedding.weight"].detach().to(torch.float32)
    width = conv.shape[0]
    assert conv.shape == (width, 3, p, p), (
        f"checkpoint patch size {tuple(conv.shape)} != encoder patch {p}"
    )
    # (width, c, ph, pw) -> (ph, pw, c, width) -> (p*p*c, width)
    patch_w = jnp.asarray(
        np.asarray(conv.permute(2, 3, 1, 0).reshape(p * p * 3, width).contiguous()),
        encoder.dtype,
    )

    pos = sd["embeddings.position_embedding.weight"].detach().to(torch.float32)
    src_tokens = pos.shape[0] - 1
    if src_tokens != encoder.tokens:
        src_grid = int(round(np.sqrt(src_tokens)))
        assert src_grid * src_grid == src_tokens, src_tokens
        cls_pos, patch_pos = pos[:1], pos[1:]
        patch_pos = patch_pos.reshape(1, src_grid, src_grid, width).permute(0, 3, 1, 2)
        patch_pos = torch.nn.functional.interpolate(
            patch_pos, size=(encoder.grid, encoder.grid),
            mode="bicubic", align_corners=False,
        )
        patch_pos = patch_pos.permute(0, 2, 3, 1).reshape(encoder.tokens, width)
        pos = torch.cat([cls_pos, patch_pos], dim=0)
    pos_w = jnp.asarray(np.asarray(pos.contiguous()), encoder.dtype)

    def norm(prefix):
        return {"weight": arr(f"{prefix}.weight"), "bias": arr(f"{prefix}.bias")}

    def linear(prefix):
        # torch Linear stores (out, in); ours is (in, out)
        return {"weight": arr(f"{prefix}.weight", transpose=True),
                "bias": arr(f"{prefix}.bias")}

    params: dict = {
        "class_embedding": arr("embeddings.class_embedding"),
        "patch_embedding": patch_w,
        "position_embedding": pos_w,
        "pre_norm": norm("pre_layrnorm"),  # HF's historical spelling
    }
    for i in range(encoder.num_layers):
        base = f"encoder.layers.{i}"
        params[f"block_{i}"] = {
            "ln1": norm(f"{base}.layer_norm1"),
            "q": linear(f"{base}.self_attn.q_proj"),
            "k": linear(f"{base}.self_attn.k_proj"),
            "v": linear(f"{base}.self_attn.v_proj"),
            "out": linear(f"{base}.self_attn.out_proj"),
            "ln2": norm(f"{base}.layer_norm2"),
            "fc1": linear(f"{base}.mlp.fc1"),
            "fc2": linear(f"{base}.mlp.fc2"),
        }
    return params
