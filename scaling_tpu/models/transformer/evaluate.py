"""Standalone evaluation: checkpoint + tokenized dataset -> loss/perplexity.

The trainer evaluates mid-run (eval_interval); this CLI scores any saved
checkpoint against any memory-map dataset after the fact:

    python -m scaling_tpu.models.transformer.evaluate \
        --checkpoint .checkpoints/run --data data/val [--batch-size 8]

Deterministic (no shuffle, sequential packing), so two runs on the same
checkpoint and data produce the same number. Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path
from typing import Optional

import jax
import numpy as np

from .data.text_dataset import TextDataset
from .inference import TransformerInferenceModule


def evaluate(
    checkpoint_dir: Path | str,
    data_prefix: Path | str,
    batch_size: int = 8,
    max_batches: Optional[int] = None,
    legacy_dataset: bool = False,
) -> dict:
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    module = TransformerInferenceModule.from_checkpoint(checkpoint_dir)
    arch = module.architecture
    dataset = TextDataset(
        data_prefix,
        sequence_length=arch.sequence_length,
        shuffle=False,
        legacy_dataset=legacy_dataset,
    )
    if len(dataset) == 0:
        # a perfect-looking zero score for nothing evaluated misleads any
        # consumer of the JSON — refuse instead
        raise ValueError(
            f"{data_prefix} packs into 0 sequences of length "
            f"{arch.sequence_length} (wrong prefix or dataset too small)"
        )

    fwd = None
    total_loss = total_weight = total_correct = 0.0
    n_batches = math.ceil(len(dataset) / batch_size)
    if max_batches is not None:
        n_batches = min(n_batches, max_batches)
    for b in range(n_batches):
        items = [
            dataset[i]
            for i in range(b * batch_size, min((b + 1) * batch_size, len(dataset)))
        ]
        batch = dataset.collate(items).as_model_input()
        if len(items) < batch_size:
            # pad the trailing batch to the jitted shape; padding rows carry
            # zero loss weight so they never contribute
            pad = batch_size - len(items)
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)], axis=0)
                if hasattr(v, "ndim") and v.ndim > 0
                else v
                for k, v in batch.items()
            }
            batch["loss_weights"][-pad:] = 0.0
        if fwd is None:

            def run(params, batch):
                from .model import per_token_loss

                ctx = module.module._make_ctx(deterministic=True, dropout_key=None)
                out = module.module.forward(params, batch, ctx)
                # weighted SUMS (not the training loss_function's means):
                # batches of unequal live-token counts aggregate exactly
                token_loss, correct = per_token_loss(
                    out["activations"], batch["target_token_ids"]
                )
                weights = batch["loss_weights"].astype("float32")
                return (
                    (token_loss * weights).sum(),
                    (correct * weights).sum(),
                    weights.sum(),
                )

            fwd = jax.jit(run)
        loss_sum, correct_sum, weight_sum = fwd(module.params, batch)
        total_loss += float(loss_sum)
        total_correct += float(correct_sum)
        total_weight += float(weight_sum)

    mean_loss = total_loss / max(total_weight, 1.0)
    return {
        "loss": round(mean_loss, 6),
        "perplexity": round(math.exp(min(mean_loss, 80.0)), 4),
        "accuracy": round(total_correct / max(total_weight, 1.0), 6),
        "tokens": int(total_weight),
        "batches": n_batches,
    }


def main(argv: Optional[list] = None) -> None:
    ap = argparse.ArgumentParser(description="score a checkpoint on a dataset")
    ap.add_argument("--checkpoint", required=True, type=Path)
    ap.add_argument("--data", required=True, type=Path,
                    help="memory-map dataset prefix")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--max-batches", type=int, default=None)
    ap.add_argument("--legacy-dataset", action="store_true",
                    help="Megatron .bin/.idx format")
    args = ap.parse_args(argv)
    stats = evaluate(args.checkpoint, args.data, args.batch_size,
                     args.max_batches, args.legacy_dataset)
    print(json.dumps({"checkpoint": str(args.checkpoint), **stats}))


if __name__ == "__main__":
    sys.exit(main())
