"""Transformer context (reference: src/scaling/transformer/context/context.py:6-15)."""

from __future__ import annotations

from ...context import BaseContext


class TransformerContext(BaseContext):
    pass
