"""HF-tokenizers wrapper.

(reference: src/scaling/transformer/tokenizer/tokenizer.py:7-103) — eos
detection, encode/decode, and the (normal, no-prefix-space) pair used by
finetuning chat templating.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Tuple

EOS_CANDIDATES = ("<|endoftext|>", "</s>")


class Tokenizer:
    def __init__(self, tokenizer) -> None:
        self.tokenizer = tokenizer
        self.eos_token = None
        self.eos_token_id: Optional[int] = None
        for candidate in EOS_CANDIDATES:
            token_id = self.tokenizer.token_to_id(candidate)
            if token_id is not None:
                self.eos_token = candidate
                self.eos_token_id = token_id
                break

    @classmethod
    def from_file(cls, vocab_file: Path | str) -> "Tokenizer":
        from tokenizers import Tokenizer as HFTokenizer

        return cls(HFTokenizer.from_file(str(vocab_file)))

    @property
    def vocab_size(self) -> int:
        return self.tokenizer.get_vocab_size()

    def encode(self, text: str) -> List[int]:
        return self.tokenizer.encode(text, add_special_tokens=False).ids

    def decode(self, token_ids: List[int]) -> str:
        return self.tokenizer.decode(list(token_ids), skip_special_tokens=False)

    def token_to_id(self, token: str) -> Optional[int]:
        return self.tokenizer.token_to_id(token)


def load_tokenizers(vocab_file: Path | str) -> Tuple[Tokenizer, Tokenizer]:
    """(normal, no-prefix-space) pair; llama2-style tokenizer jsons get the
    prefix-space surgery of the reference (tokenizer.py:64-103)."""
    tokenizer = Tokenizer.from_file(vocab_file)

    data = json.loads(Path(vocab_file).read_text())
    changed = False
    decoder = data.get("decoder") or {}
    for entry in decoder.get("decoders", []) if decoder else []:
        if entry.get("type") == "Metaspace" and entry.get("add_prefix_space", True):
            entry["add_prefix_space"] = False
            changed = True
    pre = data.get("pre_tokenizer") or {}
    candidates = [pre] + list(pre.get("pretokenizers", []) or [])
    for entry in candidates:
        if entry.get("type") == "Metaspace" and entry.get("add_prefix_space", True):
            entry["add_prefix_space"] = False
            changed = True

    if changed:
        from tokenizers import Tokenizer as HFTokenizer

        no_prefix = Tokenizer(HFTokenizer.from_str(json.dumps(data)))
    else:
        no_prefix = tokenizer
    return tokenizer, no_prefix
