"""HF-tokenizers wrapper.

(reference: src/scaling/transformer/tokenizer/tokenizer.py:7-103) — eos
detection, encode/decode, and the (normal, no-prefix-space) pair used by
finetuning chat templating.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional, Tuple

EOS_CANDIDATES = ("<|endoftext|>", "</s>")


class Tokenizer:
    def __init__(self, tokenizer) -> None:
        self.tokenizer = tokenizer
        self.eos_token = None
        self.eos_token_id: Optional[int] = None
        for candidate in EOS_CANDIDATES:
            token_id = self.tokenizer.token_to_id(candidate)
            if token_id is not None:
                self.eos_token = candidate
                self.eos_token_id = token_id
                break

    @classmethod
    def from_file(cls, vocab_file: Path | str) -> "Tokenizer":
        from tokenizers import Tokenizer as HFTokenizer

        try:
            return cls(HFTokenizer.from_file(str(vocab_file)))
        except Exception as e:
            # the rust parser's bare "expected `,` or `}` at line 1" gives
            # no hint WHAT format was expected or WHICH file failed
            raise ValueError(
                f"{vocab_file} is not a serialized huggingface tokenizer "
                f"(tokenizer.json format, as written by "
                f"tokenizers.Tokenizer.save or shipped with hf models); "
                f"a bare vocab map is not loadable ({e})"
            ) from e

    @classmethod
    def from_str(cls, json_str: str) -> "Tokenizer":
        """Build from a serialized tokenizer json (reference: tokenizer.py:28)."""
        from tokenizers import Tokenizer as HFTokenizer

        return cls(HFTokenizer.from_str(json_str))

    @classmethod
    def default(cls) -> "Tokenizer":
        """A functional byte-level fallback tokenizer (256 byte tokens +
        ``<|endoftext|>``). The reference ships a llama2 tokenizer json for
        this (tokenizer.py:33-38); building one programmatically avoids
        bundling a model asset while keeping ``default()`` usable."""
        from tokenizers import Tokenizer as HFTokenizer
        from tokenizers.decoders import ByteLevel as ByteLevelDecoder
        from tokenizers.models import BPE
        from tokenizers.pre_tokenizers import ByteLevel

        alphabet = ByteLevel.alphabet()
        vocab = {ch: i for i, ch in enumerate(sorted(alphabet))}
        vocab["<|endoftext|>"] = len(vocab)
        tok = HFTokenizer(BPE(vocab, merges=[]))
        tok.pre_tokenizer = ByteLevel(add_prefix_space=False)
        tok.decoder = ByteLevelDecoder()
        # registered as special so the literal text "<|endoftext|>" encodes
        # to the single eos id instead of byte tokens (id unchanged: it is
        # already in the vocab)
        tok.add_special_tokens(["<|endoftext|>"])
        return cls(tok)

    def __len__(self) -> int:
        return self.tokenizer.get_vocab_size()

    @property
    def vocab_size(self) -> int:
        return self.tokenizer.get_vocab_size()

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        return self.tokenizer.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, token_ids: List[int], skip_special_tokens: bool = False) -> str:
        return self.tokenizer.decode(
            list(token_ids), skip_special_tokens=skip_special_tokens
        )

    def token_to_id(self, token: str) -> Optional[int]:
        return self.tokenizer.token_to_id(token)


def load_tokenizers(vocab_file: Path | str) -> Tuple[Tokenizer, Tokenizer]:
    """(normal, no-prefix-space) pair; llama2-style tokenizer jsons get the
    prefix-space surgery of the reference (tokenizer.py:64-103)."""
    tokenizer = Tokenizer.from_file(vocab_file)

    data = json.loads(Path(vocab_file).read_text())
    changed = False

    def strip_prefix(entry: dict) -> bool:
        if entry.get("type") != "Metaspace":
            return False
        touched = False
        # modern tokenizers serialize prepend_scheme; legacy files carry
        # add_prefix_space — the two must stay consistent or from_str rejects
        if entry.get("prepend_scheme", "always") != "never":
            entry["prepend_scheme"] = "never"
            touched = True
        if entry.get("add_prefix_space", True):
            entry["add_prefix_space"] = False
            touched = True
        return touched

    decoder = data.get("decoder") or {}
    for entry in decoder.get("decoders", []) if decoder else []:
        changed |= strip_prefix(entry)
    pre = data.get("pre_tokenizer") or {}
    for entry in [pre] + list(pre.get("pretokenizers", []) or []):
        changed |= strip_prefix(entry)

    if changed:
        from tokenizers import Tokenizer as HFTokenizer

        no_prefix = Tokenizer(HFTokenizer.from_str(json.dumps(data)))
    else:
        no_prefix = tokenizer
    return tokenizer, no_prefix
