"""Pretraining text dataset: packed fixed-length windows over mmap docs.

(reference: src/scaling/transformer/data/text_dataset.py:26-462) — token
documents in a MemoryMapDataset are packed into items of
``sequence_length + 1`` tokens (input/target shifted by one). Packing state
(doc, start, end spans) is a deterministic pure function of the dataset +
sequence length; the reference caches it to disk built by rank 0 with a
``.done`` poll — here every process computes the identical index (numpy
prefix sums, fast) and an optional cache file removes even that cost.

The EOD-token resets of the reference's ``cumulative_seq_lengths``
(data/utils.py:40-75) become segment ids — the TPU-native packing
representation consumed by attention masks and Pallas kernels alike.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional

import numpy as np

from ....data import BaseDataset, BaseDatasetBatch, BaseDatasetItem
from ....data.blended_dataset import BaseBlendedDataset
from ....data.memory_map import MemoryMapDataset
from ....nn.seq_packing import get_position_ids_from_segments, get_segment_ids


@dataclass
class TextDatasetItem(BaseDatasetItem):
    token_ids: np.ndarray  # (seq_len + 1,)


class TextDatasetBatch(BaseDatasetBatch):
    """Batch pytree (reference: text_dataset_batch.py:29-140)."""

    def __init__(
        self,
        token_ids: np.ndarray,  # (b, s) inputs
        target_token_ids: np.ndarray,  # (b, s)
        position_ids: np.ndarray,
        segment_ids: np.ndarray,
        loss_weights: np.ndarray,
        input_images: "np.ndarray | None" = None,  # (b, n_img, H, W, 3)
        input_image_locations: "np.ndarray | None" = None,  # (b, n_img) starts
        input_image_mask: "np.ndarray | None" = None,  # (b, n_img) validity
    ):
        self.token_ids = token_ids
        self.target_token_ids = target_token_ids
        self.position_ids = position_ids
        self.segment_ids = segment_ids
        self.loss_weights = loss_weights
        self.input_images = input_images
        self.input_image_locations = input_image_locations
        self.input_image_mask = input_image_mask

    def as_model_input(self) -> dict:
        out = {
            "token_ids": self.token_ids,
            "target_token_ids": self.target_token_ids,
            "position_ids": self.position_ids,
            "segment_ids": self.segment_ids,
            "loss_weights": self.loss_weights,
        }
        if self.input_images is not None:
            out["input_images"] = self.input_images
            out["input_image_locations"] = self.input_image_locations
            out["input_image_mask"] = self.input_image_mask
        return out

    def only_inputs(self) -> "TextDatasetBatch":
        return self

    def only_targets(self) -> "TextDatasetBatch":
        return self


class TextDataset(BaseDataset[TextDatasetItem, TextDatasetBatch]):
    def __init__(
        self,
        data_prefix: Path | str,
        sequence_length: int,
        seed: int = 42,
        shuffle: bool = True,
        eod_token_id: int = 0,
        only_full_sequences: bool = False,
        allow_incomplete_sequences_every_n: int = 0,
        load_index_to_memory: bool = True,
        legacy_dataset: bool = False,
    ):
        self.data_prefix = Path(data_prefix)
        self.sequence_length = sequence_length
        self.eod_token_id = eod_token_id
        self.only_full_sequences = only_full_sequences
        self.allow_incomplete_sequences_every_n = allow_incomplete_sequences_every_n
        if legacy_dataset:
            # Megatron .bin/.idx data packs through the same index; the store
            # interfaces are identical (reference: legacy_dataset/)
            from ....data.legacy_indexed_dataset import LegacyIndexedDataset

            self.memory_map = LegacyIndexedDataset(
                self.data_prefix, load_index_to_memory=load_index_to_memory
            )
        else:
            self.memory_map = MemoryMapDataset(
                self.data_prefix, load_index_to_memory=load_index_to_memory
            )
        self._build_pack_index()
        super().__init__(seed=seed, shuffle=shuffle)

    # ------------------------------------------------------------ packing
    def _build_pack_index(self) -> None:
        """Item i covers tokens [i*L, i*L + L + 1) of the concatenated doc
        stream, L = sequence_length. With only_full_sequences, items are
        aligned to document starts instead (reference:
        text_dataset.py:130-300)."""
        sizes = self.memory_map.sizes().astype(np.int64)
        total_tokens = int(sizes.sum())
        L = self.sequence_length
        if not self.only_full_sequences:
            self._num_items = max((total_tokens - 1) // L, 0)
            self._item_starts = None
            self._item_ends = None
        elif (native := self._native_spans(sizes)) is not None:
            self._item_starts, self._item_ends = native
            self._num_items = len(self._item_starts)
        else:
            # greedy packing of whole documents into [start, end) windows
            # (Python fallback for the C++ builder in scaling_tpu.native)
            spans: List[tuple] = []
            doc_offsets = np.concatenate([[0], np.cumsum(sizes)])
            window_start = 0
            since_cut = 0
            every_n = self.allow_incomplete_sequences_every_n
            for d in range(len(sizes)):
                doc_start = int(doc_offsets[d])
                doc_end = int(doc_offsets[d + 1])
                if doc_end - window_start <= L:
                    continue  # doc fits into the open window
                if every_n > 0 and since_cut + 1 >= every_n:
                    # the every-n exception: cut mid-document. Windows span
                    # L+1 tokens with a 1-token overlap so the boundary token
                    # is target of one window and first input of the next —
                    # no EOD padding mid-document
                    while doc_end - window_start > L:
                        spans.append((window_start, window_start + L + 1))
                        window_start += L
                    since_cut = 0
                    continue
                # close the open window at this doc's boundary
                if doc_start > window_start:
                    spans.append((window_start, doc_start))
                    since_cut += 1
                window_start = doc_start
                if doc_end - window_start > L:
                    # over-long document: emit full L+1-token windows (same
                    # 1-token overlap); the <L-token tail is dropped so the
                    # next window realigns to a doc boundary
                    while doc_end - window_start > L:
                        spans.append((window_start, window_start + L + 1))
                        window_start += L
                        since_cut = 0
                    window_start = doc_end
            if total_tokens - window_start >= 2:
                spans.append((window_start, total_tokens))
            spans = [(s, e) for s, e in spans if e - s >= 2 and s + 2 <= total_tokens]
            self._item_starts = np.asarray([s for s, _ in spans], dtype=np.int64)
            self._item_ends = np.asarray([e for _, e in spans], dtype=np.int64)
            self._num_items = len(self._item_starts)
        self._total_tokens = total_tokens

    def _native_spans(self, sizes: np.ndarray):
        """C++ pack-index builder; None -> use the Python loop."""
        from ....native import build_pack_index

        return build_pack_index(
            sizes, self.sequence_length, self.allow_incomplete_sequences_every_n
        )

    def set_seed(self, seed: int, shuffle: bool = True) -> None:
        # item order is owned by the DP-strided RandomSampler; the dataset
        # itself is deterministic given the mmap + sequence length
        self.seed = seed
        self.shuffle = shuffle

    def ident(self) -> str:
        h = hashlib.md5(
            f"{self.data_prefix}-{self.sequence_length}-{self.only_full_sequences}".encode()
        ).hexdigest()
        return f"text-{h}"

    def __len__(self) -> int:
        return self._num_items

    def __getitem__(self, index: int) -> TextDatasetItem:
        L = self.sequence_length
        if self._item_starts is None:
            start = index * L
            n = min(L + 1, self._total_tokens - start)
        else:
            # read only this window's documents; EOD-pad the remainder so no
            # partial next-document head leaks in (and no token is trained
            # twice across adjacent windows)
            start = int(self._item_starts[index])
            n = min(L + 1, int(self._item_ends[index]) - start)
        tokens = self.memory_map.read_span(start, n)
        if n < L + 1:
            tokens = np.concatenate(
                [tokens, np.full(L + 1 - n, self.eod_token_id, dtype=tokens.dtype)]
            )
        return TextDatasetItem(token_ids=tokens.astype(np.int64))

    # ------------------------------------------------------------ collate
    def collate(self, batch: List[TextDatasetItem]) -> TextDatasetBatch:
        tokens = np.stack([item.token_ids for item in batch])  # (b, L+1)
        inputs = tokens[:, :-1]
        targets = tokens[:, 1:]
        segment_ids = get_segment_ids(inputs, self.eod_token_id)
        position_ids = get_position_ids_from_segments(segment_ids)
        # weight every real token incl. the EOD prediction; zero only inside
        # padding runs where input and target are both EOD
        # (reference: text_dataset_batch.py:106-140)
        loss_weights = np.maximum(
            (targets != self.eod_token_id).astype(np.float32),
            (inputs != self.eod_token_id).astype(np.float32),
        )
        return TextDatasetBatch(
            token_ids=inputs.astype(np.int32),
            target_token_ids=targets.astype(np.int32),
            position_ids=position_ids.astype(np.int32),
            segment_ids=segment_ids.astype(np.int32),
            loss_weights=loss_weights,
        )


class LegacyBlendedDataset(BaseBlendedDataset):
    """Blend of legacy (Megatron .bin/.idx) TextDatasets
    (reference: legacy_blended_dataset.py:22-282).

    The reference class re-implements weighting + a Megatron-format index
    cache; here both already live in BaseBlendedDataset (same
    furthest-off-target interleave, same weights_by_num_docs /
    weights_examples_proportional formulas, file-cached index), so this is
    the named entry point used when ``data.legacy_dataset`` is set.
    """


class TextBlendedDataset(BaseBlendedDataset):
    """Weighted blend over TextDatasets (reference: text_dataset.py tail)."""
