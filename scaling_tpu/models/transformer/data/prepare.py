"""Offline dataset preparation: text/jsonl -> tokenized memory map.

The reference consumes pre-tokenized ``.bin/.idx/.meta.json`` memory maps
but ships no tool to produce them; this CLI closes that gap. Each input
document is tokenized, EOS-terminated (the EOD boundary the packed
TextDataset splits on, data/text_dataset.py), and appended to a
``MemoryMapDatasetBuilder`` stream:

    python -m scaling_tpu.models.transformer.data.prepare \
        --input docs.jsonl --vocab tokenizer.json --output data/train

Input formats (by extension): ``.jsonl`` with a text field per line
(``--field``, default "text"), or plain ``.txt`` with one document per
line. The token dtype sizes itself to the tokenizer vocabulary.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Iterator

import numpy as np

from ....data.memory_map import MemoryMapDatasetBuilder
from ..tokenizer import Tokenizer


def iter_documents(path: Path, field: str) -> Iterator[str]:
    if path.suffix in (".jsonl", ".ndjson"):
        for line_no, line in enumerate(path.open(), 1):
            if not line.strip():
                continue
            record = json.loads(line)
            if not isinstance(record, dict):
                raise ValueError(
                    f"{path}:{line_no} is a JSON {type(record).__name__}, "
                    "not an object — this tool consumes pretraining jsonl "
                    "({'text': ...} per line); chat finetuning jsonl (a "
                    "list per line) is read directly by the chat dataset"
                )
            if field not in record:
                raise KeyError(
                    f"{path}:{line_no} has no {field!r} field "
                    f"(keys: {sorted(record)}; set --field)"
                )
            yield record[field]
    elif path.suffix in (".txt", ".text"):
        for line in path.open():
            if line.strip():
                yield line.rstrip("\n")
    else:
        # an explicit error beats tokenizing raw JSON (or gzip bytes) as
        # document text and writing a silently-corrupt dataset
        raise ValueError(
            f"unsupported input extension {path.suffix!r} for {path}: "
            "expected .jsonl/.ndjson (one JSON object per line) or "
            ".txt/.text (one document per line); decompress .gz first"
        )


def prepare(
    inputs: list[Path],
    vocab_file: Path,
    output_prefix: Path,
    field: str = "text",
    append_eos: bool = True,
) -> dict:
    tokenizer = Tokenizer.from_file(vocab_file)
    eos = tokenizer.eos_token_id
    if append_eos and eos is None:
        raise ValueError(
            f"{vocab_file} has no EOS token; pass --no-append-eos to pack "
            "documents without EOD boundaries"
        )
    dtype = np.uint16 if len(tokenizer) < 2**16 else np.uint32
    docs = tokens = 0
    with MemoryMapDatasetBuilder(output_prefix, dtype=dtype) as builder:
        for path in inputs:
            for text in iter_documents(path, field):
                ids = tokenizer.encode(text)
                if not ids:
                    continue
                if append_eos:
                    ids = ids + [eos]
                builder.add(np.asarray(ids, dtype=dtype))
                docs += 1
                tokens += len(ids)
    return {"documents": docs, "tokens": tokens, "dtype": str(np.dtype(dtype))}


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="tokenize documents into a training memory map"
    )
    ap.add_argument("--input", nargs="+", required=True, type=Path,
                    help=".jsonl or .txt document files")
    ap.add_argument("--vocab", required=True, type=Path,
                    help="HF-tokenizers json")
    ap.add_argument("--output", required=True, type=Path,
                    help="output prefix for .bin/.idx/.meta.json")
    ap.add_argument("--field", default="text",
                    help="jsonl field holding the document text")
    ap.add_argument("--no-append-eos", dest="append_eos", action="store_false",
                    help="do not append EOS after each document")
    args = ap.parse_args(argv)
    stats = prepare(args.input, args.vocab, args.output, args.field,
                    args.append_eos)
    print(json.dumps({"output": str(args.output), **stats}))


if __name__ == "__main__":
    sys.exit(main())
