"""Finetuning datasets: prompt/completion and chat.

(reference: src/scaling/transformer/data/finetuning_text_dataset.py:59-218,
finetuning_chat_dataset.py:27-355). Same on-disk formats so existing data
works unchanged:

- text jsonl: ``{"prompt": str, "completion": str}`` per line (prompt may be
  a list of strings; image entries are not yet supported on TPU)
- text mmap: each record ``[len_prompt, prompt..., completion...]``
- chat jsonl: each line a LIST of ``{"type": "text", "content": str,
  "has_loss": bool}`` elements; tokens of has_loss elements are trained

Loss masking (reference: finetuning_text_dataset.py:192-198): weight 0 on
prompt tokens and padding, 1 on completion tokens + the closing EOS. Items
are padded to ``sequence_length`` with EOS; over-long items are truncated
from the front of the prompt so the completion survives.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from ....data.base_dataset import BaseDataset
from ....logging import logger
from ....data.blended_dataset import BaseBlendedDataset
from ....data.memory_map import MemoryMapDataset
from ..tokenizer import Tokenizer, load_tokenizers
from .text_dataset import TextDatasetBatch
from ....nn.seq_packing import get_position_ids_from_segments, get_segment_ids


IMAGE_ENCODER_TOKEN_COUNT = 144  # 384/32 patches squared (image_encoder.py)
IMAGE_SIZE = 384
# CLIP preprocessing constants (reference: finetuning_chat_dataset.py:24
# clip_transform); kept so data pipelines transfer unchanged
_IMAGE_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
_IMAGE_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


def load_image(path: Path) -> np.ndarray:
    """Image file -> normalized (H, W, 3) float32, CLIP-style preprocessing."""
    from PIL import Image

    img = Image.open(str(path)).convert("RGB")
    img = img.resize((IMAGE_SIZE, IMAGE_SIZE), Image.BICUBIC)
    arr = np.asarray(img, dtype=np.float32) / 255.0
    return (arr - _IMAGE_MEAN) / _IMAGE_STD


class FinetuningItem:
    __slots__ = ("token_ids", "target_token_ids", "loss_weights", "images",
                 "image_locations")

    def __init__(self, token_ids, target_token_ids, loss_weights,
                 images=None, image_locations=None):
        self.token_ids = token_ids
        self.target_token_ids = target_token_ids
        self.loss_weights = loss_weights
        self.images = images  # list of (H, W, 3) arrays or None
        self.image_locations = image_locations  # list of start positions


class _FinetuningBase(BaseDataset):
    """Shared item assembly + collate for both finetuning datasets."""

    #: fixed image-slot count for every batch this dataset produces; padding
    #: to a dataset-level constant (not the per-batch max) keeps the jitted
    #: train step's input signature stable across batches — no recompiles
    max_images: int = 0

    def __init__(self, sequence_length: int, eod_token_id: int,
                 seed: int = 42, shuffle: bool = True):
        self.sequence_length = sequence_length
        self.eod_token_id = eod_token_id
        super().__init__(seed=seed, shuffle=shuffle)

    def set_seed(self, seed: int, shuffle: bool = True) -> None:
        # item order is owned by the DP-strided RandomSampler (the reference
        # shuffles in-place, finetuning_text_dataset.py:127-144; our loader
        # derives order from the seed instead)
        self.seed = seed
        self.shuffle = shuffle

    def _assemble(
        self, input_ids: List[int], target_ids: List[int], loss_mask: List[int],
        truncate: str = "front", images=None, image_locations=None,
    ) -> FinetuningItem:
        """``truncate='front'`` keeps the tail (the trained completion lives
        there — text finetuning); ``'back'`` keeps the head like the
        reference chat dataset (finetuning_chat_dataset.py:208-216), which
        keeps recorded image splice locations valid."""
        L = self.sequence_length
        if len(input_ids) > L:
            if truncate == "front":
                input_ids = input_ids[-L:]
                target_ids = target_ids[-L:]
                loss_mask = loss_mask[-L:]
            else:
                input_ids = input_ids[:L]
                target_ids = target_ids[:L]
                loss_mask = loss_mask[:L]
        if image_locations is not None:
            # drop any image whose 144-token span no longer fits: truncation
            # can cut it, and a trailing image loses its last placeholder to
            # the target shift (a partial splice would overwrite real tokens)
            keep = [
                i for i, st in enumerate(image_locations)
                if st + IMAGE_ENCODER_TOKEN_COUNT <= len(input_ids)
            ]
            images = [images[i] for i in keep]
            image_locations = [image_locations[i] for i in keep]
        pad = L - len(input_ids)
        eod = self.eod_token_id
        token_ids = np.asarray(input_ids + [eod] * pad, dtype=np.int64)
        target = np.asarray(target_ids + [eod] * pad, dtype=np.int64)
        weights = np.asarray(loss_mask + [0] * pad, dtype=np.float32)
        return FinetuningItem(token_ids, target, weights, images, image_locations)

    def collate(self, batch: List[FinetuningItem]) -> TextDatasetBatch:
        tokens = np.stack([b.token_ids for b in batch])
        targets = np.stack([b.target_token_ids for b in batch])
        weights = np.stack([b.loss_weights for b in batch])
        # one document per item: positions count up, padding masked by weight
        segment_ids = np.zeros(tokens.shape, dtype=np.int32)
        position_ids = np.broadcast_to(
            np.arange(tokens.shape[1], dtype=np.int32), tokens.shape
        ).copy()
        out = TextDatasetBatch(
            token_ids=tokens.astype(np.int32),
            target_token_ids=targets.astype(np.int32),
            position_ids=position_ids,
            segment_ids=segment_ids,
            loss_weights=weights,
        )
        n_img = self.max_images
        if n_img > 0:
            b_sz = len(batch)
            imgs = np.zeros((b_sz, n_img, IMAGE_SIZE, IMAGE_SIZE, 3), np.float32)
            locs = np.zeros((b_sz, n_img), np.int32)
            mask = np.zeros((b_sz, n_img), bool)
            for i, item in enumerate(batch):
                for j, (img, st) in enumerate(
                    zip(item.images or [], item.image_locations or [])
                ):
                    imgs[i, j] = img
                    locs[i, j] = st
                    mask[i, j] = True
            out.input_images = imgs
            out.input_image_locations = locs
            out.input_image_mask = mask
        return out


class FinetuningTextDataset(_FinetuningBase):
    """Prompt/completion pairs from jsonl or a memory map
    (reference: finetuning_text_dataset.py:59-218)."""

    def __init__(
        self,
        data_prefix: Path | str,
        sequence_length: int,
        vocab_file: Path | str,
        seed: int = 42,
        shuffle: bool = True,
        memory_map_dataset: bool = False,
        softprompt_n_tokens: int = 0,
    ):
        self.data_prefix = Path(data_prefix)
        self.vocab_file = Path(vocab_file)
        self.tokenizer, self.tokenizer_no_prefix_space = load_tokenizers(self.vocab_file)
        self.memory_map_dataset = memory_map_dataset
        self.softprompt_n_tokens = softprompt_n_tokens
        if memory_map_dataset:
            self.mmap: Optional[MemoryMapDataset] = MemoryMapDataset(self.data_prefix)
            self._records: List[Any] = list(range(len(self.mmap)))
        else:
            self.mmap = None
            path = self.data_prefix
            if path.suffix != ".jsonl" and not path.exists():
                path = path.with_suffix(".jsonl")
            self._records = [
                json.loads(line)
                for line in Path(path).read_text().splitlines()
                if line.strip()
            ]
        super().__init__(sequence_length, self.tokenizer.eos_token_id or 0,
                         seed=seed, shuffle=shuffle)

    def ident(self) -> str:
        h = hashlib.md5(
            f"{self.data_prefix}-{self.sequence_length}-{self.vocab_file}".encode()
        ).hexdigest()
        return f"finetune-text-{h}"

    def __len__(self) -> int:
        return len(self._records)

    def _token_ids(self, index: int) -> tuple[List[int], List[int]]:
        if self.mmap is not None:
            rec = np.asarray(self.mmap[self._records[index]]).tolist()
            n_prompt = int(rec[0])
            return rec[1 : n_prompt + 1], rec[n_prompt + 1 :]
        item = self._records[index]
        prompt = item["prompt"]
        if isinstance(prompt, list):
            prompt_ids: List[int] = []
            for i, p in enumerate(prompt):
                if not isinstance(p, str):
                    raise NotImplementedError(
                        "image prompt entries need the image encoder "
                        "(transformer_architecture.image_encoder)"
                    )
                tok = self.tokenizer if i == 0 else self.tokenizer_no_prefix_space
                prompt_ids.extend(tok.encode(p))
        else:
            prompt_ids = self.tokenizer.encode(prompt)
        completion_ids = self.tokenizer_no_prefix_space.encode(item["completion"])
        return prompt_ids, completion_ids

    def __getitem__(self, index: int) -> FinetuningItem:
        eos = self.eod_token_id
        prompt_ids, completion_ids = self._token_ids(index)
        if self.softprompt_n_tokens > 0:
            # placeholder ids the softprompt layer overwrites in-embedding
            # (reference: finetuning_text_dataset.py:165-175)
            prompt_ids = [0] * self.softprompt_n_tokens + prompt_ids
        stream = prompt_ids + completion_ids + [eos]
        input_ids = stream[:-1]
        target_ids = stream[1:]
        # predict completion + eos; the last prompt token predicts the first
        # completion token, so weights start at len(prompt) - 1
        loss_mask = [0] * (len(prompt_ids) - 1) + [1] * (len(completion_ids) + 1)
        return self._assemble(input_ids, target_ids, loss_mask)


class FinetuningChatDataset(_FinetuningBase):
    """Chat transcripts with per-element loss flags
    (reference: finetuning_chat_dataset.py:27-241)."""

    def __init__(
        self,
        data_prefix: Path | str,
        sequence_length: int,
        vocab_file: Path | str,
        seed: int = 42,
        shuffle: bool = True,
        softprompt_n_tokens: int = 0,
    ):
        self.data_prefix = Path(data_prefix)
        self.vocab_file = Path(vocab_file)
        self.softprompt_n_tokens = softprompt_n_tokens
        self.tokenizer, self.tokenizer_no_prefix_space = load_tokenizers(self.vocab_file)
        path = self.data_prefix
        if path.suffix != ".jsonl" and not path.exists():
            path = path.with_suffix(".jsonl")
        self._samples: List[Dict[str, Any]] = []
        eos = self.tokenizer.eos_token_id
        missing_eos = 0
        for line in Path(path).read_text().splitlines():
            if not line.strip():
                continue
            elements = json.loads(line)
            tokens: List[int] = []
            mask: List[int] = []
            image_paths: List[Path] = []
            image_locations: List[int] = []
            first_text = True
            has_text_eos = False
            for el in elements:
                if el["type"] == "text":
                    tok = self.tokenizer if first_text else self.tokenizer_no_prefix_space
                    ids = tok.encode(el["content"])
                    tokens.extend(ids)
                    mask.extend([int(bool(el.get("has_loss", False)))] * len(ids))
                    first_text = False
                    has_text_eos = has_text_eos or (eos is not None and eos in ids)
                elif el["type"] == "image":
                    # 144 placeholder tokens the embedding layer overwrites
                    # with the encoded image (reference:
                    # finetuning_chat_dataset.py:120-134)
                    image_paths.append(self.data_path_parent / el["content"])
                    image_locations.append(len(tokens))
                    tokens.extend([eos or 0] * IMAGE_ENCODER_TOKEN_COUNT)
                    mask.extend([0] * IMAGE_ENCODER_TOKEN_COUNT)
                else:
                    raise NotImplementedError(
                        f"chat content type {el['type']!r} is not supported"
                    )
            # the chat format carries its own EOS (reference warns, we do
            # too); image placeholders reuse the eos id, so only text
            # elements count
            if eos is not None and not has_text_eos:
                missing_eos += 1
            self._samples.append(
                {
                    "input": tokens[:-1],
                    "target": tokens[1:],
                    "mask": mask[1:],
                    "image_paths": image_paths,
                    "image_locations": image_locations,
                }
            )
        if missing_eos:
            logger.warning(
                f"finetuning_chat_dataset does not add EOS automatically; "
                f"{missing_eos}/{len(self._samples)} samples in {path} carry "
                f"no EOS token — append it in your data.jsonl if completions "
                f"should terminate"
            )
        self.max_images = max(
            (len(s["image_paths"]) for s in self._samples), default=0
        )
        super().__init__(sequence_length, eos or 0, seed=seed, shuffle=shuffle)

    @property
    def data_path_parent(self) -> Path:
        return self.data_prefix.parent

    def ident(self) -> str:
        h = hashlib.md5(
            f"{self.data_prefix}-{self.sequence_length}-{self.vocab_file}".encode()
        ).hexdigest()
        return f"finetune-chat-{h}"

    def __len__(self) -> int:
        return len(self._samples)

    def __getitem__(self, index: int) -> FinetuningItem:
        s = self._samples[index]
        inputs = list(s["input"])
        targets = list(s["target"])
        mask = list(s["mask"])
        locations = list(s["image_locations"])
        n_sp = self.softprompt_n_tokens
        if n_sp > 0:
            # placeholder ids the softprompt layer overwrites in-embedding;
            # prepended after the target shift like the reference
            # (finetuning_chat_dataset.py:191-206)
            inputs = [0] * n_sp + inputs
            targets = [0] * n_sp + targets
            mask = [0] * n_sp + mask
            locations = [st + n_sp for st in locations]
        images = [load_image(p) for p in s["image_paths"]] or None
        return self._assemble(
            inputs, targets, mask,
            truncate="back",  # keep the head so image locations stay valid
            images=images,
            image_locations=locations if images else None,
        )


class FinetuningTextBlendedDataset(BaseBlendedDataset):
    pass


class FinetuningChatBlendedDataset(BaseBlendedDataset):
    pass
