from .finetuning import (
    FinetuningChatBlendedDataset,
    FinetuningChatDataset,
    FinetuningItem,
    FinetuningTextBlendedDataset,
    FinetuningTextDataset,
)
from .text_dataset import (
    TextBlendedDataset,
    TextDataset,
    TextDatasetBatch,
    TextDatasetItem,
)

__all__ = [
    "FinetuningChatBlendedDataset",
    "FinetuningChatDataset",
    "FinetuningItem",
    "FinetuningTextBlendedDataset",
    "FinetuningTextDataset",
    "TextBlendedDataset",
    "TextDataset",
    "TextDatasetBatch",
    "TextDatasetItem",
]
