from .finetuning import (
    FinetuningChatBlendedDataset,
    FinetuningChatDataset,
    FinetuningItem,
    FinetuningTextBlendedDataset,
    FinetuningTextDataset,
)
from .text_dataset import (
    LegacyBlendedDataset,
    TextBlendedDataset,
    TextDataset,
    TextDatasetBatch,
    TextDatasetItem,
)

__all__ = [
    "FinetuningChatBlendedDataset",
    "FinetuningChatDataset",
    "FinetuningItem",
    "FinetuningTextBlendedDataset",
    "FinetuningTextDataset",
    "LegacyBlendedDataset",
    "TextBlendedDataset",
    "TextDataset",
    "TextDatasetBatch",
    "TextDatasetItem",
]
