from .text_dataset import (
    TextBlendedDataset,
    TextDataset,
    TextDatasetBatch,
    TextDatasetItem,
)

__all__ = [
    "TextBlendedDataset",
    "TextDataset",
    "TextDatasetBatch",
    "TextDatasetItem",
]
