"""Transformer model assembly.

(reference: src/scaling/transformer/model/model.py:43-408) — layer-spec
list, loss, parameter groups, init_model/init_optimizer. The reference's
``TransformerParallelModule`` subclass exists only to strip non-tensor
fields around pipe sends (model.py:96-119); under jit the IO dict is a
static-treedef pytree, so the plain ParallelModule works as-is.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ...nn import LayerSpec, ParamMeta, PipelineBodySpec, TiedLayerSpec
from ...optimizer import Optimizer, OptimizerParamGroup
from ...parallel.parallel_module import ParallelModule
from ...topology import Topology
from .config import TransformerConfig, TransformerArchitectureConfig
from .layers.embedding import EmbeddingInput
from .layers.layer import TransformerLayer
from .layers.lm_head import (
    LayerNormWrapper,
    TransformerEmbeddingHead,
    TransformerLMHead,
    TransformerLMHeadTied,
)

TIED_KEY = "embedding_lm_head"


def get_transformer_layer_specs(
    architecture: TransformerArchitectureConfig,
    topology: Optional[Topology] = None,
) -> List[LayerSpec]:
    """EmbeddingInput -> N x TransformerLayer -> final norm -> LM head
    [-> embedding head] (reference: model.py:122-216).

    With pipe_parallel_size > 1 the homogeneous TransformerLayer run becomes
    one PipelineBodySpec executed as a stage-stacked spatial pipeline; edge
    layers stay replicated over the pipe axis."""
    has_embedding_head = architecture.embedding_head_config is not None
    if architecture.weight_tying:
        specs: List[LayerSpec] = [
            TiedLayerSpec(
                EmbeddingInput,
                architecture,
                key=TIED_KEY,
                tied_weight_attributes=["embedding.weight"],
            )
        ]
    else:
        specs = [LayerSpec(EmbeddingInput, architecture)]

    pp = topology.pipe_parallel_size if topology is not None else 1
    if pp > 1:
        specs.append(
            PipelineBodySpec(TransformerLayer, architecture.num_layers, architecture)
        )
    else:
        for layer_index in range(architecture.num_layers):
            specs.append(LayerSpec(TransformerLayer, architecture, layer_index))

    specs.append(
        LayerSpec(LayerNormWrapper, architecture, record_embeddings=has_embedding_head)
    )

    if architecture.weight_tying:
        specs.append(
            TiedLayerSpec(
                TransformerLMHeadTied,
                architecture,
                key=TIED_KEY,
                tied_weight_attributes=["embedding.weight"],
            )
        )
    else:
        specs.append(LayerSpec(TransformerLMHead, architecture))

    if has_embedding_head:
        specs.append(LayerSpec(TransformerEmbeddingHead, architecture))
    return specs


def per_token_loss(logits, targets):
    """(token cross-entropy, correct-prediction flags) in fp32 — the one
    definition both the training loss and the standalone evaluator reduce
    (they differ only in mean-vs-sum aggregation).

    The cross entropy goes through the memory-lean custom VJP
    (ops/cross_entropy.py): same fp32 forward math, but no fp32
    ``(b, s, vocab)`` log-softmax residual held to the backward — ~2 GB
    less live memory at the bench shape, measured via compiled buffer
    assignment."""
    from ...ops.cross_entropy import cross_entropy_from_logits

    targets = targets.astype(jnp.int32)
    token_loss = cross_entropy_from_logits(logits, targets)
    # argmax is monotonic under the fp32 upcast, so comparing on the raw
    # logits keeps the old fp32-argmax semantics
    correct = (logits.argmax(-1) == targets).astype(jnp.float32)
    return token_loss, correct


def loss_function(output: Dict[str, Any], batch: Dict[str, Any]):
    """Cross entropy with per-token loss weights + accuracy
    (reference: model.py:43-76)."""
    targets = batch["target_token_ids"]
    loss_weights = batch.get("loss_weights")
    if loss_weights is None:
        loss_weights = jnp.ones(targets.shape, dtype=jnp.float32)
    loss_weights = loss_weights.astype(jnp.float32)

    token_loss, correct = per_token_loss(output["activations"], targets)
    denom = jnp.maximum(loss_weights.sum(), 1.0)
    loss = (token_loss * loss_weights).sum() / denom
    accuracy = (correct * loss_weights).sum() / denom
    metrics = {"accuracy": accuracy}
    aux = output.get("aux_loss")
    if aux is not None:
        # MoE load-balance term (already coefficient-scaled by the layers)
        aux = jnp.asarray(aux, jnp.float32).mean()
        loss = loss + aux
        metrics["moe_aux_loss"] = aux
    return loss, metrics


def metrics_aggregation_fn(metrics_list: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Mean over collected step metrics (reference: model.py:79-93; the DP
    mean happens inside the jitted step on TPU)."""
    if not metrics_list:
        return {}
    keys = metrics_list[0].keys()
    return {k: float(sum(m[k] for m in metrics_list) / len(metrics_list)) for k in keys}


NO_WEIGHT_DECAY_SUBSTRINGS = ("norm", "bias")


def get_parameter_groups(
    config: TransformerConfig, module: ParallelModule
) -> List[OptimizerParamGroup]:
    """weight-decay / no-decay / embedding groups + finetune filtering
    (reference: model.py:238-386)."""
    training = config.training
    metas = [
        m
        for m in jax.tree.leaves(
            module.param_metas(), is_leaf=lambda x: isinstance(x, ParamMeta)
        )
    ]

    include_patterns = [re.compile(p) for p in training.finetunable_parameters]
    exclude_patterns = [re.compile(p) for p in training.parameters_exclude]
    peft_names = config.transformer_architecture.peft_names

    def trainable(meta: ParamMeta) -> bool:
        name = meta.key
        if exclude_patterns and any(p.search(name) for p in exclude_patterns):
            return False
        if training.finetune:
            if any(p.search(name) for p in include_patterns):
                return True
            # PEFT params are always trainable in finetune mode
            # (reference: config.py:426-459 auto-separates them). Match the
            # naming convention `..._{name}.` / `...bias_{name}` exactly —
            # a bare substring test would let a short PEFT name like "ad"
            # claim unrelated params ("lm_head")
            return any(
                re.search(rf"(_|bias_){re.escape(n)}(\.|$)", name) for n in peft_names
            )
        return True

    decay_keys, no_decay_keys, embedding_keys = set(), set(), set()
    for meta in metas:
        if not trainable(meta):
            continue
        if (
            training.use_separate_lr_on_embeddings
            and meta.lr_group == "embedding"
        ):
            embedding_keys.add(meta.key)
        elif meta.no_weight_decay or any(
            s in meta.parameter_name.lower() for s in NO_WEIGHT_DECAY_SUBSTRINGS
        ) or meta.lr_group == "embedding":
            no_decay_keys.add(meta.key)
        else:
            decay_keys.add(meta.key)

    # muP (Adam rule): LR scales by 1/width-mult for matrices whose FAN-IN
    # grows with hidden_size — qkv/dense/mlp/expert weights, the readout,
    # adapter down-projections, lora_a, the first embedding-head
    # projection. Everything width-independent keeps the base LR: vectors,
    # the input-like embedding table and softprompts (in whichever decay
    # set they already lived — muP must not change decay membership),
    # adapter up, lora_b, later embedding-head projections, the whole
    # image encoder — their update scale never grew with width, so
    # shrinking it has no muP justification.
    mup_mult = config.transformer_architecture.mup_width_mult

    def fan_in_scales_with_width(meta: ParamMeta) -> bool:
        if len(meta.partition_spec) < 2:
            return False  # vectors (norms, biases)
        name = meta.parameter_name
        if meta.lr_group == "embedding" or "softprompt" in name:
            return False  # input-like: fan_in is vocab / prompt slots
        if "image_encoder" in name:
            return False
        if name.endswith(".up") or "lora_b" in name:
            return False
        m = re.search(r"proj_(\d+)_", name)
        if m:
            return int(m.group(1)) == 0
        return True

    if mup_mult == 1.0:
        group_spec = (
            (decay_keys, training.weight_decay, "weight_decay_params", 1.0),
            (no_decay_keys, 0.0, "no_weight_decay_params", 1.0),
        )
    else:
        by_key = {meta.key: meta for meta in metas}

        def split(keys: set) -> tuple[set, set]:
            scaled = {k for k in keys if fan_in_scales_with_width(by_key[k])}
            return scaled, keys - scaled

        decay_scaled, decay_fixed = split(decay_keys)
        no_decay_scaled, no_decay_fixed = split(no_decay_keys)
        group_spec = (
            (decay_scaled, training.weight_decay, "weight_decay_params",
             1.0 / mup_mult),
            (decay_fixed, training.weight_decay,
             "weight_decay_params_fixed_width", 1.0),
            (no_decay_scaled, 0.0, "no_weight_decay_params_width_scaled",
             1.0 / mup_mult),
            (no_decay_fixed, 0.0, "no_weight_decay_params", 1.0),
        )

    groups = []
    for keys, wd, name, lr_scale in group_spec:
        if keys:
            groups.append(
                OptimizerParamGroup(
                    keys=keys,
                    weight_decay=wd,
                    learning_rate_scheduler=config.learning_rate_scheduler,
                    name=name,
                    lr_scale=lr_scale,
                )
            )
    if embedding_keys:
        groups.append(
            OptimizerParamGroup(
                keys=embedding_keys,
                weight_decay=0.0,
                learning_rate_scheduler=config.embedding_learning_rate_scheduler,
                name="embedding_params",
            )
        )
    if not groups:
        raise ValueError("no trainable parameters selected")
    return groups


def init_model(config: TransformerConfig, topology: Optional[Topology] = None) -> ParallelModule:
    specs = get_transformer_layer_specs(config.transformer_architecture, topology)
    return ParallelModule(
        specs,
        topology=topology,
        compute_dtype=config.transformer_architecture.dtype,
    )


def init_optimizer(
    config: TransformerConfig, module: ParallelModule, topology: Optional[Topology] = None
) -> Optimizer:
    groups = get_parameter_groups(config, module)
    return Optimizer(config.optimizer, groups, module.param_metas(), topology=topology)
