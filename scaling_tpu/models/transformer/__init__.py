from .config import (
    AdapterConfig,
    BitfitConfig,
    EmbeddingHeadConfig,
    MLPType,
    Precision,
    RelativePositionEmbeddingType,
    SoftpromptConfig,
    TrainingConfig,
    TransformerArchitectureConfig,
    TransformerConfig,
)
from .context import TransformerContext
from .inference import (
    CompletionOutput,
    TransformerInferenceModule,
    make_sampler,
    sample_argmax,
)
from .model import (
    get_parameter_groups,
    get_transformer_layer_specs,
    init_model,
    init_optimizer,
    loss_function,
    metrics_aggregation_fn,
)
from .tokenizer import Tokenizer, load_tokenizers

__all__ = [
    "AdapterConfig",
    "BitfitConfig",
    "EmbeddingHeadConfig",
    "MLPType",
    "Precision",
    "RelativePositionEmbeddingType",
    "SoftpromptConfig",
    "TrainingConfig",
    "TransformerArchitectureConfig",
    "TransformerConfig",
    "TransformerContext",
    "CompletionOutput",
    "TransformerInferenceModule",
    "make_sampler",
    "sample_argmax",
    "get_parameter_groups",
    "get_transformer_layer_specs",
    "init_model",
    "init_optimizer",
    "loss_function",
    "metrics_aggregation_fn",
    "Tokenizer",
    "load_tokenizers",
]
