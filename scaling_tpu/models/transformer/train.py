"""Transformer training entry.

(reference: src/scaling/transformer/train.py:80-304) — config -> topology
-> context -> model -> optimizer -> datasets -> trainer.run_training, with
the per-step TFLOPs/MFU instrumentation riding on the trainer's metric hook.
Runnable per host: ``python -m scaling_tpu.models.transformer.train
--payload=<b64 config>`` or programmatically via ``main(config)``.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax

from ...data.blended_dataset import BlendedDatasetConfig
from ...logging import logger
from ...runner import LaunchConfig, initialize_distributed
from ...topology import Topology
from ...trainer import BaseTrainer
from .config import TransformerConfig
from .context import TransformerContext
from .data.finetuning import (
    FinetuningChatBlendedDataset,
    FinetuningChatDataset,
    FinetuningTextBlendedDataset,
    FinetuningTextDataset,
)
from .data.text_dataset import LegacyBlendedDataset, TextBlendedDataset, TextDataset
from .model import init_model, init_optimizer, loss_function
from .utils.get_tflops import (
    HardwareType,
    get_flops_per_token,
    get_model_parameter_count,
    get_palm_mfu,
    get_tflops_aleph_alpha,
    get_tflops_bloom,
    get_tflops_electra,
    get_tflops_megatron,
)


def batch_to_model_input(batch) -> dict:
    return batch.as_model_input()


def log_metrics_fn(trainer: BaseTrainer, output, metrics: dict) -> dict:
    """Adds tokens/s, the 4 TFLOPs estimators and PaLM MFU
    (reference: train.py:80-136)."""
    config: TransformerConfig = trainer.context.config
    arch = config.transformer_architecture
    topo = trainer.topology.config
    step_time = output.step_duration or 1e-9
    tokens = topo.global_batch_size * arch.sequence_length
    glu = arch.mlp_type.value == "swiglu"
    param_count = get_model_parameter_count(
        arch.hidden_size, arch.num_layers, arch.vocab_size, arch.mlp_factor, glu
    )
    metrics["tokens_per_second"] = tokens / step_time
    metrics["tflops_megatron"] = get_tflops_megatron(
        param_count, step_time, topo.global_batch_size, arch.sequence_length
    )
    metrics["tflops_bloom"] = get_tflops_bloom(
        arch.hidden_size, arch.num_layers, arch.vocab_size, step_time,
        topo.global_batch_size, arch.sequence_length,
        activation_checkpointing=topo.activation_checkpointing_type.value != "disabled",
    )
    metrics["tflops_electra"] = get_tflops_electra(
        arch.hidden_size, arch.num_layers, arch.num_attention_heads,
        arch.vocab_size, arch.sequence_length, step_time,
        topo.global_batch_size, arch.mlp_factor,
    )
    metrics["tflops_aleph_alpha"] = get_tflops_aleph_alpha(
        arch.hidden_size, arch.num_layers, arch.num_attention_heads,
        arch.vocab_size, arch.sequence_length, step_time,
        topo.global_batch_size, arch.mlp_factor,
    )
    metrics["palm_mfu"] = get_palm_mfu(
        param_count, arch.num_layers, arch.hidden_size, arch.sequence_length,
        metrics["tokens_per_second"], topo.world_size,
        hardware=HardwareType.TPU_V5P,
    )
    return metrics


def _read_dataset(config: TransformerConfig, prefixes: Optional[List[Any]]):
    if not prefixes:
        return None
    arch = config.transformer_architecture
    data = config.data
    if data.finetuning_dataset or data.finetuning_chat_dataset:
        if arch.vocab_file is None:
            raise ValueError("finetuning datasets need transformer_architecture.vocab_file")
        if data.finetuning_chat_dataset:
            softprompt_chat = arch.softprompt_config
            datasets: List[Any] = [
                FinetuningChatDataset(
                    data_prefix=p,
                    sequence_length=arch.sequence_length,
                    vocab_file=arch.vocab_file,
                    seed=config.trainer.seed,
                    softprompt_n_tokens=(
                        softprompt_chat.n_tokens if softprompt_chat else 0
                    ),
                )
                for p in prefixes
            ]
            blended_cls: Any = FinetuningChatBlendedDataset
        else:
            softprompt = arch.softprompt_config
            datasets = [
                FinetuningTextDataset(
                    data_prefix=p,
                    sequence_length=arch.sequence_length,
                    vocab_file=arch.vocab_file,
                    seed=config.trainer.seed,
                    memory_map_dataset=data.finetuning_dataset_memory_map,
                    softprompt_n_tokens=softprompt.n_tokens if softprompt else 0,
                )
                for p in prefixes
            ]
            blended_cls = FinetuningTextBlendedDataset
    else:
        datasets = [
            TextDataset(
                data_prefix=p,
                sequence_length=arch.sequence_length,
                seed=config.trainer.seed,
                eod_token_id=data.eod_token_id,
                only_full_sequences=data.only_full_sequences,
                allow_incomplete_sequences_every_n=data.allow_incomplete_sequences_every_n,
                load_index_to_memory=data.load_mmap_index_to_memory,
                legacy_dataset=data.legacy_dataset,
            )
            for p in prefixes
        ]
        blended_cls = LegacyBlendedDataset if data.legacy_dataset else TextBlendedDataset
    if len(datasets) == 1:
        return datasets[0]
    blended_config = data.blended_dataset or BlendedDatasetConfig()
    return blended_cls(
        seed=config.trainer.seed, config=blended_config, datasets=datasets
    )


class TransformerTrainer(BaseTrainer):
    # accepts BOTH the legacy positional name and the BaseTrainer keyword
    # (run_with_resume and other generic wrappers call the base surface
    # `run_training(log_metrics_fn=...)` — it must not TypeError here)
    def run_training(self, log_metrics_fn_=None, *,
                     log_metrics_fn=None) -> None:  # noqa: D102
        fn = log_metrics_fn_ or log_metrics_fn or globals()["log_metrics_fn"]
        super().run_training(log_metrics_fn=fn)


def main(config: TransformerConfig) -> TransformerTrainer:
    topology = Topology(config.topology)
    logger.configure(config.logger, name="transformer")
    logger.log_config(config)
    context = TransformerContext(config=config, topology=topology)
    module = init_model(config, topology)
    optimizer = init_optimizer(config, module, topology)
    dataset = _read_dataset(config, config.data.data_prefixes)
    dataset_evaluation = _read_dataset(config, config.data.validation_data_prefixes)
    from ...profiler import Profiler

    trainer = TransformerTrainer(
        config=config.trainer,
        context=context,
        parallel_module=module,
        optimizer=optimizer,
        loss_function=loss_function,
        dataset=dataset,
        dataset_evaluation=dataset_evaluation,
        batch_to_model_input=batch_to_model_input,
        profiler=Profiler(config.profiler),
    )
    # declare the model's FLOPs-per-token once so the trainer's telemetry
    # emits per-step achieved-TFLOPs/MFU gauges (docs/OBSERVABILITY.md)
    # alongside the per-step estimator metrics log_metrics_fn computes
    arch = config.transformer_architecture
    topo = config.topology
    param_count = get_model_parameter_count(
        arch.hidden_size, arch.num_layers, arch.vocab_size, arch.mlp_factor,
        glu=arch.mlp_type.value == "swiglu",
    )
    trainer.telemetry.configure(
        flops_per_token=get_flops_per_token(
            param_count, arch.num_layers, arch.hidden_size,
            arch.sequence_length,
        ),
        tokens_per_step=topo.global_batch_size * arch.sequence_length,
        world_size=topo.world_size,
        peak_tflops=HardwareType.TPU_V5P.max_tflops,
    )
    from ...resilience import controlplane_from_env

    # under the multi-host supervisor every worker finds the control
    # plane in its environment (SCALING_TPU_CONTROL_DIR/_ADDR); joining
    # it turns on heartbeats (without which the supervisor would declare
    # a healthy host hung after the startup grace), the coordinated
    # preemption drain, and the cross-host commit barrier
    cp = controlplane_from_env()
    if cp is not None:
        trainer.attach_control_plane(
            cp, shared_save_dir=config.trainer.multihost_shared_save_dir
        )
        trainer.install_preemption_handler()
    from ...determined import DeterminedGlue

    glue = DeterminedGlue.detect()
    try:
        if glue is None:
            trainer.initialize(load_checkpoint=config.trainer.load_dir is not None)
        else:
            # under Determined the experiment's own latest checkpoint wins
            # over the configured load_dir (reference: trainer.py:416-428)
            glue.attach(trainer)
            with glue.latest_checkpoint() as det_ckpt:
                trainer.initialize(
                    load_checkpoint=(
                        det_ckpt is not None or config.trainer.load_dir is not None
                    ),
                    load_dir=det_ckpt,
                )
        clip_ckpt = config.transformer_architecture.image_encoder_clip_checkpoint
        if clip_ckpt is not None:
            _apply_pretrained_clip(trainer, module, clip_ckpt)
        trainer.run_training()
    finally:
        if glue is not None:
            glue.close()
    return trainer


def _apply_pretrained_clip(trainer, module, path) -> None:
    """Splice pretrained CLIP vision weights into the image-encoder trunk
    at startup (reference: clip.py constructs its trunk pretrained). Skipped
    whenever the loaded checkpoint already restored image-encoder weights
    (resume OR finetune-with-load_context=False — either way the trained
    trunk is in the checkpoint); applied on fresh runs and
    finetunes-from-LM-only-checkpoints. Optimizer masters for the spliced
    subtree re-derive so the first step can't revert it; moments loaded
    for the REST of the model are kept."""
    from pathlib import Path

    if trainer.context.iterations > 0:
        logger.info(f"resume at step {trainer.context.iterations}: "
                    "skipping pretrained CLIP splice (trunk is in the checkpoint)")
        return
    restored = trainer.restored_model_keys or set()
    # gate on the TRUNK specifically: a checkpoint restoring only the
    # shared non-trunk pieces (image_encoder.proj / final_norm) must not
    # suppress the splice the config explicitly asked for
    if any("image_encoder.clip" in k for k in restored):
        logger.info(
            "loaded checkpoint already restored the CLIP trunk; "
            "skipping pretrained CLIP splice"
        )
        return
    import torch

    p = Path(path)
    if p.is_dir():
        from transformers import CLIPVisionModel

        sd = CLIPVisionModel.from_pretrained(p).state_dict()
    else:
        sd = torch.load(p, map_location="cpu", weights_only=True)
        sd = sd.get("state_dict", sd)

    for i, layer in enumerate(module.layers):
        encoder = getattr(layer, "image_encoder", None)
        if encoder is None:
            continue
        name = module.layer_name(i)
        emb_params = trainer.params[name]
        fresh = encoder.load_clip_weights(emb_params["image_encoder"], sd)
        placed = jax.tree.map(
            lambda new, old: jax.device_put(new.astype(old.dtype), old.sharding)
            if hasattr(old, "sharding") else new.astype(old.dtype),
            fresh, emb_params["image_encoder"],
        )
        trainer.params = {
            **trainer.params, name: {**emb_params, "image_encoder": placed},
        }
        if trainer.optimizer_states_loaded:
            # the splice only replaced the clip TRUNK (load_clip_weights
            # leaves proj/final_norm untouched), so only that subtree gets
            # fresh masters/zero moments; loaded moments everywhere else —
            # including image_encoder.proj/final_norm — are kept. `only`
            # keeps the rest of the fresh tree at cheap placeholders, so
            # no full fp32 transient on big models.
            fresh = trainer.optimizer.init_state(
                trainer.params,
                only=lambda m: "image_encoder.clip" in m.parameter_name,
            )

            def graft(dst, src):
                enc = dst[name]["image_encoder"]
                fresh_enc = src[name]["image_encoder"]
                return {
                    **dst,
                    name: {
                        **dst[name],
                        "image_encoder": {**enc, "clip": fresh_enc["clip"]},
                    },
                }

            trainer.opt_state = trainer.opt_state._replace(
                master=graft(trainer.opt_state.master, fresh.master),
                exp_avg=graft(trainer.opt_state.exp_avg, fresh.exp_avg),
                exp_avg_sq=graft(trainer.opt_state.exp_avg_sq, fresh.exp_avg_sq),
            )
        else:
            trainer.opt_state = trainer.optimizer.init_state(trainer.params)
        logger.info(f"loaded pretrained CLIP vision weights from {path}")
        return
    raise ValueError(
        "image_encoder_clip_checkpoint set but the model has no image "
        "encoder (set image_encoder: true, image_encoder_backbone: clip)"
    )


if __name__ == "__main__":
    launch_config = LaunchConfig.from_launcher_args()
    initialize_distributed(launch_config)
    assert launch_config.payload is not None, "--payload required"
    config = TransformerConfig.from_dict(launch_config.payload)
    main(config)
