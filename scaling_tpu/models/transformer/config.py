"""Transformer suite configuration.

TPU-native re-design of the reference's transformer config composition
(reference: src/scaling/transformer/context/config.py:28-459): one frozen
pydantic tree wiring topology + optimizer + LR schedules + trainer + data +
architecture. ``Precision`` maps straight onto jnp dtypes (bf16 is the TPU
native compute type); fp16 keeps the dynamic loss scaler for parity.
"""

from __future__ import annotations

from enum import Enum
from pathlib import Path
from typing import Any, List, Optional

import jax.numpy as jnp
from pydantic import AliasChoices, Field, model_validator

from ...config import BaseConfig
from ...context.context import ContextConfig
from ...logging import LoggerConfig
from ...nn.activation_function import ActivationFunction
from ...nn.lora import LoRaConfig
from ...nn.masked_softmax import MaskedSoftmaxConfig
from ...nn.norm import LayerNormConfig, NormType
from ...optimizer import LearningRateSchedulerConfig, OptimizerConfig
from ...topology import TopologyConfig
from ...trainer import TrainerConfig


class Precision(Enum):
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    FLOAT32 = "float32"

    @property
    def dtype(self):
        return {
            Precision.FLOAT16: jnp.float16,
            Precision.BFLOAT16: jnp.bfloat16,
            Precision.FLOAT32: jnp.float32,
        }[self]


class MLPType(Enum):
    DEFAULT = "default"
    SWIGLU = "swiglu"
    # beyond the reference: routed mixture-of-experts FFN with expert
    # parallelism over the data mesh axis (nn/moe.py; SURVEY §2.4 lists EP
    # as absent upstream)
    MOE = "moe"


class RelativePositionEmbeddingType(Enum):
    NONE = "none"
    ROTARY = "rotary"
    ROTARY_COMPLEX = "rotary_complex"


class BitfitConfig(BaseConfig):
    """BitFit fine-tuning: fresh named bias parameters on linears/norms
    (reference: config.py:72-78)."""

    name: str = Field("bitfit", description="name suffix of the fresh bias parameters")


class AdapterConfig(BaseConfig):
    """Bottleneck adapters inserted after attention and/or MLP blocks
    (reference: config.py:80-97, layers/layer.py:140-187)."""

    name: str = Field("adapter", description="adapter parameter name suffix")
    attention_downsampling_factor: Optional[float] = Field(
        None,
        description="adapter width = hidden * factor after the attention "
        "block (multiplicative like the reference, config.py:105 — e.g. "
        "0.25 for a 4x bottleneck)",
        gt=0,
    )
    mlp_downsampling_factor: Optional[float] = Field(
        None,
        description="adapter width = hidden * factor after the mlp block",
        gt=0,
    )
    init_std: float = Field(1.0e-3, description="std of the adapter init")


class SoftpromptConfig(BaseConfig):
    """Learned prompt embeddings overwriting the first ``n_tokens``
    positions (reference: config.py:99-105, layers/embedding.py:63-81)."""

    name: str = Field("softprompt", description="softprompt parameter name suffix")
    n_tokens: int = Field(8, description="number of learned prompt positions", gt=0)


class EmbeddingHeadConfig(BaseConfig):
    """Projection stack on weighted-mean-pooled hidden states for
    embedding models (reference: config.py:107-124, embedding_head.py:12-80)."""

    name: str = Field("embedding_head", description="")
    proj_layers: List[int] = Field(
        default_factory=list,
        description="hidden sizes of the projection stack; last entry is the "
        "embedding dimension",
    )


class MupConfig(BaseConfig):
    """Maximal-update parametrization (Tensor Programs V, Yang & Hu 2021):
    tune hyperparameters on a small base width, transfer them to any width.

    The reference shipped a ``umup`` flag that implemented nothing; this is
    the real thing, wired through four rules (Adam variant):

    - hidden-matrix AND readout learning rates scale by
      base_hidden_size / hidden_size (applied as ``lr_scale`` on the
      optimizer param groups; embedding, norms, biases and softprompts
      stay unscaled);
    - attention logits scale 1/d beyond the base width
      (sqrt(base_head_dim)/head_dim — equal to 1/sqrt(head_dim) at base);
    - LM-head logits multiply by the width-independent tunable output_mult
      (the width correction is the readout LR scale — the multiplier and
      LR formulations of the muP output rule are alternatives, not
      composable);
    - the LM head zero-initializes (readout_zero_init), removing the
      width-dependent readout noise at init.

    Hidden weights keep xavier init (variance already ~1/width). Verified
    by the coordinate-check test: logit updates stay width-independent
    where standard parametrization grows with width
    (tests/transformer/test_mup.py)."""

    base_hidden_size: int = Field(
        description="hidden size of the tuned base model; scaling rules "
        "activate as hidden_size grows past it",
        gt=0,
    )
    base_num_attention_heads: Optional[int] = Field(
        None,
        description="head count of the tuned base model; defaults to this "
        "model's head count (width grown by head_dim). Set it when width "
        "is grown by ADDING heads instead — the attention rule needs the "
        "base model's true head_dim, not hidden/width-mult",
        gt=0,
    )
    output_mult: float = Field(
        1.0, description="tunable multiplier on the LM-head logits", gt=0
    )
    readout_zero_init: bool = Field(
        True, description="zero-initialize the LM head projection"
    )


class TransformerArchitectureConfig(BaseConfig):
    """Model shape + feature switches
    (reference: src/scaling/transformer/context/config.py:126-330)."""

    vocab_size: int = Field(description="size of the vocabulary", gt=0)
    vocab_file: Optional[Path] = Field(None, description="tokenizer vocab json")
    hidden_size: int = Field(description="transformer hidden size", gt=0)
    num_layers: int = Field(description="number of transformer layers", ge=0)
    num_attention_heads: int = Field(description="number of attention heads", gt=0)
    attention_num_kv_heads: Optional[int] = Field(
        None, description="number of kv heads for grouped-query attention"
    )
    attention_qkv_in_one: bool = Field(
        True, description="store q,k,v projections in one fused weight"
    )
    attention_bias: bool = Field(
        True, description="add bias terms to the attention projections"
    )
    attention_use_matmul: bool = Field(
        False,
        description="kept for config parity with the reference's "
        "torch.matmul/baddbmm switch (config.py:215); XLA picks the matmul "
        "strategy itself, so this has no effect on TPU",
    )
    num_local_attention_heads: int = Field(
        0, description="number of heads restricted to a local window", ge=0
    )
    local_attention_window_size: Optional[int] = Field(
        None, description="window size of local attention heads"
    )
    rotary_embedding_base: int = Field(10000, description="rotary base theta")
    rotary_percentage: float = Field(
        1.0, description="fraction of head dim that is rotated", gt=0.0, le=1.0
    )
    sequence_length: int = Field(2048, description="training sequence length", gt=0)
    norm_type: NormType = Field(NormType.LAYERNORM, description="")
    relative_position_embedding_type: RelativePositionEmbeddingType = Field(
        RelativePositionEmbeddingType.ROTARY, description=""
    )
    mlp_type: MLPType = Field(MLPType.DEFAULT, description="")
    mlp_factor: float = Field(4.0, description="mlp intermediate = factor * hidden", gt=0)
    mlp_bias: bool = Field(True, description="add bias terms to the mlp projections")
    moe_num_experts: int = Field(
        8, description="expert count for mlp_type 'moe'", gt=0
    )
    moe_top_k: int = Field(2, description="experts routed per token", gt=0)
    moe_capacity_factor: float = Field(
        1.25, description="per-expert token buffer slack over the uniform share",
        gt=0,
    )
    moe_aux_loss_coef: float = Field(
        0.01, description="Switch-style load-balance loss coefficient", ge=0
    )
    activation_function: ActivationFunction = Field(ActivationFunction.GELU, description="")
    precision: Precision = Field(Precision.FLOAT32, description="compute/param dtype")
    layernorm: LayerNormConfig = Field(LayerNormConfig(), description="")
    masked_softmax: MaskedSoftmaxConfig = Field(MaskedSoftmaxConfig(), description="")
    causal: bool = Field(True, description="use a causal attention mask")
    key_query_norm: bool = Field(False, description="normalise q/k per head")
    weight_tying: bool = Field(False, description="tie lm head to the embedding")
    masked_softmax_fusion: bool = Field(True, description="kept for config parity")
    layernorm_epsilon: float = Field(1.0e-5, description="kept for config parity")

    dropout_embedding: float = Field(0.0, description="", ge=0.0, le=1.0)
    dropout_attention_probs: float = Field(0.0, description="", ge=0.0, le=1.0)
    dropout_after_attention: float = Field(0.0, description="", ge=0.0, le=1.0)
    dropout_after_mlp: float = Field(0.0, description="", ge=0.0, le=1.0)

    mup: Optional[MupConfig] = Field(
        None,
        description="maximal-update parametrization for width-transferable "
        "hyperparameters (see MupConfig)",
    )

    # fine tuning / PEFT
    bitfit_bias_config: Optional[BitfitConfig] = Field(None, description="")
    adapter_config: Optional[AdapterConfig] = Field(None, description="")
    softprompt_config: Optional[SoftpromptConfig] = Field(None, description="")
    lora_config: Optional[LoRaConfig] = Field(None, description="")
    embedding_head_config: Optional[EmbeddingHeadConfig] = Field(None, description="")
    finetunable_token_ids: List[int] = Field(
        default_factory=list,
        description="restrict embedding gradients to these token ids",
    )
    image_encoder: bool = Field(
        False,
        description="multimodal image encoder: 384x384 images become 144 "
        "prefix tokens spliced into the embedding stream (ViT patch "
        "backbone; the reference uses a CLIP ResNet, image_encoder.py)",
    )
    image_encoder_width: int = Field(768, description="vision tower width", gt=0)
    image_encoder_layers: int = Field(6, description="vision tower depth", gt=0)
    image_encoder_heads: int = Field(12, description="vision tower heads", gt=0)
    image_encoder_backbone: str = Field(
        "vit",
        description="'vit' trains the patch backbone from scratch; 'clip' "
        "builds a CLIP-ViT trunk that loads pretrained huggingface "
        "CLIPVisionModel weights; 'clip_resnet' builds the reference's "
        "actual trunk — the CLIP ModifiedResNet (RN50x16 at the defaults, "
        "clip.py) — so reference/magma vision checkpoints transfer. Set "
        "image_encoder_clip_checkpoint to load the weights at startup, or "
        "call ImageEncoder.load_clip_weights manually",
        pattern="^(vit|clip|clip_resnet)$",
    )
    image_encoder_resnet_stages: List[int] = Field(
        [6, 8, 18, 8],
        description="bottleneck blocks per stage for the clip_resnet "
        "backbone (default: RN50x16); exactly 4 stages (CLIP layout)",
        min_length=4,
        max_length=4,
    )
    image_encoder_resnet_channels: int = Field(
        96,
        description="stem output channels for the clip_resnet backbone "
        "(default: RN50x16; feature dim is 8*channels*4)",
        gt=0,
    )
    image_encoder_clip_checkpoint: Optional[str] = Field(
        None,
        description="path to pretrained CLIP vision weights applied at "
        "train startup (fresh runs only, not resumes): a torch state_dict "
        "file (torch.load) or a local transformers CLIPVisionModel "
        "directory; requires a clip backbone with geometry matching the "
        "checkpoint",
    )
    dropout_image_encoder: float = Field(
        0.0, description="dropout applied after the image encoder projection",
        ge=0.0, le=1.0,
    )

    @model_validator(mode="after")
    def _validate(self):
        if self.num_local_attention_heads > 0 and self.local_attention_window_size is None:
            raise ValueError("local attention heads require local_attention_window_size")
        if self.mlp_type == MLPType.MOE:
            if self.moe_top_k > self.moe_num_experts:
                raise ValueError(
                    f"moe_top_k ({self.moe_top_k}) cannot exceed "
                    f"moe_num_experts ({self.moe_num_experts})"
                )
            if self.mlp_bias:
                raise ValueError(
                    "mlp_type 'moe' does not support mlp_bias; set it false "
                    "(experts are GLU FFNs without bias)"
                )
        if self.mup is not None and self.weight_tying:
            raise ValueError(
                "mup does not compose with weight_tying: the tied table "
                "would need embedding-scale init and readout-scale LR at "
                "once; untie the head to use mup"
            )
        return self

    @property
    def mup_width_mult(self) -> float:
        """Width multiplier m = hidden / base_hidden (1.0 when mup is off)."""
        if self.mup is None:
            return 1.0
        return self.hidden_size / self.mup.base_hidden_size

    @property
    def dtype(self):
        return self.precision.dtype

    @property
    def peft_names(self) -> List[str]:
        """Names of active PEFT modules — drives separate checkpoint files
        (reference: config.py:426-459)."""
        names = []
        if self.bitfit_bias_config:
            names.append(self.bitfit_bias_config.name)
        if self.adapter_config:
            names.append(self.adapter_config.name)
        if self.softprompt_config:
            names.append(self.softprompt_config.name)
        if self.lora_config:
            names.append(self.lora_config.name)
        if self.embedding_head_config:
            names.append(self.embedding_head_config.name)
        return names


class TrainingConfig(BaseConfig):
    weight_decay: float = Field(1.0e-4, description="weight decay for linear weights")
    finetune: bool = Field(
        False, description="train only parameters matched by finetunable_parameters"
    )
    finetunable_parameters: List[str] = Field(
        default_factory=list,
        description="regexes of parameter names to train when finetune is set",
    )
    parameters_exclude: List[str] = Field(
        default_factory=list,
        description="regexes of parameter names to exclude from training",
    )
    use_deterministic_torch_algorithms: bool = Field(
        False, description="kept for config parity; XLA is deterministic by default"
    )
    use_separate_lr_on_embeddings: bool = Field(
        False,
        description="use embedding_learning_rate_scheduler on embedding weights",
        validation_alias=AliasChoices(
            # the misspelled alias keeps legacy reference configs loading
            # (reference: context/config.py:55-57)
            "use_separate_lr_on_embeddings", "use_seperate_lr_on_embeddings"
        ),
    )


class DataConfig(BaseConfig):
    data_prefixes: Optional[List[Path]] = Field(
        None, description="prefixes of memory-map dataset files"
    )
    blended_dataset: Optional["BlendedDatasetConfig"] = Field(
        None, description="blending over data_prefixes"
    )
    eod_token_id: int = Field(
        0, description="token id marking end-of-document in tokenized data; "
        "drives segmenting, position resets and loss masking", ge=0
    )
    validation_data_prefixes: Optional[List[Path]] = Field(None, description="")
    legacy_dataset: bool = Field(False, description="load Megatron-format .bin/.idx data")
    finetuning_dataset: bool = Field(False, description="prompt/completion jsonl data")
    finetuning_chat_dataset: bool = Field(False, description="chat jsonl data")
    finetuning_dataset_memory_map: bool = Field(False, description="")
    use_mmap: bool = Field(True, description="")
    load_mmap_index_to_memory: bool = Field(False, description="")
    load_data_item_mmap_index_to_memory: bool = Field(False, description="")
    only_full_sequences: bool = Field(False, description="")
    allow_incomplete_sequences_every_n: int = Field(0, description="", ge=0)


from ...data.blended_dataset import BlendedDatasetConfig  # noqa: E402

DataConfig.model_rebuild()


from ...profiler import ProfilerConfig  # noqa: E402


# config keys that existed in earlier releases and were removed; configs
# baked into old checkpoints still carry them, and extra="forbid" would
# otherwise refuse to load those checkpoints
REMOVED_CONFIG_KEYS = (
    ("transformer_architecture", "umup"),
    ("data", "embedding_dataset"),
    ("data", "embedding_dataset_memory_map"),
)


def strip_removed_config_keys(d: dict) -> dict:
    """Drop known-removed keys from a checkpoint-embedded config dict."""
    d = {k: (dict(v) if isinstance(v, dict) else v) for k, v in d.items()}
    for section, key in REMOVED_CONFIG_KEYS:
        sub = d.get(section)
        if isinstance(sub, dict):
            sub.pop(key, None)
    return d


class TransformerConfig(BaseConfig):
    """Composition root (reference: config.py:364-425)."""

    version: str = Field("0.1.0", description="")
    runner: Optional["RunnerConfig"] = Field(None, description="")
    logger: LoggerConfig = Field(LoggerConfig(), description="")
    topology: TopologyConfig = Field(description="")
    optimizer: OptimizerConfig = Field(OptimizerConfig(), description="")
    learning_rate_scheduler: LearningRateSchedulerConfig = Field(
        LearningRateSchedulerConfig(), description=""
    )
    embedding_learning_rate_scheduler: LearningRateSchedulerConfig = Field(
        LearningRateSchedulerConfig(), description=""
    )
    training: TrainingConfig = Field(TrainingConfig(), description="")
    trainer: TrainerConfig = Field(TrainerConfig(), description="")
    profiler: ProfilerConfig = Field(ProfilerConfig(), description="")
    transformer_architecture: TransformerArchitectureConfig = Field(description="")
    data: DataConfig = Field(DataConfig(), description="")
    determined_experiment_id: Optional[int] = Field(None, description="")
    determined_trial_id: Optional[int] = Field(None, description="")
    context: ContextConfig = Field(ContextConfig(), description="")

    @classmethod
    def from_dict(cls, d: dict, overwrite_values: Optional[dict] = None):
        return super().from_dict(d, overwrite_values=overwrite_values)


from ...runner.config import RunnerConfig  # noqa: E402

TransformerConfig.model_rebuild()
