"""Inference: checkpoint -> logits / generate with KV cache.

(reference: src/scaling/transformer/inference/inference_model.py:30-263,
core/nn/parallel_module/inference_module.py). The reference hops layer
slices across GPUs with ``.to_(device)`` and grows a KV cache by
concatenation; under jit both collapse: layers run in one XLA program and
the cache is a fixed-capacity buffer written with ``dynamic_update_slice``
(static shapes — one compiled decode step serves the whole generation).

Cached vs uncached generate (reference: inference_model.py:159-235):
- cached (default): one prefill over the prompt, then ONE jitted
  ``lax.while_loop`` running every decode step on-device — KV caches in
  the carry, tokens/logits written into preallocated buffers, per-row
  stop masks, early exit when all rows are done. The reference (and the
  ``fused_decode=False`` escape hatch here) instead dispatches one jit
  call per token; on TPU each of those dispatches pays host-round-trip
  latency, which dominates decode wall-clock.
- uncached: the whole padded sequence is re-fed each step (parity baseline).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Callable, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import yaml

from .config import TransformerConfig
from .layers.layer import TransformerLayer
from .model import get_transformer_layer_specs
from .tokenizer import Tokenizer
from ...checkpoint import load_model_checkpoint
from ...parallel.parallel_module import ParallelModule


class CompletionOutput(NamedTuple):
    completion_ids: List[int]
    completion: Optional[str]
    logits: Optional[jax.Array] = None


def sample_argmax(logits: jax.Array, key: Optional[jax.Array] = None) -> jax.Array:
    """Greedy sampling (reference: inference/sample.py)."""
    return jnp.argmax(logits, axis=-1)


class _LeftPadLayout(NamedTuple):
    """Position/segment/mask views for a left-padded (ragged) batch; all
    None when the batch is rectangular."""

    pos_all: Optional[jax.Array] = None  # (b, prompt+gen) rotary positions
    seg_all: Optional[jax.Array] = None  # (b, prompt+gen) pad segment = 1
    prompt_pos: Optional[jax.Array] = None  # prompt-prefix slices of the above
    prompt_seg: Optional[jax.Array] = None
    content_len: Optional[jax.Array] = None  # (b,) per-row rotary clock base
    pad_mask: Optional[jax.Array] = None  # (b,1,1,prompt+gen) additive -1e9

    @property
    def ragged(self) -> bool:
        return self.pos_all is not None


def _left_pad_layout(
    pad_start: Optional[jax.Array], prompt_len: int, max_tokens: int,
    use_cache: bool,
) -> _LeftPadLayout:
    """One left-padded layout over the full generation buffer: positions
    restart at each row's first content token and run straight into the
    generated slots; pads keep their own segment. Prefill slices the
    prompt prefix; the uncached path uses the full-buffer views directly;
    the decode paths blank the pad cache slots with the additive mask."""
    if pad_start is None:
        return _LeftPadLayout()
    slots_all = jnp.arange(prompt_len + max_tokens)[None]
    ps = pad_start[:, None]
    pos_all = jnp.clip(slots_all - ps, 0)
    seg_all = jnp.where(slots_all >= ps, 0, 1).astype(jnp.int32)
    return _LeftPadLayout(
        pos_all=pos_all,
        seg_all=seg_all,
        prompt_pos=pos_all[:, :prompt_len],
        prompt_seg=seg_all[:, :prompt_len],
        content_len=prompt_len - pad_start,
        pad_mask=(
            jnp.where(slots_all < ps, -1e9, 0.0)[:, None, None, :]
            if use_cache
            else None
        ),
    )


def make_sampler(
    temperature: float = 1.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> Callable[[jax.Array, jax.Array], jax.Array]:
    """Temperature / top-k / top-p (nucleus) sampling, composable like the
    reference's transform chain (reference: inference/sample.py:17-45).

    The returned closure carries ``_sampler_key`` (its configuration), so
    the jitted decode loops recognise two ``make_sampler(...)`` calls with
    identical settings as the same sampler instead of re-tracing the whole
    while-loop program per ``generate()`` call. Custom sampler callables
    without the attribute fall back to object identity — reuse one object
    across calls to keep the compiled loop warm."""

    def sample(logits: jax.Array, key: jax.Array) -> jax.Array:
        scaled = logits.astype(jnp.float32) / max(temperature, 1e-6)
        if top_k is not None:
            kth = jnp.sort(scaled, axis=-1)[..., -top_k][..., None]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        if top_p is not None:
            # keep the smallest prefix of descending-prob tokens whose
            # cumulative mass reaches top_p (always keeping the best token)
            sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = cum - probs < top_p
            kept = jnp.sum(keep_sorted, axis=-1, keepdims=True)
            cutoff = jnp.take_along_axis(sorted_logits, kept - 1, axis=-1)
            scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
        return jax.random.categorical(key, scaled, axis=-1)

    sample._sampler_key = ("make_sampler", temperature, top_k, top_p)
    return sample


def _sampler_cache_id(sample: Callable) -> Any:
    """Cache identity for a sampler: its configuration when it advertises
    one, the object itself otherwise."""
    return getattr(sample, "_sampler_key", sample)


def sample_rows(
    logits: jax.Array,       # (rows, vocab)
    temperatures: jax.Array,  # (rows,) f32; <= 0 means greedy
    top_ks: jax.Array,        # (rows,) i32; <= 0 or >= vocab disables
    keys: jax.Array,          # (rows, 2) uint32 per-row PRNG keys
    top_ps: Optional[jax.Array] = None,  # (rows,) f32; <=0 or >=1 disables
) -> jax.Array:
    """Per-row temperature / top-k / top-p sampling with per-row keys —
    the serving engine's batched counterpart of :func:`make_sampler`.

    The engine decodes MANY requests in one jitted program, so the
    sampler configuration must be traced per-row data, never baked-in
    constants (a per-config program would be a recompile per request —
    the exact storm the ``serve_decode`` golden pins against). The math
    mirrors ``make_sampler`` op-for-op (same temperature clamp, same
    sort-based top-k cutoff, same nucleus cutoff over the descending
    sort, same ``jax.random.categorical``) so a row here and a
    single-request ``generate()`` with the same settings and key draw
    the SAME token — parity-pinned in tests/transformer/test_serving.py.
    ``temperature <= 0`` short-circuits to argmax: greedy stays the
    default AND the zero-temperature limit, with no randomness
    consumed."""
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits.astype(jnp.float32) / jnp.maximum(
        temperatures, 1e-6
    )[:, None]
    # traced per-row k: make_sampler's static `sort(...)[..., -k]` becomes
    # a take_along_axis at index vocab - k on the ascending sort — the
    # identical cutoff value, so the masked logits match bit-for-bit
    sorted_scaled = jnp.sort(scaled, axis=-1)
    k_active = (top_ks > 0) & (top_ks < vocab)
    k_idx = jnp.clip(vocab - top_ks, 0, vocab - 1)
    kth = jnp.take_along_axis(sorted_scaled, k_idx[:, None], axis=-1)
    scaled = jnp.where(
        k_active[:, None] & (scaled < kth), -jnp.inf, scaled
    )
    if top_ps is not None:
        # nucleus cutoff AFTER top-k, exactly make_sampler's order; the
        # math is already shape-static in p, so the per-row threshold
        # simply rides in as traced data — same ops, bit-identical mask
        p_active = (top_ps > 0.0) & (top_ps < 1.0)
        # re-sort AFTER the top-k mask, like make_sampler: nucleus mass
        # is computed over the surviving (possibly -inf-masked) logits
        sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_ps[:, None]
        kept = jnp.sum(keep_sorted, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(
            sorted_desc, jnp.maximum(kept - 1, 0), axis=-1
        )
        scaled = jnp.where(
            p_active[:, None] & (scaled < cutoff), -jnp.inf, scaled
        )
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row[None], axis=-1)[0]
    )(keys, scaled)
    return jnp.where(temperatures <= 0.0, greedy, sampled).astype(jnp.int32)


def request_sample_key(base_key: jax.Array, req_id: jax.Array,
                       num_generated: jax.Array) -> jax.Array:
    """The per-token sampling key: ``fold_in(fold_in(base, req_id), n)``
    where ``n`` counts tokens already generated for the request.

    Keyed by REQUEST position, not by engine tick: a preempted-and-
    resumed sequence regenerates its tokens at the same positions and so
    redraws the SAME samples — recompute-style preemption stays invisible
    in the output even for temperature > 0 rows."""
    return jax.random.fold_in(
        jax.random.fold_in(base_key, req_id), num_generated
    )


class TransformerInferenceModule:
    """Single-host inference over a trained checkpoint."""

    def __init__(
        self,
        config: TransformerConfig,
        module: ParallelModule,
        params: Any,
        tokenizer: Optional[Tokenizer] = None,
    ):
        self.config = config
        self.architecture = config.transformer_architecture
        self.module = module
        self.params = params
        self.tokenizer = tokenizer
        self._logits_fn = None
        self._decode_fn = None
        # (max_len, ragged) the per-step decode closure was traced for
        self._decode_key: Optional[tuple] = None
        self._decode_loop = None
        self._decode_loop_key = None

    # ------------------------------------------------------------- loading
    @classmethod
    def from_checkpoint(
        cls,
        checkpoint_dir: Path | str,
        vocab_file: Optional[Path | str] = None,
        overwrite_config: Optional[dict] = None,
        topology: Optional[dict] = None,
    ) -> "TransformerInferenceModule":
        """Reads ``config.yml`` + per-layer npz files from a checkpoint dir
        (reference: inference_model.py:55-87).

        ``topology`` enables mesh-sharded inference for models too big for
        one chip: e.g. ``{"model_parallel_size": 4}`` tensor-parallelizes
        every layer over 4 devices (the reference instead hops layer slices
        across GPUs sequentially, inference_module.py:77-109 — TP keeps all
        devices busy every layer). Checkpoints are layout-independent, so
        any saved model loads at any ``model_parallel_size``."""
        ckpt = Path(checkpoint_dir)
        latest = ckpt / "latest"
        if latest.is_file():
            ckpt = ckpt / latest.read_text().strip()
        config_file = ckpt / "config.yml"
        if not config_file.is_file():
            raise FileNotFoundError(f"no config.yml in {ckpt}")
        from .config import strip_removed_config_keys

        config = TransformerConfig.from_dict(
            strip_removed_config_keys(yaml.safe_load(config_file.read_text())),
            overwrite_values=overwrite_config,
        )
        topo = None
        if topology is not None:
            from ...topology import Topology, TopologyConfig

            tdict = dict(topology)
            if tdict.get("pipe_parallel_size", 1) != 1:
                # explicit raise (not assert): stripped asserts would let a
                # pp>1 stack silently decode without its KV caches
                raise ValueError(
                    "inference shards with model parallelism only; use "
                    "model_parallel_size, not pipe stages"
                )
            tdict.setdefault("pipe_parallel_size", 1)
            tdict.setdefault("data_parallel_size", 1)
            tdict.setdefault("micro_batch_size", 1)
            tdict.setdefault("gradient_accumulation_steps", 1)
            topo = Topology(TopologyConfig.from_dict(tdict))
        specs = get_transformer_layer_specs(config.transformer_architecture, topo)
        module = ParallelModule(
            specs, topology=topo, compute_dtype=config.transformer_architecture.dtype
        )
        if topo is None:
            params = module.init_params(jax.random.PRNGKey(0))
            params = module.ckpt_unview(
                load_model_checkpoint(
                    ckpt, module.ckpt_view(params), module.ckpt_metas()
                ),
                params,
            )
        else:
            # init + load on host CPU first: doing it on the accelerator
            # would materialize the full model on device 0 and OOM exactly
            # the too-big-for-one-chip models sharded inference is for;
            # shard_params then device_puts each leaf pre-sharded
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                params = module.init_params(jax.random.PRNGKey(0))
                params = module.ckpt_unview(
                    load_model_checkpoint(
                        ckpt, module.ckpt_view(params), module.ckpt_metas()
                    ),
                    params,
                )
            params = module.shard_params(params)
        tokenizer = None
        vocab = Path(vocab_file) if vocab_file else ckpt / "vocab.json"
        if vocab.is_file():
            tokenizer = Tokenizer.from_file(vocab)
        return cls(config, module, params, tokenizer)

    # ------------------------------------------------------------- forward
    def _run_layers(self, params, batch, caches, offset, paged_kernel=None,
                    gather_start=None, gather_width=None):
        """One pass through the stack; TransformerLayers consume/produce the
        KV caches, edge layers run as in training (deterministic).

        ``paged_kernel`` (static; serving engine only) selects the
        attention back-end for block-paged caches: 'pallas' streams KV
        blocks through the flash-style kernel (nn/paged_attention.py),
        'xla' gathers each row's window (the fallback). Dense caches
        ignore it.

        ``gather_start`` (a traced per-row (b,) start index) with
        ``gather_width`` (static) slices each row's window of trunk
        activations AFTER the last TransformerLayer and BEFORE the
        post-trunk layers — which are position-pointwise, so only the
        positions that will actually be SAMPLED pay the final norm and
        the vocab projection (the serving engine's fused mixed program
        samples ≤ spec_k+1 of its ``mixed_width`` positions per row;
        projecting all of them priced a (rows, width, vocab) logit
        block nobody read). The returned logits then cover positions
        ``gather_start .. gather_start + gather_width - 1`` per row.

        A pipelined (pp>1) stack wraps its TransformerLayers in a
        ``PipelinedBody``, which cannot consume KV caches: the cached path
        raises instead of silently decoding with no history (the caches
        would be skipped and every token computed as if it were first);
        the uncached path runs the body unstacked, like training's
        ``ParallelModule.forward``."""
        from ...parallel.pipeline import PipelinedBody

        ctx = self.module._make_ctx(deterministic=True, dropout_key=None)
        if paged_kernel is not None:
            ctx.paged_kernel = paged_kernel
        last_tl = None
        if gather_start is not None:
            tls = [
                i for i, l in enumerate(self.module.layers)
                if isinstance(l, TransformerLayer)
            ]
            if not tls:
                raise ValueError(
                    "gather_start needs a TransformerLayer trunk to "
                    "gather after (pipelined/edge-only stacks have none)"
                )
            last_tl = max(tls)
        x = batch
        new_caches = []
        li = 0
        for i, layer in enumerate(self.module.layers):
            p = self.module._layer_params(params, i)
            if isinstance(layer, TransformerLayer):
                if caches is None:
                    x = layer(p, x, ctx)
                else:
                    x, kv = layer(p, x, ctx, kv_cache=caches[li], cache_offset=offset)
                    new_caches.append(kv)
                    li += 1
                if i == last_tl:
                    x = dict(x)
                    x["activations"] = jax.vmap(
                        lambda a, s: jax.lax.dynamic_slice_in_dim(
                            a, s, gather_width, axis=0
                        )
                    )(x["activations"], gather_start)
            elif isinstance(layer, PipelinedBody):
                if caches is not None:
                    raise ValueError(
                        "cached decode through a pipelined (pp>1) layer "
                        "stack would silently skip the KV caches and "
                        "recompute every token without history; decode at "
                        "pipe_parallel_size=1 (checkpoints are layout-"
                        "independent) or use generate(use_cache=False)"
                    )
                x = layer(p, x, ctx, stacked=False, remat=False)
            else:
                x = layer(p, x, ctx)
        if caches is not None and li != len(caches):
            raise ValueError(
                f"layer stack consumed {li} KV cache(s) but {len(caches)} "
                "were provided — a cache silently skipped here means "
                "silently wrong decode output"
            )
        return x["activations"], new_caches

    def _make_batch(
        self,
        token_ids: jax.Array,
        position_ids: jax.Array,
        segment_ids: Optional[jax.Array] = None,
        scores_manipulation: Optional[jax.Array] = None,
    ) -> dict:
        b, s = token_ids.shape
        return {
            "token_ids": token_ids.astype(jnp.int32),
            "target_token_ids": jnp.zeros((b, s), jnp.int32),
            "position_ids": position_ids.astype(jnp.int32),
            "segment_ids": (
                jnp.zeros((b, s), jnp.int32)
                if segment_ids is None
                else segment_ids.astype(jnp.int32)
            ),
            "loss_weights": None,
            "embeddings": None,
            "attention_scores_manipulation": scores_manipulation,
        }

    def logits(self, token_ids, controls=None, control_log_additive=True) -> jax.Array:
        """Full-sequence logits (b, s, vocab).

        ``controls``: AtMan-style per-token attention controls
        (attention_control.Control) applied in every layer; with
        ``control_log_additive=True`` (reference default) as log(factor)
        score offsets, with ``False`` as multiplicative factors on
        min-shifted scores (reference: inference_settings.py:24-30 +
        attention.py:158-170)."""
        token_ids = jnp.asarray(token_ids)
        if token_ids.ndim == 1:
            token_ids = token_ids[None]
        b, s = token_ids.shape
        pos = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        manipulation = None
        if controls:
            from .attention_control import build_attention_scores_manipulation

            manipulation = build_attention_scores_manipulation(
                controls, seq_len=s, batch_size=b,
                log_additive=control_log_additive,
            )
        if self._logits_fn is None:
            def run(p, t, po, manip, log_additive):
                batch = self._make_batch(t, po)
                batch["attention_scores_manipulation"] = manip
                batch["attention_scores_manipulation_log_additive"] = log_additive
                return self._run_layers(p, batch, None, None)[0]

            # the flag is STATIC: each value compiles its own graph
            self._logits_fn = jax.jit(run, static_argnums=(4,))
        return self._logits_fn(
            self.params, token_ids, pos, manipulation, bool(control_log_additive)
        )

    def hidden_states(
        self,
        token_ids,
        include: Optional[List[int]] = None,
        exclude: Optional[List[int]] = None,
    ) -> dict:
        """Per-layer hidden states keyed ``layer_{i}_{Class}``; filter with
        include/exclude layer indices (reference: HiddenStateRecorder,
        inference_module.py:24-74, inference_model.py:121-135)."""
        token_ids = jnp.asarray(token_ids)
        if token_ids.ndim == 1:
            token_ids = token_ids[None]
        b, s = token_ids.shape
        pos = jnp.broadcast_to(jnp.arange(s)[None], (b, s))

        def run(params, t, po):
            ctx = self.module._make_ctx(deterministic=True, dropout_key=None)
            x = self._make_batch(t, po)
            recorded = {}
            for i, layer in enumerate(self.module.layers):
                p = self.module._layer_params(params, i)
                x = layer(p, x, ctx)
                if include is not None and i not in include:
                    continue
                if exclude is not None and i in exclude:
                    continue
                recorded[f"layer_{i}_{type(layer).__name__}"] = x["activations"]
            return recorded

        return jax.jit(run)(self.params, token_ids, pos)

    # ------------------------------------------------------------ generate
    def _alloc_caches(self, kvs, max_len: int):
        caches = []
        for k, v in kvs:
            b, s = k.shape[0], k.shape[1]
            ck = jnp.zeros((b, max_len) + k.shape[2:], k.dtype)
            cv = jnp.zeros((b, max_len) + v.shape[2:], v.dtype)
            caches.append(
                (
                    jax.lax.dynamic_update_slice_in_dim(ck, k, 0, axis=1),
                    jax.lax.dynamic_update_slice_in_dim(cv, v, 0, axis=1),
                )
            )
        return caches

    def prefill_forward(self, params, token_ids, position_ids,
                        segment_ids=None, last_index=None):
        """Traceable prompt pass: full stack with ``return_kv=True`` (the
        flash kernel stays active — no cache is CONSUMED here), returning
        (logits for one position, per-layer (k, v)).

        The sampled position is the last one by default; ``last_index``
        (a traced scalar) selects another — right-padded prompts, as the
        serving engine's bucketed prefill uses, sample at prompt_len-1.
        Shared by ``generate``'s dense-cache prefill and the serving
        engine's paged prefill (serve/engine.py), so the two products of
        one prompt pass can never diverge."""
        from ...parallel.pipeline import PipelinedBody

        ctx = self.module._make_ctx(deterministic=True, dropout_key=None)
        transformer_idxs = [
            i for i, l in enumerate(self.module.layers)
            if isinstance(l, TransformerLayer)
        ]
        if not transformer_idxs:
            if any(isinstance(l, PipelinedBody) for l in self.module.layers):
                raise ValueError(
                    "cached generation through a pipelined (pp>1) layer "
                    "stack would silently decode without its KV caches; "
                    "decode at pipe_parallel_size=1 (checkpoints are "
                    "layout-independent) or use generate(use_cache=False)"
                )
            raise ValueError(
                "cannot run cached generation on a module with no "
                "TransformerLayer (nothing produces KV caches); use "
                "generate(use_cache=False) or fix the layer stack"
            )
        last_tl = max(transformer_idxs)

        x = self._make_batch(token_ids, position_ids, segment_ids=segment_ids)
        kvs = []
        for i, layer in enumerate(self.module.layers):
            p = self.module._layer_params(params, i)
            if isinstance(layer, TransformerLayer):
                x, kv = layer(p, x, ctx, return_kv=True)
                kvs.append(kv)
            else:
                x = layer(p, x, ctx)
            if i == last_tl:
                # only the sampled position feeds the post-trunk layers —
                # they are position-pointwise, and running the vocab
                # projection over the whole prompt would materialize
                # (b, s, vocab) logits (>1 GB at bench shapes, ~8 GB at a
                # 32k prompt)
                x = dict(x)
                if last_index is None:
                    x["activations"] = x["activations"][:, -1:]
                else:
                    x["activations"] = jax.lax.dynamic_slice_in_dim(
                        x["activations"], last_index, 1, axis=1
                    )
        return x["activations"], kvs

    def _prefill(
        self,
        token_ids: jax.Array,
        max_len: int,
        position_ids: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
    ):
        """Prompt pass collecting per-layer KV, then seed fixed-size caches.

        ``position_ids``/``segment_ids`` carry left-padded (ragged) prompt
        batches: pads sit in their own segment so content never attends to
        them, and positions restart at the first content token so rotary
        phases match the unpadded prompt."""
        b, s = token_ids.shape
        pos = (
            jnp.broadcast_to(jnp.arange(s)[None], (b, s))
            if position_ids is None
            else position_ids
        )
        logits, kvs = jax.jit(self.prefill_forward)(
            self.params, token_ids, pos, segment_ids
        )
        return logits, self._alloc_caches(kvs, max_len)

    def _build_decode_loop(self, sample, stop_ids, steps, ragged=False):
        """The whole decode as one device program: ``lax.while_loop`` whose
        carry holds the KV caches, the last token, and preallocated
        (b, steps+1) token / (b, steps+1, vocab) logit buffers. The key
        sequence matches the per-step path exactly (first token sampled
        with the caller's key outside, each loop step splits), so fused
        and unfused decode produce identical generations.

        ``ragged``: the loop additionally takes per-row content lengths
        (the rotary clock — cache slots stay the causal clock, see
        nn/attention.py) and an additive pad mask that blanks the
        left-pad cache slots."""
        stop_arr = jnp.asarray(stop_ids, jnp.int32) if stop_ids else None

        def is_stop(tok):
            if stop_arr is None:
                return jnp.zeros(tok.shape, bool)
            return jnp.isin(tok, stop_arr)

        def loop(params, caches, tok0, logits0, prompt_len, key,
                 content_len=None, pad_mask=None):
            b = tok0.shape[0]
            tok0 = tok0.astype(jnp.int32)
            toks = jnp.zeros((b, steps + 1), jnp.int32)
            toks = jax.lax.dynamic_update_slice(toks, tok0[:, None], (0, 0))
            lgts = jnp.zeros((b, steps + 1, logits0.shape[-1]), logits0.dtype)
            lgts = jax.lax.dynamic_update_slice(lgts, logits0[:, None], (0, 0, 0))

            def cond(c):
                t, done = c[0], c[-1]
                return (t <= steps) & ~jnp.all(done)

            def body(c):
                t, caches, tok, key, toks, lgts, done = c
                key, sub = jax.random.split(key)
                offset = prompt_len + t - 1
                if ragged:
                    pos = (content_len + (t - 1))[:, None]
                    batch = self._make_batch(
                        tok[:, None], pos, scores_manipulation=pad_mask
                    )
                else:
                    pos = jnp.broadcast_to(offset[None, None], (b, 1))
                    batch = self._make_batch(tok[:, None], pos)
                logits, caches = self._run_layers(params, batch, caches, offset)
                nxt = sample(logits[:, -1], sub).astype(jnp.int32)
                # finished rows keep stepping (their output is trimmed on
                # the host), matching the per-step path's lockstep advance
                toks = jax.lax.dynamic_update_slice(toks, nxt[:, None], (0, t))
                lgts = jax.lax.dynamic_update_slice(
                    lgts, logits[:, -1][:, None], (0, t, 0)
                )
                return (t + 1, caches, nxt, key, toks, lgts, done | is_stop(nxt))

            init = (jnp.int32(1), caches, tok0, key, toks, lgts, is_stop(tok0))
            _, caches, _, _, toks, lgts, done = jax.lax.while_loop(
                cond, body, init
            )
            # the final caches are dead weight to the caller, but returning
            # them is what makes donate_argnums=(1,) real: donation only
            # frees an input when it aliases a same-shaped OUTPUT, and the
            # cache input has no other output to alias
            return toks, lgts, done, caches

        return loop

    def generate(
        self,
        input_ids,
        max_tokens: int = 32,
        sample_fn: Optional[Callable] = None,
        use_cache: bool = True,
        eos_token_id: Optional[int] = None,
        stop_tokens: Optional[List[int]] = None,
        seed: int = 0,
        fused_decode: bool = True,
    ) -> CompletionOutput:
        """Autoregressive decode (reference: inference_model.py:195-263).

        Stops at ``eos_token_id`` or any of ``stop_tokens`` (reference's
        ``stop_tokens`` sequence); per-step logits for the emitted tokens
        come back in ``CompletionOutput.logits`` like the reference's
        ``completion_logits``.

        Accepts a batch of prompts — a (b, s) array or a list of b token
        lists, including RAGGED lists of unequal length — and decodes all
        rows in one pass, each row stopping independently (the reference's
        cache is bs=1 only, attention.py:491). Ragged prompts are
        left-padded internally: pads sit in their own attention segment
        during prefill, decode masks their cache slots, and per-row rotary
        positions start at each row's first content token, so every row
        generates exactly what it would alone. Batched input returns a
        list of ``CompletionOutput``; 1-D input keeps the single-output
        form."""
        if isinstance(input_ids, str):
            assert self.tokenizer is not None, "text prompt needs a tokenizer"
            input_ids = self.tokenizer.encode(input_ids)
        elif (
            isinstance(input_ids, (list, tuple))
            and input_ids
            and isinstance(input_ids[0], str)
        ):
            # a batch of text prompts: encode each; unequal lengths ride
            # the ragged (left-padded) path below
            assert self.tokenizer is not None, "text prompts need a tokenizer"
            input_ids = [self.tokenizer.encode(s) for s in input_ids]
        pad_start = None
        if (
            isinstance(input_ids, (list, tuple))
            and input_ids
            and isinstance(input_ids[0], (list, tuple))
            and len({len(r) for r in input_ids}) > 1
        ):
            lens = [len(r) for r in input_ids]
            longest = max(lens)
            pad_start = jnp.asarray([longest - n for n in lens], jnp.int32)
            input_ids = [
                [0] * (longest - n) + list(r) for r, n in zip(input_ids, lens)
            ]
        prompt = jnp.asarray(input_ids, jnp.int32)
        single = prompt.ndim == 1
        if single:
            prompt = prompt[None]
        b, prompt_len = prompt.shape
        lay = _left_pad_layout(pad_start, prompt_len, max_tokens, use_cache)
        if eos_token_id is None and self.tokenizer is not None:
            eos_token_id = self.tokenizer.eos_token_id
        stop = set(stop_tokens or [])
        if eos_token_id is not None:
            stop.add(int(eos_token_id))
        sample = sample_fn or sample_argmax
        key = jax.random.PRNGKey(seed)
        row_tokens: List[List[int]] = [[] for _ in range(b)]
        # per row: a list of per-step (vocab,) arrays (per-step paths) OR
        # one contiguous (steps, vocab) slice (fused path); row_logits_out
        # below normalizes the union
        row_logits: List[Any] = [[] for _ in range(b)]
        finished = [False] * b

        def collect(tok, step_logits):
            """Append this step's token/logits to unfinished rows."""
            tok_host = np.asarray(tok)  # one transfer per step, not per row
            for i in range(b):
                if finished[i]:
                    continue
                row_tokens[i].append(int(tok_host[i]))
                row_logits[i].append(step_logits[i])
                finished[i] = row_tokens[i][-1] in stop

        if use_cache:
            max_len = prompt_len + max_tokens
            logits, caches = self._prefill(
                prompt, max_len, position_ids=lay.prompt_pos, segment_ids=lay.prompt_seg
            )
            next_tok = sample(logits[:, -1], key)

        if use_cache and fused_decode:
            # max_tokens<=1 still emits the prologue's one token (matching
            # the per-step path); the loop body just never runs
            steps = max(0, max_tokens - 1)
            stop_ids = tuple(sorted(stop))
            ragged = lay.ragged
            fkey = (steps, _sampler_cache_id(sample), stop_ids, ragged)
            # shapes (batch, cache length, vocab) re-trace via jit; only
            # the baked-in constants need an explicit cache key
            if self._decode_loop is None or self._decode_loop_key != fkey:
                # the prefill caches die with this call — donating them
                # lets XLA run the loop carry in place instead of holding
                # a second (b, max_len) KV copy during decode. CPU can't
                # donate (every call would warn), so only accelerators do.
                donate = (1,) if jax.default_backend() != "cpu" else ()
                self._decode_loop = jax.jit(
                    self._build_decode_loop(sample, stop_ids, steps, ragged),
                    donate_argnums=donate,
                )
                self._decode_loop_key = fkey
            extra = (lay.content_len, lay.pad_mask) if ragged else ()
            toks, lgts, _, _ = self._decode_loop(
                self.params, caches, next_tok, logits[:, -1],
                jnp.asarray(prompt_len, jnp.int32), key, *extra,
            )
            toks_host = np.asarray(toks)  # ONE device->host transfer
            for i in range(b):
                end = toks_host.shape[1]
                for j in range(toks_host.shape[1]):
                    if int(toks_host[i, j]) in stop:
                        end = j + 1  # the stop token itself is emitted
                        break
                row_tokens[i] = [int(x) for x in toks_host[i, :end]]
                row_logits[i] = lgts[i, :end]  # contiguous, already stacked
        elif use_cache:
            collect(next_tok, logits[:, -1])

            # the jitted decode closure bakes in the sampler: invalidate on
            # a new length, a different sample_fn, or a raggedness change,
            # or a later call would silently reuse a stale closure
            ragged = lay.ragged
            if (
                self._decode_fn is None
                or self._decode_key != (max_len, ragged)
                or getattr(self, "_decode_sampler", None)
                != _sampler_cache_id(sample)
            ):
                def decode(params, caches, tok, offset, k, base=None, pm=None):
                    bb = tok.shape[0]
                    if base is not None:
                        pos = base[:, None]
                        batch = self._make_batch(
                            tok[:, None], pos, scores_manipulation=pm
                        )
                    else:
                        pos = jnp.broadcast_to(offset[None, None], (bb, 1))
                        batch = self._make_batch(tok[:, None], pos)
                    logits, new_caches = self._run_layers(params, batch, caches, offset)
                    nxt = sample(logits[:, -1], k)
                    return nxt, logits[:, -1], new_caches

                self._decode_fn = jax.jit(decode)
                self._decode_key = (max_len, ragged)
                self._decode_sampler = _sampler_cache_id(sample)

            tok = next_tok
            for t in range(1, max_tokens):
                if all(finished):
                    break
                key, sub = jax.random.split(key)
                # finished rows keep stepping (their output is discarded);
                # rows advance in lockstep so one shared cache_offset works
                extra = (lay.content_len + (t - 1), lay.pad_mask) if ragged else ()
                tok, step_logits, caches = self._decode_fn(
                    self.params, caches, tok,
                    jnp.asarray(prompt_len + t - 1, jnp.int32), sub, *extra,
                )
                collect(tok, step_logits)
        else:
            # refeed the whole (fixed-size) buffer each step: one compile
            max_len = prompt_len + max_tokens
            buf = jnp.zeros((b, max_len), jnp.int32)
            buf = jax.lax.dynamic_update_slice_in_dim(buf, prompt, 0, axis=1)
            fwd = jax.jit(
                lambda p, t, po, sg: self._run_layers(
                    p, self._make_batch(t, po, segment_ids=sg), None, None
                )[0]
            )
            if lay.ragged:
                pos, seg = lay.pos_all, lay.seg_all  # the shared left-padded layout
            else:
                pos = jnp.broadcast_to(jnp.arange(max_len)[None], (b, max_len))
                seg = None
            cur = prompt_len
            for _ in range(max_tokens):
                if all(finished):
                    break
                logits = fwd(self.params, buf, pos, seg)
                key, sub = jax.random.split(key)
                nxt = sample(logits[:, cur - 1], sub)
                collect(nxt, logits[:, cur - 1])
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt[:, None].astype(jnp.int32), (0, cur)
                )
                cur += 1

        def row_logits_out(rl):
            if isinstance(rl, list):  # per-step paths collect step arrays
                return jnp.stack(rl, axis=0) if rl else None
            return rl  # fused path already holds the contiguous (end, vocab) slice

        outs = [
            CompletionOutput(
                completion_ids=row_tokens[i],
                completion=(
                    self.tokenizer.decode(row_tokens[i]) if self.tokenizer else None
                ),
                logits=row_logits_out(row_logits[i]),
            )
            for i in range(b)
        ]
        return outs[0] if single else outs
