from . import transformer

__all__ = ["transformer"]
