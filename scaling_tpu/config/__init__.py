from .base import BaseConfig, overwrite_recursive

__all__ = ["BaseConfig", "overwrite_recursive"]
