"""Config base class.

Pydantic v2 models that are frozen, forbid unknown keys, load/save yaml+json,
support recursive dict overwrites and emit self-documenting commented config
templates from the field descriptions.

Capability parity with the reference ``scaling.core.config.base``
(reference: src/scaling/core/config/base.py:26-153); implementation is new.
"""

from __future__ import annotations

import json
from enum import Enum
from pathlib import Path
from typing import Any, Type, TypeVar

import yaml
from pydantic import BaseModel, ConfigDict

T = TypeVar("T", bound="BaseConfig")


def overwrite_recursive(base: dict, overwrite: dict) -> dict:
    """Merge ``overwrite`` into ``base`` in place, recursing into nested dicts.

    Non-dict values (including lists) replace wholesale.
    """
    for key, value in overwrite.items():
        if isinstance(value, dict) and isinstance(base.get(key), dict):
            overwrite_recursive(base[key], value)
        else:
            base[key] = value
    return base


def _to_jsonable(value: Any) -> Any:
    if isinstance(value, Enum):
        return value.value
    if isinstance(value, Path):
        return str(value)
    if isinstance(value, dict):
        return {k: _to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    return value


class BaseConfig(BaseModel):
    """Immutable config node; composes into trees (e.g. TransformerConfig)."""

    model_config = ConfigDict(
        frozen=True,
        extra="forbid",
        use_enum_values=False,
        populate_by_name=True,
    )

    # ------------------------------------------------------------- loading
    @classmethod
    def from_dict(cls: Type[T], d: dict, overwrite_values: dict | None = None) -> T:
        data = json.loads(json.dumps(_to_jsonable(dict(d))))
        if overwrite_values:
            overwrite_recursive(data, _to_jsonable(overwrite_values))
        return cls(**data)

    @classmethod
    def from_yaml(cls: Type[T], path: str | Path, overwrite_values: dict | None = None) -> T:
        with open(path) as f:
            data = yaml.safe_load(f)
        return cls.from_dict(data or {}, overwrite_values=overwrite_values)

    @classmethod
    def from_json(cls: Type[T], path: str | Path, overwrite_values: dict | None = None) -> T:
        with open(path) as f:
            data = json.load(f)
        return cls.from_dict(data or {}, overwrite_values=overwrite_values)

    # -------------------------------------------------------------- saving
    def as_dict(self) -> dict:
        return _to_jsonable(self.model_dump(mode="json"))

    def as_str(self) -> str:
        return json.dumps(self.as_dict(), indent=2)

    def save(self, path: str | Path, indent: int = 2) -> None:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        data = self.as_dict()
        if path.suffix in (".yml", ".yaml"):
            with open(path, "w") as f:
                yaml.safe_dump(data, f, sort_keys=False)
        else:
            with open(path, "w") as f:
                json.dump(data, f, indent=indent)

    # ------------------------------------------------------------ template
    @classmethod
    def get_template_str(cls, indent: int = 0) -> str:
        """Commented json-ish template built from field descriptions."""
        pad = " " * indent
        lines = [f"{pad}{{", f"{pad}    # {cls.__name__}"]
        doc = (cls.__doc__ or "").strip().splitlines()
        for d in doc[:1]:
            lines.append(f"{pad}    # {d.strip()}")
        lines.append("")
        items = list(cls.model_fields.items())
        for i, (name, field) in enumerate(items):
            desc = field.description
            if desc:
                for dline in str(desc).splitlines():
                    lines.append(f"{pad}    # {dline.strip()}")
            annotation = field.annotation
            nested = _unwrap_config_type(annotation)
            if nested is not None:
                lines.append(f'{pad}    "{name}":')
                lines.append(nested.get_template_str(indent=indent + 4))
            else:
                default = field.default
                if isinstance(default, Enum):
                    default = default.value
                try:
                    rendered = json.dumps(_to_jsonable(default))
                except (TypeError, ValueError):
                    rendered = "null"
                lines.append(f'{pad}    "{name}": {rendered}')
            if i != len(items) - 1:
                lines[-1] += ","
            lines.append("")
        lines.append(f"{pad}}}")
        return "\n".join(lines)

    @classmethod
    def save_template(cls, path: str | Path) -> None:
        Path(path).write_text(cls.get_template_str() + "\n")


def _unwrap_config_type(annotation: Any) -> type | None:
    """Return the BaseConfig subclass inside an annotation, if any."""
    import typing

    if isinstance(annotation, type) and issubclass(annotation, BaseConfig):
        return annotation
    for arg in typing.get_args(annotation):
        if isinstance(arg, type) and issubclass(arg, BaseConfig):
            return arg
    return None
