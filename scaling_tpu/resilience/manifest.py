"""Checkpoint integrity manifest: write, verify, prune.

``MANIFEST.json`` sits inside every committed ``global_stepN/`` directory
and records, for each artifact file, its size and crc32 digest, plus the
step, a config fingerprint and a schema version. It is written LAST
(inside the staging dir, before the atomic rename), so its presence
implies every listed file was fully written — and its digests are
computed from the bytes the writer INTENDED where available, so even
write-time corruption (torn page, bad DMA) is caught on restore.

Checkpoints without a manifest (pre-manifest layouts, externally
produced trees, direct ``save_model_checkpoint`` callers) are accepted
as *legacy*: loadable, integrity unverified — backwards compatibility
with every existing checkpoint on disk.
"""

from __future__ import annotations

import json
import zlib
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from ..logging import logger

MANIFEST_NAME = "MANIFEST.json"
SCHEMA_VERSION = 1

_CHUNK = 1 << 20


def _is_optimizer_artifact(rel: str) -> bool:
    rel = Path(rel).as_posix()
    return rel.startswith("optimizer_state") or rel.startswith("orbax/optimizer/")


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity verification (or was unreadable)."""


def crc32_file(path: Path) -> Tuple[int, str]:
    """(size, crc32-hex) of a file, streamed."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return size, f"{crc & 0xFFFFFFFF:08x}"


def crc32_bytes(data: bytes) -> str:
    return f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"


def _iter_files(root: Path) -> Iterable[Path]:
    for p in sorted(root.rglob("*")):
        if p.is_file() and p.name != MANIFEST_NAME:
            yield p


def write_manifest(
    step_dir: Path,
    step: int,
    recorded: Optional[Dict[str, Tuple[int, str]]] = None,
    config_fingerprint: Optional[str] = None,
) -> Path:
    """Scan ``step_dir`` and write its manifest.

    ``recorded`` maps relpath -> (size, crc32) for files whose digests
    the writer computed from the in-memory bytes (npz writes); files not
    in it (context.json, config.yml, orbax trees) are digested from
    disk. Returns the manifest path; the caller fsyncs/renames.
    """
    step_dir = Path(step_dir)
    files: Dict[str, dict] = {}
    for p in _iter_files(step_dir):
        rel = p.relative_to(step_dir).as_posix()
        if recorded is not None and rel in recorded:
            size, digest = recorded[rel]
        else:
            size, digest = crc32_file(p)
        files[rel] = {"size": size, "crc32": digest}
    payload = {
        "schema_version": SCHEMA_VERSION,
        "step": step,
        "config_fingerprint": config_fingerprint,
        "files": files,
    }
    out = step_dir / MANIFEST_NAME
    out.write_text(json.dumps(payload, indent=1, sort_keys=True))
    return out


def read_manifest(step_dir: Path) -> Optional[dict]:
    """Parsed manifest, or None when absent. Raises
    CheckpointCorruptionError on an unparseable or future-schema one."""
    f = Path(step_dir) / MANIFEST_NAME
    if not f.is_file():
        return None
    try:
        payload = json.loads(f.read_text())
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointCorruptionError(f"{f}: unreadable manifest ({e})") from e
    if payload.get("schema_version", 0) > SCHEMA_VERSION:
        raise CheckpointCorruptionError(
            f"{f}: manifest schema {payload.get('schema_version')} is newer "
            f"than this build understands ({SCHEMA_VERSION})"
        )
    return payload


def verify_checkpoint(step_dir: Path, deep: bool = True) -> List[str]:
    """Integrity problems of ``step_dir`` ([] == loadable).

    With a manifest: every listed file must exist with the recorded size
    and (``deep``) crc32 digest. Without one (legacy checkpoint): accept
    when recognizable checkpoint artifacts are present, flag otherwise.
    """
    step_dir = Path(step_dir)
    if not step_dir.is_dir():
        return [f"{step_dir}: not a directory"]
    try:
        manifest = read_manifest(step_dir)
    except CheckpointCorruptionError as e:
        return [str(e)]
    if manifest is None:
        has_artifacts = (
            any(step_dir.glob("model_state_layer_*.npz"))
            or (step_dir / "orbax").is_dir()
            or (step_dir / "context.json").is_file()
        )
        if not has_artifacts:
            return [f"{step_dir}: no manifest and no recognizable checkpoint files"]
        logger.warning(
            f"{step_dir}: no MANIFEST.json (legacy checkpoint); "
            "integrity not verified"
        )
        return []
    problems: List[str] = []
    for rel, meta in manifest.get("files", {}).items():
        p = step_dir / rel
        if not p.is_file():
            if _is_optimizer_artifact(rel):
                # optimizer state is legitimately prunable by hand
                # (delete_past_optimizer_states rewrites the manifest,
                # but operators also rmtree it to save disk) — absence
                # is pruning, not corruption; the loader falls back to
                # fresh optimizer state as it always has
                logger.warning(
                    f"{step_dir}: optimizer artifact {rel} pruned "
                    "(listed in manifest but absent)"
                )
                continue
            problems.append(f"{rel}: listed in manifest but missing")
            continue
        size = p.stat().st_size
        if size != meta["size"]:
            problems.append(
                f"{rel}: size {size} != manifest {meta['size']} (truncated?)"
            )
            continue
        if deep:
            _, digest = crc32_file(p)
            if digest != meta["crc32"]:
                problems.append(
                    f"{rel}: crc32 {digest} != manifest {meta['crc32']} "
                    "(bit rot / torn write)"
                )
    return problems


def prune_manifest_entries(step_dir: Path, removed: Iterable[str]) -> None:
    """Drop deleted files from an old checkpoint's manifest.

    ``delete_past_optimizer_states`` legitimately removes optimizer
    files from committed checkpoints; without this the pruned checkpoint
    would look corrupt to the fallback scanner and be skipped forever.
    """
    step_dir = Path(step_dir)
    manifest = read_manifest(step_dir)
    if manifest is None:
        return
    removed = {Path(r).as_posix() for r in removed}
    files = manifest.get("files", {})
    kept = {rel: meta for rel, meta in files.items() if rel not in removed}
    if len(kept) == len(files):
        return
    manifest["files"] = kept
    manifest["optimizer_pruned"] = True
    from .guards import retry_io

    text = json.dumps(manifest, indent=1, sort_keys=True)
    retry_io(
        lambda: (step_dir / MANIFEST_NAME).write_text(text),
        what="pruned manifest rewrite",
    )
