"""Reshard-on-restore: continue a run on a different mesh shape.

The checkpoint format is already layout-independent — per-logical-layer
files holding GLOBAL arrays keyed by parameter path, with the pipeline
stage stacking undone by ``ckpt_view``/``ckpt_unview`` before disk — so
the mechanics of restoring onto a different mesh are: assemble each
param / optimizer leaf to its global value (host-streamed, one leaf at
a time — bounded memory) and re-slice it onto the NEW mesh via the
current metas' shardings. What this module adds is the POLICY around
those mechanics (ATP, arxiv 2301.08658 — adaptive re-parallelization on
world-size change):

- :func:`reshard_plan` — compare the checkpoint's ``MESH.json``
  signature against the restoring topology, pre-flight the logical
  param tree (:func:`.meshmeta.validate_param_tree` — a global-shape
  disagreement is a different model, never a reshard), and describe the
  transition for the obs rails;
- :func:`rescale_consumed_samples` — the data-stream contract across a
  reshard. The loader stream is a pure function of
  ``(seed, consumed_samples)`` and each step consumes one contiguous
  ``global_batch_size`` block, so the SAME global count resumes the
  stream with no sample skipped or repeated at any dp — provided the
  new ``micro_batch_size * dp`` grid divides it (validated here, with
  an actionable error when the operator picks an incompatible batch
  hierarchy);
- :func:`iter_global_leaves` — a mesh-free streaming reader over the
  committed npz artifacts (one leaf at a time through ``retry_io``),
  for tooling that reconstructs global arrays without building a model;
- the ``ckpt.reshard`` / ``restore.assemble`` fault points
  (docs/RESILIENCE.md): ``restore.assemble`` fires once per artifact
  file the leaf assembly reads, inside the trainer's bounded-retry load
  layer — a transient failure retries, a persistent one demotes the
  candidate and restore falls back to the newest valid checkpoint;
  ``ckpt.reshard`` fires once when the reshard path engages.

jax-free like the rest of the package; numpy is imported lazily by the
streaming reader only.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Dict, Iterator, Optional, Tuple

from ..logging import logger
from .faults import get_fault_plan
from .guards import retry_io
from .meshmeta import (
    mesh_matches,
    signature_label,
    topology_signature,
    validate_param_tree,
)


class ReshardError(ValueError):
    """The checkpoint cannot be resharded onto the requested topology
    (different model, or an incompatible batch hierarchy). Deliberately
    NOT a corruption error: falling back to an older checkpoint would
    hit the same wall — the config is wrong, not the disk."""


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """One restore's mesh transition, ready for logging/telemetry."""

    saved: Dict[str, int]
    restoring: Dict[str, int]

    @property
    def needed(self) -> bool:
        return self.saved != self.restoring

    @property
    def saved_label(self) -> str:
        return signature_label(self.saved)

    @property
    def restoring_label(self) -> str:
        return signature_label(self.restoring)

    def event_fields(self) -> dict:
        """Fields for the ``ckpt-reshard`` lifecycle event the restart
        timeline renders as a world-size transition."""
        return {
            "saved": self.saved_label,
            "restoring": self.restoring_label,
            "saved_world": self.saved["world_size"],
            "restoring_world": self.restoring["world_size"],
            "saved_hosts": self.saved["num_hosts"],
            "restoring_hosts": self.restoring["num_hosts"],
        }


def reshard_plan(
    mesh_meta: Optional[dict],
    current_topology: Dict[str, Any],
    current_params: Optional[Dict[str, dict]] = None,
) -> Optional[ReshardPlan]:
    """Decide whether this restore crosses mesh shapes.

    Returns None when no decision is possible or needed: a legacy
    checkpoint without ``MESH.json`` (same-shape restore assumed, as
    always) or a matching signature. Otherwise pre-flights the logical
    param tree and returns the transition; an incompatible tree raises
    :class:`ReshardError`.
    """
    if mesh_meta is None:
        return None
    if mesh_matches(mesh_meta, current_topology):
        return None
    plan = ReshardPlan(
        saved=topology_signature(mesh_meta.get("topology", {})),
        restoring=topology_signature(current_topology),
    )
    if current_params is not None:
        problems = validate_param_tree(mesh_meta, current_params)
        if problems:
            raise ReshardError(
                f"cannot reshard {plan.saved_label} -> "
                f"{plan.restoring_label}: " + "; ".join(problems)
            )
    return plan


def fire_reshard_point(step_dir: Path | str, plan: ReshardPlan) -> None:
    """The ``ckpt.reshard`` fault point: fired once per engaged reshard
    restore, before any leaf is re-sliced onto the new mesh."""
    get_fault_plan().fire("ckpt.reshard", path=step_dir)
    logger.info(
        f"resharding checkpoint {Path(step_dir).name}: "
        f"{plan.saved_label} -> {plan.restoring_label}"
    )


def rescale_consumed_samples(
    consumed_samples: int,
    *,
    micro_batch_size: int,
    data_parallel_size: int,
    what: str = "consumed_samples",
    on_misaligned: str = "error",
) -> int:
    """Carry the data cursor across a mesh change, skip/repeat-free.

    ``consumed_samples`` counts GLOBAL samples and each optimizer step
    consumes one contiguous ``global_batch_size`` block of the
    deterministic stream, so the count itself is mesh-independent — the
    "rescale" is the invariant that the same number resumes the stream
    exactly. The one hard constraint is the sampler's grid: the new
    ``micro_batch_size * data_parallel_size`` must divide the saved
    count, else micro-batch boundaries would land mid-stride and the
    loader (correctly) refuses. Validated here so a downsized relaunch
    fails with an actionable message at RESTORE time, not steps later
    inside the sampler.

    ``on_misaligned``: ``"error"`` (the TRAIN cursor — loss-exactness
    rides on it) raises; ``"floor"`` aligns DOWN to the nearest grid
    multiple with a warning — for the EVAL cursor, which advances by
    the OLD ``mbs * dp`` per eval micro-batch and so is legitimately
    not gbs-aligned: re-seeing a few eval samples is harmless, while
    hard-failing there would turn a viable downsize into budget
    exhaustion.
    """
    grid = micro_batch_size * data_parallel_size
    if grid <= 0:
        raise ReshardError(f"invalid batch grid mbs*dp = {grid}")
    if consumed_samples % grid != 0:
        if on_misaligned == "floor":
            aligned = (consumed_samples // grid) * grid
            logger.warning(
                f"{what} ({consumed_samples}) is not a multiple of the "
                f"new mbs*dp grid ({grid}); aligning down to {aligned} "
                f"({consumed_samples - aligned} sample(s) will be "
                "re-seen)"
            )
            return aligned
        raise ReshardError(
            f"{what} ({consumed_samples}) is not divisible by the new "
            f"micro_batch_size * data_parallel_size ({grid}): resuming "
            "here would split a micro-batch stride mid-step (samples "
            "skipped or repeated). Pick a batch hierarchy whose mbs*dp "
            f"divides {consumed_samples} — the saving run's "
            "global_batch_size always does"
        )
    return consumed_samples


# ------------------------------------------------- mesh-free leaf streaming
def iter_global_leaves(
    step_dir: Path | str,
    *,
    retry_attempts: int = 3,
    retry_backoff: float = 0.05,
) -> Iterator[Tuple[str, str, Any]]:
    """Stream ``(file_name, entry_name, global_array)`` for every model
    and optimizer artifact in a committed npz checkpoint — one array
    materialized at a time, each file read through ``retry_io`` with the
    ``restore.assemble`` fault point. This is the "any reader can
    reconstruct global arrays without the original mesh" contract
    MESH.json promises, usable without building a module or a mesh.
    """
    import numpy as np

    step_dir = Path(step_dir)
    files = sorted(step_dir.glob("model_state_layer_*.npz")) + sorted(
        step_dir.glob("optimizer_state_layer_*.npz")
    )
    for f in files:
        def _open(path=f):
            get_fault_plan().fire("restore.assemble", path=path)
            return np.load(path)

        z = retry_io(
            _open, attempts=retry_attempts, base_delay=retry_backoff,
            what=f"reshard assemble {f.name}",
        )
        try:
            for name in z.files:
                yield f.name, name, z[name]
        finally:
            z.close()
