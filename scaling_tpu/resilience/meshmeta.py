"""Mesh-shape-independent checkpoint metadata: ``MESH.json``.

Every checkpoint committed through :class:`.commit.CheckpointCommit`
carries, next to ``MANIFEST.json``, a ``MESH.json`` recording

- the **logical parameter tree**: for every parameter (and mirrored
  optimizer leaf) its meta key, GLOBAL shape, dtype, and per-axis
  sharding spec — enough for any reader to reconstruct global arrays
  from the on-disk artifacts without instantiating the saving mesh;
- the **saving topology**: pp / dp / cp / mp, virtual stages, token
  slices, world size, batch hierarchy, and the host count of the
  supervised pod that wrote it.

Restore compares the recorded topology against the restoring one
(:func:`mesh_matches`); a mismatch routes the load through the
reshard-aware path (:mod:`.reshard`) instead of assuming the shapes on
disk line up with the current mesh. Checkpoints WITHOUT a ``MESH.json``
(legacy layouts, external trees) restore exactly as before — at the
same shape, unverified (backward compatibility, pinned by test).

Like the rest of :mod:`scaling_tpu.resilience`, this module is
jax-free: the trainer hands it plain shapes/dtypes/spec strings.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional

from .manifest import CheckpointCorruptionError

MESH_NAME = "MESH.json"
MESH_SCHEMA_VERSION = 1

# the topology fields whose change means the on-disk layout was written
# by a DIFFERENT mesh than the one restoring (order fixed for rendering)
SIGNATURE_FIELDS = (
    "world_size",
    "pipe_parallel_size",
    "data_parallel_size",
    "context_parallel_size",
    "model_parallel_size",
    "pipe_virtual_size",
    "pipe_token_slices",
    "num_hosts",
)


def _spec_entry(part: Any) -> Any:
    """One partition-spec dim as JSON: None, an axis name, or a list of
    fused axis names."""
    if part is None or isinstance(part, str):
        return part
    if isinstance(part, (tuple, list)):
        return [str(p) for p in part]
    return str(part)


def param_record(shape, dtype, partition_spec) -> dict:
    """One leaf's logical record (global shape — never a shard's)."""
    return {
        "shape": [int(s) for s in shape],
        "dtype": str(dtype),
        "partition_spec": [_spec_entry(p) for p in (partition_spec or ())],
    }


def topology_signature(topo: Dict[str, Any]) -> Dict[str, Any]:
    """The layout-identity slice of a topology dict (missing fields
    default to the single-host / unsliced value, so legacy writers and
    minimal dicts compare cleanly)."""
    defaults = {"num_hosts": 1, "pipe_virtual_size": 1, "pipe_token_slices": 1}
    return {
        f: int(topo.get(f, defaults.get(f, 1)) or defaults.get(f, 1))
        for f in SIGNATURE_FIELDS
    }


def signature_label(topo: Dict[str, Any]) -> str:
    """Compact human label: ``world4·pp2·dp2·cp1·mp1·hosts1``."""
    sig = topology_signature(topo)
    parts = [
        f"world{sig['world_size']}",
        f"pp{sig['pipe_parallel_size']}",
        f"dp{sig['data_parallel_size']}",
        f"cp{sig['context_parallel_size']}",
        f"mp{sig['model_parallel_size']}",
    ]
    if sig["pipe_virtual_size"] > 1:
        parts.append(f"v{sig['pipe_virtual_size']}")
    if sig["pipe_token_slices"] > 1:
        parts.append(f"ts{sig['pipe_token_slices']}")
    parts.append(f"hosts{sig['num_hosts']}")
    return "·".join(parts)


def mesh_matches(meta: Dict[str, Any], current_topology: Dict[str, Any]) -> bool:
    """True when the checkpoint's saving topology and the restoring one
    are the same mesh shape (restore may take the plain path)."""
    return topology_signature(meta.get("topology", {})) == topology_signature(
        current_topology
    )


def build_mesh_meta(
    topology: Dict[str, Any],
    params: Dict[str, dict],
    optimizer: Optional[Dict[str, Any]] = None,
    step: Optional[int] = None,
) -> dict:
    """Assemble the MESH.json payload. ``params`` maps meta key ->
    :func:`param_record`; ``optimizer`` carries the optimizer-state
    layout facts a resharder needs (zero stage, partitioned-or-global)."""
    return {
        "schema_version": MESH_SCHEMA_VERSION,
        "step": step,
        "topology": dict(topology),
        "params": dict(params),
        "optimizer": dict(optimizer or {}),
    }


def write_mesh_meta(stage_dir: Path | str, meta: dict) -> Path:
    """Write ``MESH.json`` into a checkpoint STAGING dir (the atomic
    commit's manifest scan digests it like every other staged file, so
    it is covered by restore verification)."""
    out = Path(stage_dir) / MESH_NAME
    from .guards import retry_io

    text = json.dumps(meta, indent=1, sort_keys=True)
    retry_io(lambda: out.write_text(text), what="MESH.json stage write")
    return out


def read_mesh_meta(step_dir: Path | str) -> Optional[dict]:
    """Parsed ``MESH.json``, or None when absent (legacy checkpoint —
    restorable at the same shape only). Raises
    :class:`CheckpointCorruptionError` on an unparseable or
    future-schema file: a checkpoint CLAIMING mesh metadata it cannot
    deliver must not silently restore as legacy."""
    f = Path(step_dir) / MESH_NAME
    if not f.is_file():
        return None
    try:
        payload = json.loads(f.read_text())
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointCorruptionError(f"{f}: unreadable MESH.json ({e})") from e
    if not isinstance(payload, dict):
        raise CheckpointCorruptionError(f"{f}: MESH.json is not an object")
    if payload.get("schema_version", 0) > MESH_SCHEMA_VERSION:
        raise CheckpointCorruptionError(
            f"{f}: MESH.json schema {payload.get('schema_version')} is newer "
            f"than this build understands ({MESH_SCHEMA_VERSION})"
        )
    return payload


def validate_param_tree(
    meta: Dict[str, Any], current_params: Dict[str, dict]
) -> List[str]:
    """Reshard pre-flight: every key BOTH trees know must agree on the
    global shape ([] == compatible). Keys only one side has are left to
    the loader's allow-list policy (PEFT adds/drops adapters
    legitimately); a GLOBAL-shape disagreement can never be resharded —
    it is a different model, and re-slicing it would be wrong science."""
    problems: List[str] = []
    recorded = meta.get("params", {})
    for key, rec in current_params.items():
        old = recorded.get(key)
        if old is None:
            continue
        if list(old.get("shape", [])) != list(rec.get("shape", [])):
            problems.append(
                f"{key}: global shape {old.get('shape')} (saved) != "
                f"{rec.get('shape')} (restoring) — not a reshard, a "
                "different model"
            )
    return problems
