"""Verified restore: find the newest checkpoint that passes integrity.

The ``latest`` pointer is a hint, not the truth — after a crash it can
point at a checkpoint that later rotted on disk, or (legacy layouts,
pre-atomic-commit writers) at a half-written directory; it can also be
missing entirely while valid ``global_step*`` dirs sit next to it.
``select_checkpoint`` honors a *valid* ``latest`` exactly as before
(tests and tooling deliberately repoint it to replay older steps), and
otherwise scans newest-first for the most recent checkpoint that passes
:func:`..resilience.manifest.verify_checkpoint`, reporting exactly what
was skipped and why.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import List, Optional, Tuple

from ..logging import logger
from .manifest import CheckpointCorruptionError, verify_checkpoint

_STEP_RE = re.compile(r"^global_step(\d+)$")


def scan_step_dirs(base: Path | str) -> List[Tuple[int, Path]]:
    """``(step, dir)`` for every ``global_stepN`` child, newest first."""
    out = []
    for d in Path(base).iterdir() if Path(base).is_dir() else []:
        m = _STEP_RE.match(d.name)
        if m and d.is_dir():
            out.append((int(m.group(1)), d))
    return sorted(out, reverse=True)


def checkpoint_candidates(base: Path | str) -> List[Path]:
    """Candidate step dirs under ``base``, in restore-preference order:
    the ``latest``-pointed dir first (when it exists), then every other
    ``global_step*`` newest-first; ``base`` itself when it IS a step dir
    (direct loads: inference, export tooling)."""
    base = Path(base)
    cands: List[Path] = []
    pointed: Optional[Path] = None
    latest = base / "latest"
    if latest.is_file():
        from .guards import retry_io

        pointed = base / retry_io(
            latest.read_text, what="latest pointer read"
        ).strip()
        if pointed.is_dir():
            cands.append(pointed)
        else:
            logger.warning(
                f"latest pointer names {pointed.name!r} but no such "
                f"directory exists under {base}; falling back to a scan"
            )
            pointed = None
    newer_than_pointed = []
    for step, d in scan_step_dirs(base):
        if pointed is None or d != pointed:
            cands.append(d)
            if pointed is not None:
                m = _STEP_RE.match(pointed.name)
                if m and step > int(m.group(1)):
                    newer_than_pointed.append(d.name)
    if newer_than_pointed:
        # a crash between a commit's rename and its latest update leaves
        # the pointer lagging a newer committed checkpoint; latest is
        # still honored (replay workflows repoint it deliberately), but
        # the operator should know a newer step exists
        logger.warning(
            f"latest points at {pointed.name} but newer committed "
            f"checkpoint(s) exist: {', '.join(newer_than_pointed)} — "
            "repoint 'latest' (or remove it) to resume from the newest"
        )
    if not cands and (
        (base / "context.json").is_file()
        or any(base.glob("model_state_layer_*.npz"))
        or (base / "orbax").is_dir()
    ):
        cands.append(base)
    return cands


def select_checkpoint(
    base: Path | str, strict: bool = False, deep: bool = True
) -> Tuple[Optional[Path], List[str]]:
    """The newest checkpoint under ``base`` that verifies, plus the
    skip log (one line per rejected candidate, saying why).

    ``strict=True`` raises :class:`CheckpointCorruptionError` on the
    FIRST invalid candidate instead of falling back — for runs where
    silently resuming from an older step would invalidate the science.
    """
    skipped: List[str] = []
    for cand in checkpoint_candidates(base):
        problems = verify_checkpoint(cand, deep=deep)
        if not problems:
            if skipped:
                logger.warning(
                    f"restored from {cand} after skipping "
                    f"{len(skipped)} invalid checkpoint(s): "
                    + " | ".join(skipped)
                )
            return cand, skipped
        line = f"{cand.name}: {'; '.join(problems)}"
        if strict:
            raise CheckpointCorruptionError(
                f"checkpoint verification failed (strict mode): {line}"
            )
        logger.warning(f"skipping invalid checkpoint {line}")
        skipped.append(line)
    return None, skipped
