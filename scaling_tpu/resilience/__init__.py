"""Crash-consistency + fault-tolerance layer (ISSUE 3).

The subsystem that decides whether a preempted/crashed run loses ten
minutes or ten days (PAPERS: Megatron-LM's fault-tolerant harness). Four
pieces, all host-side (nothing here touches the lowered step program):

- :mod:`.faults` — deterministic fault injection. Production code calls
  ``get_fault_plan().fire("point")`` at named points; with no plan
  configured that is a counter bump and a dict lookup (no-op). Tests set
  ``SCALING_TPU_FAULTS`` to kill/fail/corrupt at precise moments.
- :mod:`.manifest` — per-checkpoint ``MANIFEST.json`` (file list, sizes,
  crc32 digests, step, config fingerprint, schema version) and its
  verifier.
- :mod:`.commit` — the atomic commit protocol: write into a
  ``.tmp-global_stepN`` staging dir, manifest, fsync, atomic rename,
  then the ``latest`` pointer. A kill at ANY instant leaves either the
  old committed checkpoint or the new one — never a half-written dir
  that ``latest`` points at.
- :mod:`.guards` — in-loop protection: bounded retry-with-backoff for
  transient I/O, the non-finite-loss budget, and a step-stall watchdog
  that dumps thread stacks.
- :mod:`.restore` — verified restore: scan ``global_step*`` newest-first
  for the most recent checkpoint that passes manifest verification.
- :mod:`.meshmeta` — ``MESH.json``: the logical param tree (global
  shapes, dtypes, sharding specs) plus the saving topology, written
  next to the manifest so any reader can reconstruct global arrays
  without the original mesh.
- :mod:`.reshard` — reshard-on-restore policy (ISSUE 12, elastic
  training): mesh-transition planning, the consumed-samples carry
  contract, a mesh-free streaming leaf reader, and the
  ``ckpt.reshard`` / ``restore.assemble`` fault points.
- :mod:`.resume` — ``run_with_resume``: bounded auto-restart from the
  newest valid checkpoint after a recoverable failure.
- :mod:`.controlplane` — the multi-host supervision channel (ISSUE 4):
  heartbeats, named barriers with timeouts, and broadcast flags over a
  shared directory or a coordinator TCP server; the out-of-band signal
  path beside the XLA collectives that a dead peer leaves hanging.

Import cost matters (subprocess restarts pay it on the reclaim critical
path), so nothing in this package imports jax at module level.

See docs/RESILIENCE.md for the operator-facing guide.
"""

from .commit import CheckpointCommit
from .controlplane import (
    BarrierTimeout,
    ControlPlane,
    FileControlPlane,
    JobAborted,
    TcpControlPlane,
    TcpControlPlaneServer,
    controlplane_from_env,
    straggler_table,
)
from .faults import FaultPlan, InjectedFault, get_fault_plan, set_fault_plan
from .guards import (
    NonFiniteGuard,
    NonFiniteLossError,
    StepStallWatchdog,
    dump_thread_stacks,
    retry_io,
)
from .manifest import (
    MANIFEST_NAME,
    CheckpointCorruptionError,
    prune_manifest_entries,
    verify_checkpoint,
    write_manifest,
)
from .meshmeta import (
    MESH_NAME,
    build_mesh_meta,
    mesh_matches,
    param_record,
    read_mesh_meta,
    signature_label,
    topology_signature,
    write_mesh_meta,
)
from .reshard import (
    ReshardError,
    ReshardPlan,
    fire_reshard_point,
    iter_global_leaves,
    rescale_consumed_samples,
    reshard_plan,
)
from .restore import scan_step_dirs, select_checkpoint
from .resume import run_with_resume

__all__ = [
    "CheckpointCommit",
    "BarrierTimeout",
    "ControlPlane",
    "FileControlPlane",
    "JobAborted",
    "TcpControlPlane",
    "TcpControlPlaneServer",
    "controlplane_from_env",
    "straggler_table",
    "FaultPlan",
    "InjectedFault",
    "get_fault_plan",
    "set_fault_plan",
    "NonFiniteGuard",
    "NonFiniteLossError",
    "StepStallWatchdog",
    "dump_thread_stacks",
    "retry_io",
    "MANIFEST_NAME",
    "CheckpointCorruptionError",
    "prune_manifest_entries",
    "verify_checkpoint",
    "write_manifest",
    "MESH_NAME",
    "build_mesh_meta",
    "mesh_matches",
    "param_record",
    "read_mesh_meta",
    "signature_label",
    "topology_signature",
    "write_mesh_meta",
    "ReshardError",
    "ReshardPlan",
    "fire_reshard_point",
    "iter_global_leaves",
    "rescale_consumed_samples",
    "reshard_plan",
    "scan_step_dirs",
    "select_checkpoint",
    "run_with_resume",
]
