"""Elastic capacity: auto size-back-up and train<->serve chip arbitration.

The supervisor downsizes onto survivors (``runner.downsize_after``) but a
pod that lost a host stays small forever unless an operator relaunches.
This module closes the loop in both directions, riding the SAME file
rails as the control plane (never ad-hoc sockets):

- **Announcements** (`capacity/announce/<name>.json`): a restored or
  standby host publishes ``{name, host, slots, incarnation}``. The
  supervisor watches the channel with :class:`UpsizeTracker` hysteresis
  — ``upsize_after`` CONSECUTIVE fresh observations of the SAME
  incarnation are required before an upsize fires, mirroring
  ``downsize_after`` on the way down. Every restore bumps the
  incarnation, so a flapping host resets its own streak by construction
  and can never churn the pod; a host that downsized the job must
  re-prove itself from zero (the tracker resets on every downsize).
- **Demand** (`capacity/demand.json`): the serving fleet heartbeats its
  pool pressure / queue depth / replica count.
- **Leases** (`capacity/lease-<host>.json`): the arbitration journal.
  One :class:`CapacityManager` (supervisor-side) moves a host between
  training and serving through an explicit state machine::

      granted -> active -> reclaiming -> released

  Sustained fleet pressure borrows a host from training (training
  drains + downsizes, the lease is written ``granted``, the fleet's
  placement planner spawns replicas there and marks it ``active``);
  sustained fleet idle triggers a reclaim (``reclaiming``, the fleet
  drains its replicas and writes ``released``, training upsizes). A
  lease stuck in ``granted`` past ``lease_timeout_s`` — the client died
  mid-handoff — is expired back to training, so a `capacity.lease`
  chaos kill leaves no orphaned host. Cooldowns plus the
  ``min_train_hosts`` / ``min_replicas`` floors bound the churn.

Every transition lands as a journaled ``capacity-*`` event on the obs
rails, and the three fault points ``capacity.upsize`` /
``capacity.lease`` / ``capacity.reclaim`` (docs in :mod:`.faults`) let
chaos drills kill or fail each leg mid-handoff.

The channel lives at ``<control_root>/capacity`` — deliberately OUTSIDE
the per-epoch control dirs the supervisor wipes at each relaunch, so
announcements and leases survive coordinator epochs. Writers only ever
replace whole files (same atomicity contract as
:class:`~.controlplane.FileControlPlane`), and every backend op rides
:func:`~.guards.retry_io`. Nothing here imports jax (resilience package
rule).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from ..logging import logger
from ..obs.spans import derive_trace_id, span, trace_context
from .faults import get_fault_plan
from .guards import retry_io

# default freshness horizon: an announcement or demand record older than
# this is treated as withdrawn (the publisher stopped heartbeating)
DEFAULT_STALE_S = 15.0

LEASE_STATES = ("granted", "active", "reclaiming", "released")


@dataclasses.dataclass(frozen=True)
class HostOffer:
    """One fresh capacity announcement, as observed by the supervisor."""

    name: str  # announcement identity (unique per standby unit)
    host: str  # hostname workers/replicas are spawned on
    slots: int
    incarnation: int
    age_s: float


@dataclasses.dataclass
class FleetDemand:
    """The serving fleet's newest demand heartbeat."""

    pressure: float  # 0..1 pool pressure (max across alive replicas)
    queue: int  # total queued requests across the fleet
    replicas: int  # alive replica count
    wall: float  # channel receipt stamp (reader's FS clock)

    def age(self, now: Optional[float] = None) -> float:
        return (now if now is not None else time.time()) - self.wall


@dataclasses.dataclass
class Lease:
    """One host's position in the train<->serve handoff state machine."""

    host: str
    slots: int
    state: str  # granted -> active -> reclaiming -> released
    since: float  # wall time of the last state transition
    epoch: int = 0  # training coordinator epoch at grant (diagnostics)
    reason: str = ""  # why the newest transition happened

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def outstanding(self) -> bool:
        """True while the host is NOT training's to use."""
        return self.state in ("granted", "active", "reclaiming")


def lease_trace(host: str, epoch: int) -> str:
    """The lease lifecycle's distributed-trace id: derived from the
    lease identity ``(host, epoch)``, so the manager's grant/reclaim and
    the fleet's activate/release — separate processes that never
    exchange a trace context — independently stamp the SAME trace
    (docs/OBSERVABILITY.md "Tracing"; same trick as checkpoint
    commits)."""
    return derive_trace_id("capacity-lease", host, epoch)


class CapacityChannel:
    """File rails for announcements, fleet demand, and the lease journal.

    Layout under ``root`` (conventionally ``<control_root>/capacity``)::

        announce/<name>.json   standby/restored capacity heartbeats
        demand.json            fleet pressure heartbeat (atomic replace)
        lease-<host>.json      arbitration journal, one file per host

    Freshness is judged by file mtime — one clock (the FS server's) for
    every record, same reasoning as the control plane's heartbeats.
    Leases carry no freshness: the journal is durable state, and a
    crashed participant is exactly what ``lease_timeout_s`` handles.
    """

    def __init__(self, root: Path | str):
        self.root = Path(root)
        (self.root / "announce").mkdir(parents=True, exist_ok=True)

    # -- shared atomic write (same contract as FileControlPlane) -------
    def _atomic_write(self, path: Path, text: str) -> None:
        tmp = path.with_name(
            f".{path.name}.tmp{os.getpid()}.{threading.get_ident()}"
        )
        tmp.write_text(text)
        os.replace(tmp, path)

    # -- announcements --------------------------------------------------
    def announce(self, name: str, host: str, slots: int,
                 incarnation: int) -> None:
        """Publish (or refresh) one standby unit's availability. Callers
        MUST bump ``incarnation`` on every restore — that is what makes
        a flap reset its own hysteresis streak."""
        rec = {"name": name, "host": host, "slots": int(slots),
               "incarnation": int(incarnation)}
        retry_io(
            lambda: self._atomic_write(
                self.root / "announce" / f"{name}.json", json.dumps(rec)
            ),
            what=f"capacity announce {name!r}",
        )

    def withdraw(self, name: str) -> None:
        """Remove an announcement (the unit went away again)."""
        def op():
            try:
                (self.root / "announce" / f"{name}.json").unlink()
            except FileNotFoundError:
                pass  # already consumed/withdrawn — the benign race

        retry_io(op, what=f"capacity withdraw {name!r}")

    # consume == withdraw; the separate name marks intent (the
    # supervisor absorbed the capacity, the unit did not vanish)
    consume = withdraw

    def offers(self, stale_s: float = DEFAULT_STALE_S,
               now: Optional[float] = None) -> Dict[str, HostOffer]:
        """Every FRESH announcement, keyed by name. Stale files are left
        in place (the publisher may resume heartbeating) but invisible."""
        return retry_io(
            lambda: self._offers_once(stale_s, now),
            what="capacity offers read",
        )

    def _offers_once(self, stale_s: float,
                     now: Optional[float]) -> Dict[str, HostOffer]:
        now = now if now is not None else time.time()
        out: Dict[str, HostOffer] = {}
        for f in (self.root / "announce").glob("*.json"):
            try:
                rec = json.loads(f.read_text())
                age = now - f.stat().st_mtime
                if age > stale_s:
                    continue
                offer = HostOffer(
                    name=str(rec["name"]), host=str(rec["host"]),
                    slots=int(rec["slots"]),
                    incarnation=int(rec["incarnation"]), age_s=age,
                )
                out[offer.name] = offer
            except (OSError, ValueError, KeyError, TypeError) as e:
                # reader racing the writer's first publish — transient
                logger.debug(f"unreadable announcement {f}: {e!r}")
        return out

    # -- fleet demand ---------------------------------------------------
    def publish_demand(self, pressure: float, queue: int,
                       replicas: int) -> None:
        rec = {"pressure": float(pressure), "queue": int(queue),
               "replicas": int(replicas)}
        retry_io(
            lambda: self._atomic_write(
                self.root / "demand.json", json.dumps(rec)
            ),
            what="capacity demand publish",
        )

    def read_demand(self, stale_s: float = DEFAULT_STALE_S,
                    now: Optional[float] = None) -> Optional[FleetDemand]:
        def op():
            f = self.root / "demand.json"
            try:
                rec = json.loads(f.read_text())
                wall = f.stat().st_mtime
            except FileNotFoundError:
                return None
            return FleetDemand(
                pressure=float(rec["pressure"]), queue=int(rec["queue"]),
                replicas=int(rec["replicas"]), wall=wall,
            )

        try:
            demand = retry_io(op, what="capacity demand read")
        except (ValueError, KeyError, TypeError) as e:
            logger.debug(f"unreadable demand record: {e!r}")
            return None
        if demand is None:
            return None
        if demand.age(now) > stale_s:
            return None  # the fleet stopped heartbeating — no demand
        return demand

    # -- lease journal --------------------------------------------------
    def _lease_path(self, host: str) -> Path:
        return self.root / f"lease-{host.replace('/', '_')}.json"

    def write_lease(self, lease: Lease) -> None:
        assert lease.state in LEASE_STATES, lease.state
        retry_io(
            lambda: self._atomic_write(
                self._lease_path(lease.host), json.dumps(lease.to_dict())
            ),
            what=f"lease write {lease.host!r}",
        )

    def read_leases(self) -> Dict[str, Lease]:
        return retry_io(self._read_leases_once, what="lease read")

    def _read_leases_once(self) -> Dict[str, Lease]:
        out: Dict[str, Lease] = {}
        for f in self.root.glob("lease-*.json"):
            try:
                out_lease = Lease(**json.loads(f.read_text()))
                out[out_lease.host] = out_lease
            except (OSError, ValueError, KeyError, TypeError) as e:
                logger.debug(f"unreadable lease {f}: {e!r}")
        return out

    def clear_lease(self, host: str) -> None:
        """Drop a lease the supervisor fully absorbed (post-upsize)."""
        def op():
            try:
                self._lease_path(host).unlink()
            except FileNotFoundError:
                pass

        retry_io(op, what=f"lease clear {host!r}")


class TcpCapacityChannel(CapacityChannel):
    """Capacity rails over the TCP control plane (no shared FS).

    Same surface as the file channel; records live in the coordinator's
    :class:`~.controlplane.TcpControlPlaneServer` under the ``cap_*``
    ops. Freshness uses server receipt stamps translated into this
    clock, exactly like heartbeat reads."""

    def __init__(self, address: str):
        # deliberately NOT calling super().__init__ — no directory
        from .controlplane import TcpControlPlane

        self._cp = TcpControlPlane(address, host_id=0, num_hosts=1)

    def _put(self, kind: str, name: str, record: dict) -> None:
        self._cp.capacity_set(kind, name, record)

    def _list(self, kind: str) -> Tuple[List[dict], float]:
        reply = self._cp.capacity_list(kind)
        offset = time.time() - float(reply.get("now") or time.time())
        return list(reply["records"]), offset

    def _del(self, kind: str, name: str) -> None:
        self._cp.capacity_del(kind, name)

    def announce(self, name: str, host: str, slots: int,
                 incarnation: int) -> None:
        self._put("announce", name, {
            "name": name, "host": host, "slots": int(slots),
            "incarnation": int(incarnation),
        })

    def withdraw(self, name: str) -> None:
        self._del("announce", name)

    consume = withdraw

    def offers(self, stale_s: float = DEFAULT_STALE_S,
               now: Optional[float] = None) -> Dict[str, HostOffer]:
        now = now if now is not None else time.time()
        records, offset = self._list("announce")
        out: Dict[str, HostOffer] = {}
        for rec in records:
            age = now - (float(rec["wall"]) + offset)
            if age > stale_s:
                continue
            offer = HostOffer(
                name=str(rec["name"]), host=str(rec["host"]),
                slots=int(rec["slots"]),
                incarnation=int(rec["incarnation"]), age_s=age,
            )
            out[offer.name] = offer
        return out

    def publish_demand(self, pressure: float, queue: int,
                       replicas: int) -> None:
        self._put("demand", "demand", {
            "pressure": float(pressure), "queue": int(queue),
            "replicas": int(replicas),
        })

    def read_demand(self, stale_s: float = DEFAULT_STALE_S,
                    now: Optional[float] = None) -> Optional[FleetDemand]:
        now = now if now is not None else time.time()
        records, offset = self._list("demand")
        if not records:
            return None
        rec = records[-1]
        demand = FleetDemand(
            pressure=float(rec["pressure"]), queue=int(rec["queue"]),
            replicas=int(rec["replicas"]), wall=float(rec["wall"]) + offset,
        )
        return None if demand.age(now) > stale_s else demand

    def write_lease(self, lease: Lease) -> None:
        assert lease.state in LEASE_STATES, lease.state
        self._put("lease", lease.host, lease.to_dict())

    def read_leases(self) -> Dict[str, Lease]:
        records, _ = self._list("lease")
        out: Dict[str, Lease] = {}
        for rec in records:
            rec = {k: v for k, v in rec.items() if k != "wall"}
            lease = Lease(**rec)
            out[lease.host] = lease
        return out

    def clear_lease(self, host: str) -> None:
        self._del("lease", host)


# ---------------------------------------------------------- pure policy
def classify_offers(
    offers: Dict[str, HostOffer],
    member_hosts: Set[str],
    leases: Dict[str, Lease],
) -> Dict[str, List[str]]:
    """Split fresh announcements into candidate / member / leased names.

    *member*: the announced hostname is already in the training pool
    (operator noise or a confused host — never upsize on it). For
    local slot-expansion pools pass ``member_hosts=set()``: there the
    hostname is always "localhost" and every announced slot is real
    additional capacity. *leased*: the hostname has an outstanding
    lease — it is the FLEET's until released, invisible to the upsize
    tracker. Pure function, mirrors :func:`..runner.supervise.classify_workers`.
    """
    out: Dict[str, List[str]] = {"candidate": [], "member": [], "leased": []}
    for name, offer in offers.items():
        lease = leases.get(offer.host)
        if lease is not None and lease.outstanding():
            out["leased"].append(name)
        elif offer.host in member_hosts:
            out["member"].append(name)
        else:
            out["candidate"].append(name)
    for bucket in out.values():
        bucket.sort()
    return out


class UpsizeTracker:
    """Hysteresis for size-back-up: a candidate must be observed fresh
    ``upsize_after`` CONSECUTIVE polls — same incarnation throughout —
    before it may trigger an upsize.

    Mirror image of ``downsize_after``'s consecutive-loss counter. The
    incarnation rule is what makes flap immunity *structural* rather
    than timing-dependent: a host that dies and re-announces bumps its
    incarnation, so even a flap faster than the poll cadence (invisible
    as an absence) resets the streak. Pure observation logic — no I/O,
    no clocks — so the flap drill is a deterministic unit test."""

    def __init__(self, upsize_after: int):
        assert upsize_after >= 1
        self.upsize_after = upsize_after
        # name -> (incarnation, consecutive fresh observations)
        self._streaks: Dict[str, Tuple[int, int]] = {}

    def observe(self, candidates: Dict[str, HostOffer]) -> List[str]:
        """Feed one poll's candidate offers; returns the names whose
        streak just reached maturity (stable order)."""
        matured: List[str] = []
        for name in list(self._streaks):
            if name not in candidates:
                del self._streaks[name]  # absence resets the streak
        for name, offer in candidates.items():
            inc, count = self._streaks.get(name, (offer.incarnation, 0))
            if inc != offer.incarnation:
                count = 0  # a restore happened between polls: re-prove
            count += 1
            self._streaks[name] = (offer.incarnation, count)
            if count >= self.upsize_after:
                matured.append(name)
        return sorted(matured)

    def forget(self, name: str) -> None:
        self._streaks.pop(name, None)

    def reset(self) -> None:
        """Every streak back to zero — called on each downsize so
        capacity that just failed the job must re-prove itself."""
        self._streaks.clear()


@dataclasses.dataclass
class ArbitrationPolicy:
    """Knobs for the train<->serve arbiter (all times in seconds)."""

    pressure_high: float = 0.5  # sustained pool pressure that borrows a host
    idle_low: float = 0.05  # pressure below this with an empty queue = idle
    sustain_s: float = 2.0  # how long pressure must hold before a lease
    idle_sustain_s: float = 2.0  # how long idle must hold before a reclaim
    cooldown_s: float = 5.0  # minimum gap between lease/reclaim decisions
    lease_timeout_s: float = 30.0  # granted-but-never-activated expiry
    min_train_hosts: int = 1  # training never lends below this
    min_replicas: int = 1  # never reclaim the fleet below this


class CapacityManager:
    """Arbitrates one shared host pool between training and serving.

    Same shape as the serving fleet's ``AutoscalePolicy``: ``decide``
    is fed observations (the clock, the fleet's demand heartbeat, the
    lease journal, training's world size) and returns at most one
    action — all I/O, journaling, and fault injection stay with the
    caller. Sustain windows and the cooldown are the only state."""

    def __init__(self, policy: Optional[ArbitrationPolicy] = None):
        self.policy = policy or ArbitrationPolicy()
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_action_at: Optional[float] = None

    def note_action(self, now: float) -> None:
        """Start the cooldown (the caller EXECUTED a decision)."""
        self._last_action_at = now
        self._pressure_since = None
        self._idle_since = None

    def _cooled(self, now: float) -> bool:
        return (self._last_action_at is None
                or now - self._last_action_at >= self.policy.cooldown_s)

    def decide(
        self,
        now: float,
        *,
        demand: Optional[FleetDemand],
        leases: Dict[str, Lease],
        train_world: int,
    ) -> Optional[tuple]:
        """At most one of:

        ``("expire", lease)`` — a ``granted`` lease the fleet never
        activated within ``lease_timeout_s``: the client died
        mid-handoff, the host goes straight back to training. Checked
        first and exempt from the cooldown — an orphaned host is a
        safety condition, not churn.

        ``("reclaim", lease)`` — sustained fleet idle on an ``active``
        lease, and the fleet would keep ``min_replicas`` without it.

        ``("lease", demand)`` — sustained fleet pressure, training above
        ``min_train_hosts``, and no lease already outstanding (one host
        in flight at a time keeps the journal trivially arbitrable).
        """
        p = self.policy
        for lease in leases.values():
            if (lease.state == "granted"
                    and now - lease.since > p.lease_timeout_s):
                return ("expire", lease)
        outstanding = [l for l in leases.values() if l.outstanding()]
        if demand is None:
            # no fleet heartbeat: demand is unknowable — never lease on
            # silence, and let active leases ride (the timeout above
            # only guards the granted-but-unclaimed window)
            self._pressure_since = None
            self._idle_since = None
            return None
        # sustain windows (explicit None checks: a window that opened at
        # t=0.0 is falsy but very much open)
        if demand.pressure >= p.pressure_high:
            if self._pressure_since is None:
                self._pressure_since = now
        else:
            self._pressure_since = None
        if demand.pressure <= p.idle_low and demand.queue == 0:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        if not self._cooled(now):
            return None
        active = [l for l in outstanding if l.state == "active"]
        if (self._idle_since is not None
                and now - self._idle_since >= p.idle_sustain_s
                and active
                and demand.replicas - 1 >= p.min_replicas):
            return ("reclaim", active[0])
        if (self._pressure_since is not None
                and now - self._pressure_since >= p.sustain_s
                and not outstanding
                and train_world - 1 >= p.min_train_hosts):
            return ("lease", demand)
        return None


# -------------------------------------------------- supervisor binding
class SupervisorCapacity:
    """The training supervisor's view of the capacity channel.

    ``poll`` is called from the epoch monitor loop; it throttles itself,
    feeds the hysteresis tracker, runs the arbiter, executes the
    journal-only transitions (reclaim initiation, expiry) in place, and
    returns the drain-requiring actions for the supervisor to execute
    at a step boundary:

    - ``("upsize", [HostOffer, ...])`` — announcements matured
    - ``("upsize-release", Lease)`` — the fleet released a leased host
    - ``("lease", FleetDemand)`` — the arbiter wants to lend a host
    """

    def __init__(
        self,
        channel: CapacityChannel,
        *,
        upsize_after: Optional[int] = None,
        manager: Optional[CapacityManager] = None,
        stale_s: float = DEFAULT_STALE_S,
        poll_interval_s: float = 0.5,
    ):
        self.channel = channel
        self.tracker = (
            UpsizeTracker(upsize_after) if upsize_after is not None else None
        )
        self.manager = manager
        self.stale_s = stale_s
        self.poll_interval_s = poll_interval_s
        self._next_poll = 0.0

    def poll(self, now: float, *, member_hosts: Set[str],
             train_world: int) -> Optional[tuple]:
        if now < self._next_poll:
            return None
        self._next_poll = now + self.poll_interval_s
        leases = self.channel.read_leases()
        # fleet gave a host back: training takes it at the next boundary
        for lease in leases.values():
            if lease.state == "released":
                return ("upsize-release", lease)
        if self.manager is not None:
            demand = self.channel.read_demand(self.stale_s, now=now)
            act = self.manager.decide(
                now, demand=demand, leases=leases, train_world=train_world,
            )
            if act is not None:
                kind, obj = act
                if kind == "expire":
                    self._reclaim(obj, now, reason="expired",
                                  to_state="released")
                elif kind == "reclaim":
                    self._reclaim(obj, now, reason="idle",
                                  to_state="reclaiming")
                else:  # lease — needs a training drain first
                    return act
        if self.tracker is not None:
            offers = self.channel.offers(self.stale_s, now=now)
            buckets = classify_offers(offers, member_hosts, leases)
            matured = self.tracker.observe(
                {n: offers[n] for n in buckets["candidate"]}
            )
            if matured:
                get_fault_plan().fire(
                    "capacity.upsize", path=",".join(matured)
                )
                return ("upsize", [offers[n] for n in matured])
        return None

    def _reclaim(self, lease: Lease, now: float, *, reason: str,
                 to_state: str) -> None:
        """Journal a reclaim initiation (idle) or an expiry (dead
        client). ``capacity.reclaim`` fires BEFORE the journal write —
        a chaos kill here leaves the lease in its prior state, which
        either side can resume from (granted re-expires, active
        re-reclaims)."""
        get_fault_plan().fire("capacity.reclaim", path=f"{reason}:{lease.host}")
        with trace_context(lease_trace(lease.host, lease.epoch)):
            with span("capacity.reclaim", host=lease.host, reason=reason):
                self.channel.write_lease(dataclasses.replace(
                    lease, state=to_state, since=now, reason=reason,
                ))
            logger.log_event(
                "capacity-reclaim", host=lease.host, state=to_state,
                reason=reason,
            )
        if self.manager is not None:
            self.manager.note_action(now)

    def grant(self, host: str, slots: int, *, epoch: int,
              now: Optional[float] = None) -> Lease:
        """Journal a lease grant (the drain already completed; training
        no longer occupies ``host``). ``capacity.lease`` fires BEFORE
        the write: a kill here means no lease exists — the caller keeps
        the host and relaunches at full size, nothing orphaned."""
        now = now if now is not None else time.time()
        get_fault_plan().fire("capacity.lease", path=f"grant:{host}")
        lease = Lease(host=host, slots=slots, state="granted", since=now,
                      epoch=epoch, reason="pressure")
        # one trace per lease lifecycle: grant/activate/reclaim/release
        # derive the SAME id from (host, epoch) on whichever side —
        # manager or fleet — performs the transition, so the whole
        # handoff reads as one cross-process trace in obs trace
        with trace_context(lease_trace(host, epoch)):
            with span("capacity.grant", host=host, slots=slots):
                self.channel.write_lease(lease)
            logger.log_event(
                "capacity-lease", host=host, slots=slots, state="granted",
                epoch=epoch,
            )
        if self.manager is not None:
            self.manager.note_action(now)
        return lease

    def absorb(self, action: tuple) -> None:
        """Consume the channel state behind an EXECUTED upsize so it can
        never retrigger: matured announcements are withdrawn, a
        released lease is cleared from the journal."""
        kind = action[0]
        if kind == "upsize":
            for offer in action[1]:
                self.channel.consume(offer.name)
                if self.tracker is not None:
                    self.tracker.forget(offer.name)
        elif kind == "upsize-release":
            self.channel.clear_lease(action[1].host)
        if self.manager is not None:
            self.manager.note_action(time.time())

    def on_downsize(self) -> None:
        """A downsize happened: every upsize streak starts over (the
        capacity that shrank the job must re-prove itself)."""
        if self.tracker is not None:
            self.tracker.reset()


# -------------------------------------------------------- fleet binding
class FleetCapacityClient:
    """The serving fleet's side of the handoff.

    The fleet loop calls :meth:`publish` every tick (self-throttled
    demand heartbeat), spawns replicas on :meth:`granted` leases and
    :meth:`activate`\\ s them, and on :meth:`reclaiming` leases drains
    the host's replicas then :meth:`release`\\ s. All journal writes are
    idempotent whole-file replaces — a crashed fleet repeats them
    safely after relaunch."""

    def __init__(self, channel: CapacityChannel, *,
                 publish_interval_s: float = 0.5):
        self.channel = channel
        self.publish_interval_s = publish_interval_s
        self._next_publish = 0.0

    def publish(self, *, pressure: float, queue: int, replicas: int,
                now: Optional[float] = None) -> None:
        now = now if now is not None else time.time()
        if now < self._next_publish:
            return
        self._next_publish = now + self.publish_interval_s
        self.channel.publish_demand(pressure, queue, replicas)

    def granted(self) -> List[Lease]:
        return [l for l in self.channel.read_leases().values()
                if l.state == "granted"]

    def activate(self, lease: Lease,
                 now: Optional[float] = None) -> Lease:
        """granted -> active, AFTER the replica on the leased host came
        up. ``capacity.lease`` fires before the write: a kill here
        leaves the lease ``granted``, which the manager expires back to
        training after ``lease_timeout_s`` — the crashed fleet cannot
        strand the host."""
        now = now if now is not None else time.time()
        get_fault_plan().fire("capacity.lease", path=f"activate:{lease.host}")
        out = dataclasses.replace(lease, state="active", since=now,
                                  reason="activated")
        with trace_context(lease_trace(lease.host, lease.epoch)):
            with span("capacity.activate", host=lease.host):
                self.channel.write_lease(out)
            logger.log_event(
                "capacity-lease", host=lease.host, slots=lease.slots,
                state="active",
            )
        return out

    def reclaiming(self) -> List[Lease]:
        return [l for l in self.channel.read_leases().values()
                if l.state == "reclaiming"]

    def release(self, lease: Lease, now: Optional[float] = None) -> Lease:
        """reclaiming -> released, AFTER the host's replicas drained."""
        now = now if now is not None else time.time()
        out = dataclasses.replace(lease, state="released", since=now,
                                  reason="drained")
        with trace_context(lease_trace(lease.host, lease.epoch)):
            with span("capacity.release", host=lease.host):
                self.channel.write_lease(out)
            logger.log_event(
                "capacity-lease", host=lease.host, slots=lease.slots,
                state="released",
            )
        return out
