"""Atomic checkpoint commit protocol.

A checkpoint is written entirely inside a dot-prefixed staging directory
(``.tmp-global_stepN`` — invisible to the ``global_step*`` globs the
loader, the fallback scanner and the optimizer-state pruner use), then:

1. ``MANIFEST.json`` is written from the recorded/scanned digests
   (fault point ``ckpt.manifest``);
2. every staged file and the staging dir itself are fsynced;
3. the staging dir is atomically renamed onto ``global_stepN``
   (fault point ``ckpt.rename``; an existing dir from a crash-recovery
   re-reach of the same step is removed first);
4. the parent dir is fsynced, then the ``latest`` pointer is updated via
   its own write-tmp-then-rename.

A ``kill -9`` at any instant therefore leaves either the previous
committed checkpoint (staging debris is swept by the next save) or the
new one — never a half-written directory that ``latest`` points at.

Works the same for both backends: the npz writer records per-file
digests as it serializes; orbax writes its tree into the staging dir and
is digested from disk at manifest time.
"""

from __future__ import annotations

import os
import shutil
from pathlib import Path
from typing import Dict, Optional, Tuple

from ..logging import logger
from ..obs.spans import span
from .faults import get_fault_plan
from .manifest import write_manifest

TMP_PREFIX = ".tmp-global_step"
LATEST_NAME = "latest"


def _fsync_path(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class CheckpointCommit:
    """One checkpoint save's staging dir + commit sequence.

    Thread contract: ``record`` and ``finalize``/``update_latest`` run
    either all on the caller's thread (sync save) or all on the single
    async-writer thread in FIFO order — never concurrently.
    """

    def __init__(self, base: Path | str, step: int,
                 config_fingerprint: Optional[str] = None):
        self.base = Path(base)
        self.step = step
        self.config_fingerprint = config_fingerprint
        self.final_dir = self.base / f"global_step{step}"
        self.tmp_dir = self.base / f"{TMP_PREFIX}{step}"
        self._recorded: Dict[str, Tuple[int, str]] = {}
        self.sweep_stale_tmp(self.base)
        if self.tmp_dir.exists():
            shutil.rmtree(self.tmp_dir)
        self.tmp_dir.mkdir(parents=True)

    @staticmethod
    def sweep_stale_tmp(base: Path) -> None:
        """Remove staging debris left by crashed saves (never committed,
        so never loadable — safe to delete unconditionally)."""
        for stale in Path(base).glob(f"{TMP_PREFIX}*"):
            logger.warning(f"removing stale checkpoint staging dir {stale}")
            shutil.rmtree(stale, ignore_errors=True)

    def record(self, path: Path | str, size: int, crc32_hex: str) -> None:
        """Register the intended (size, crc32) of a file written under
        the staging dir, so the manifest detects write-time corruption."""
        rel = Path(path).resolve().relative_to(self.tmp_dir.resolve()).as_posix()
        self._recorded[rel] = (size, crc32_hex)

    def finalize(self) -> Path:
        """Manifest -> fsync -> atomic rename. Returns the final dir.

        Traced as ``ckpt.manifest`` (digest + manifest write) and
        ``ckpt.rename`` (the fsync walk + atomic rename — on slow shared
        storage the fsync walk IS the commit cost, so it belongs to the
        rename phase the analyzer breaks out)."""
        plan = get_fault_plan()
        plan.fire("ckpt.manifest", path=self.tmp_dir)
        with span("ckpt.manifest", step=self.step):
            write_manifest(
                self.tmp_dir, self.step, recorded=self._recorded,
                config_fingerprint=self.config_fingerprint,
            )
        with span("ckpt.rename", step=self.step):
            # npz writes fsync themselves; sync the rest (manifest, context,
            # config, orbax tree) plus every directory so the rename never
            # commits names whose contents are still in flight
            for p in sorted(self.tmp_dir.rglob("*")):
                if p.is_file() and p.suffix != ".npz":
                    _fsync_path(p)
                elif p.is_dir():
                    _fsync_path(p)
            _fsync_path(self.tmp_dir)
            plan.fire("ckpt.rename", path=self.final_dir)
            if self.final_dir.exists():
                # crash recovery re-reached this step; replace the old save
                shutil.rmtree(self.final_dir)
            os.replace(self.tmp_dir, self.final_dir)
            _fsync_path(self.base)
        return self.final_dir

    def update_latest(self) -> None:
        """Atomically point ``latest`` at the committed step."""
        tmp = self.base / f"{LATEST_NAME}.tmp"
        tmp.write_text(self.final_dir.name)
        _fsync_path(tmp)
        os.replace(tmp, self.base / LATEST_NAME)
        _fsync_path(self.base)
