"""In-loop guards: bounded I/O retry, non-finite-loss budget, stall watchdog.

All host-side — nothing here enters the jitted step program, so the
lowered HLO (and the analysis goldens pinned against it) is unchanged.
"""

from __future__ import annotations

import math
import sys
import threading
import time
import traceback
from typing import Callable, Optional, Tuple, Type

from ..logging import logger

DEFAULT_RETRY_ATTEMPTS = 3
DEFAULT_RETRY_BACKOFF_SECONDS = 0.05


class NonFiniteLossError(RuntimeError):
    """The non-finite budget was exhausted; carries the diagnosis."""


def retry_io(
    fn: Callable,
    *,
    attempts: int = DEFAULT_RETRY_ATTEMPTS,
    base_delay: float = DEFAULT_RETRY_BACKOFF_SECONDS,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    what: str = "i/o operation",
):
    """Call ``fn()``; on a transient error retry with exponential backoff.

    Deterministic (no jitter): delay doubles each attempt starting at
    ``base_delay``. The final failure re-raises the original exception.
    Only use around idempotent operations (index-based reads, whole-file
    writes) — a retried side effect must be safe to repeat.
    """
    assert attempts >= 1
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts:
                logger.error(
                    f"{what} failed after {attempts} attempt(s): {e!r}"
                )
                raise
            delay = base_delay * (2 ** (attempt - 1))
            logger.warning(
                f"{what} failed (attempt {attempt}/{attempts}): {e!r}; "
                f"retrying in {delay:.3f}s"
            )
            time.sleep(delay)


class NonFiniteGuard:
    """Skip-then-abort policy for overflow/NaN training signals.

    Sits ON TOP of the dynamic loss scaler: the scaler already turns a
    NaN-grad step into a no-op update plus a scale backoff, which rides
    out isolated bursts; this guard bounds how long a PERSISTENT
    non-finite condition (diverged optimum, poisoned data shard, sick
    chip) is allowed to burn pod-hours. ``observe`` returns True while
    the budget tolerates the streak; once more than ``budget``
    consecutive non-finite observations arrive it raises
    :class:`NonFiniteLossError` with a diagnosis (the caller saves a
    checkpoint first so the run can be resumed from a finite state).
    """

    def __init__(self, budget: int):
        assert budget >= 0
        self.budget = budget
        self.streak = 0

    def observe(self, step: int, loss: Optional[float],
                overflow: Optional[bool], loss_scale: Optional[float]) -> bool:
        nonfinite = bool(overflow) or (
            loss is not None and not math.isfinite(loss)
        )
        if not nonfinite:
            self.streak = 0
            return True
        self.streak += 1
        logger.warning(
            f"non-finite training signal at step {step}: loss={loss} "
            f"overflow={overflow} loss_scale={loss_scale} "
            f"({self.streak}/{self.budget} consecutive tolerated)"
        )
        if self.streak <= self.budget:
            return True
        raise NonFiniteLossError(
            f"aborting after {self.streak} consecutive non-finite steps "
            f"(budget {self.budget}): last step {step}, loss={loss}, "
            f"overflow={overflow}, loss_scale={loss_scale}. Likely causes: "
            "diverged optimization (check LR/warmup), a poisoned data "
            "shard (check consumed_samples against the data manifest), "
            "or bad hardware. Resume from the checkpoint just saved — or "
            "an earlier one if the saved state is already non-finite."
        )


def dump_thread_stacks() -> str:
    """Every thread's current Python stack, formatted (stall forensics)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


class StepStallWatchdog:
    """Background thread that fires when the train loop stops beating.

    The loop calls ``beat(step)`` at the top of every iteration; if no
    beat arrives for ``timeout_s`` the watchdog logs every thread's
    stack (the post-mortem for hung collectives, wedged storage mounts,
    stuck data workers) and invokes ``on_stall(step, elapsed)`` once per
    stall. It cannot safely snapshot device state mid-step (the jitted
    step donates its input buffers), so saving is the callback's job at
    the next safe point — the trainer's default callback flags
    preemption, which saves-and-exits the moment the step completes.
    """

    # Deliberately lock-free cross-thread scalars: the main loop writes
    # ``_last_beat`` (a monotonic float) and ``_step`` (an int) in
    # ``beat()``; the watchdog thread only READS them, and a torn or
    # stale read merely shifts one poll's staleness verdict by one
    # interval — GIL-atomic scalar handoff, a lock here would make the
    # per-step beat contend with the poll loop for nothing.
    # sta: lock(_last_beat, _step)

    def __init__(self, timeout_s: float,
                 on_stall: Optional[Callable[[int, float], None]] = None,
                 poll_interval_s: Optional[float] = None):
        assert timeout_s > 0
        self.timeout_s = timeout_s
        self.on_stall = on_stall
        self._poll = poll_interval_s or min(timeout_s / 4, 1.0)
        self._last_beat = time.monotonic()
        self._step = 0
        self._fired_for_beat: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stall_count = 0

    def start(self) -> None:
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()

    def beat(self, step: int) -> None:
        self._step = step
        self._last_beat = time.monotonic()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            last = self._last_beat
            elapsed = time.monotonic() - last
            if elapsed < self.timeout_s or self._fired_for_beat == last:
                continue
            self._fired_for_beat = last  # once per stall, not per poll
            self.stall_count += 1
            logger.error(
                f"step stall: no progress for {elapsed:.1f}s "
                f"(timeout {self.timeout_s}s) after step {self._step}; "
                f"thread stacks follow\n{dump_thread_stacks()}"
            )
            if self.on_stall is not None:
                try:
                    self.on_stall(self._step, elapsed)
                except Exception as e:
                    logger.error(f"watchdog on_stall callback failed: {e!r}")
