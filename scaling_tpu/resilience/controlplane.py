"""Multi-host control plane: heartbeats, named barriers, broadcast flags.

The supervision layer (ISSUE 4) needs a tiny out-of-band channel beside
the XLA collectives: collectives can tell you *nothing* when a peer host
is gone — they just hang. The control plane is that channel. Two
backends with one contract:

- :class:`FileControlPlane` — a shared directory (tests, single-machine
  fake pods, NFS-backed pods). Heartbeats are atomic file replaces,
  barriers are arrival files, flags are files. No daemon.
- :class:`TcpControlPlane` — a line-JSON socket server on the
  coordinator host (run by the supervisor or host 0) for real pods
  where the hosts share no filesystem.

Contract (both backends):

- ``heartbeat(step)`` publishes this host's liveness + progress; a
  SIGKILLed host simply stops publishing.
- ``peer_heartbeats()`` returns every host's newest record — the
  supervisor's dead/hung detection and the watchdog's straggler table
  read this.
- ``barrier(name, timeout_s)`` blocks until all ``num_hosts`` arrive at
  ``name``. It raises :class:`BarrierTimeout` when peers never show
  (the caller must NOT proceed — that is the commit-barrier guarantee)
  and :class:`JobAborted` as soon as the supervisor raises the abort
  flag, so survivors of a dead host exit in seconds, not after the
  full barrier timeout.
- ``set_flag``/``get_flag`` broadcast small strings: coordinated
  preemption (``preempt``), supervisor teardown (``abort``).

Barrier names are namespaced per coordinator epoch by construction: the
supervisor hands every epoch a fresh control-plane root, so a relaunch
can never observe arrivals from the dead epoch.

Fault points (docs in :mod:`.faults`): ``barrier.timeout`` fires on
every barrier entry — arm ``kill``/``hang`` to make this host die or
stall exactly between its work and the rendezvous.

Nothing here imports jax (resilience package rule: subprocess restarts
pay the import cost on the reclaim critical path).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import socket
import threading
import time
from pathlib import Path
from typing import Dict, Optional

from ..logging import logger
from ..obs.registry import get_registry
from ..obs.spans import span
from .faults import get_fault_plan
from .guards import retry_io

ENV_CONTROL_DIR = "SCALING_TPU_CONTROL_DIR"
ENV_CONTROL_ADDR = "SCALING_TPU_CONTROL_ADDR"
ENV_HOST_ID = "SCALING_TPU_HOST_ID"
ENV_NUM_HOSTS = "SCALING_TPU_NUM_HOSTS"
ENV_COORD_EPOCH = "SCALING_TPU_COORD_EPOCH"

PREEMPT_FLAG = "preempt"
ABORT_FLAG = "abort"
# raised alongside PREEMPT when the drain was triggered by a step-stall
# watchdog, not an operator: the supervisor must treat the resulting
# clean exit as a failure to relaunch, not a finished run
STALL_FLAG = "stall"

DEFAULT_BARRIER_POLL_S = 0.05


class BarrierTimeout(RuntimeError):
    """Peers never arrived: a host is dead/hung, or the net partitioned."""


class JobAborted(RuntimeError):
    """The supervisor raised the abort flag: stop waiting and exit."""


@dataclasses.dataclass
class HostHeartbeat:
    host: int
    step: int
    status: str
    wall: float  # publisher's time.time() at publish

    def age(self, now: Optional[float] = None) -> float:
        return (now if now is not None else time.time()) - self.wall

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ControlPlane:
    """Backend-independent surface; see module docstring for semantics."""

    def __init__(self, host_id: int, num_hosts: int):
        assert 0 <= host_id < num_hosts
        self.host_id = host_id
        self.num_hosts = num_hosts
        self._last_step = 0

    # -- backend hooks --------------------------------------------------
    def _publish_heartbeat(self, record: HostHeartbeat) -> None:
        raise NotImplementedError

    def _read_heartbeats(self) -> Dict[int, HostHeartbeat]:
        raise NotImplementedError

    def _arrive(self, name: str) -> None:
        raise NotImplementedError

    def _arrived_count(self, name: str) -> int:
        raise NotImplementedError

    def _prune_barrier(self, name: str) -> None:
        raise NotImplementedError

    def set_flag(self, name: str, value: str = "1") -> None:
        raise NotImplementedError

    def get_flag(self, name: str) -> Optional[str]:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def clock_offset(self) -> float:
        """This host's wall clock minus the control plane's reference
        clock, in seconds (best effort; 0.0 when the backend has no
        shared clock). Subtracting it from local ``time.time()`` stamps
        maps them onto the ONE reference clock every host shares — the
        same skew-immune trick the heartbeat staleness math uses — which
        is what lets ``obs trace`` order one request's records across
        hosts. Each host logs it as a ``clock-offset`` event at startup
        so the alignment survives into the run dir."""
        return 0.0

    # -- shared logic ---------------------------------------------------
    def heartbeat(self, step: int, status: str = "running") -> None:
        self._last_step = step
        t0 = time.perf_counter()
        self._publish_heartbeat(
            HostHeartbeat(self.host_id, step, status, time.time())
        )
        # send lag is the leading indicator for control-plane storage
        # trouble (NFS degradation, coordinator overload) — a heartbeat
        # that takes seconds to publish will read as a stale host soon
        lag = time.perf_counter() - t0
        reg = get_registry()
        labels = {"host": str(self.host_id)}
        reg.gauge("controlplane_heartbeat_send_seconds", labels).set(lag)
        reg.histogram("controlplane_heartbeat_send", labels).observe(lag)

    def peer_heartbeats(self) -> Dict[int, HostHeartbeat]:
        """Newest record per host (own host included)."""
        return self._read_heartbeats()

    def arrive(self, name: str) -> None:
        """Register arrival at ``name`` WITHOUT waiting.

        For exit paths that will never re-enter the loop (preemption at
        this boundary): peers may already be parked inside this
        barrier, and a host that exits without registering would leave
        them waiting out the full timeout."""
        self._arrive(name)

    def prune_barrier(self, name: str) -> None:
        """Drop a barrier's arrival state once no host can ever wait on
        it again (the lockstep protocol guarantees this for barriers two
        steps behind). Without pruning, a per-step barrier accrues state
        for the life of the epoch — millions of entries on a long run."""
        self._prune_barrier(name)

    def barrier(
        self,
        name: str,
        timeout_s: float,
        poll_s: float = DEFAULT_BARRIER_POLL_S,
    ) -> None:
        """Block until all ``num_hosts`` arrive at ``name``.

        Raises :class:`JobAborted` the moment the abort flag appears
        (supervisor teardown must not wait out the timeout) and
        :class:`BarrierTimeout` when the deadline passes with hosts
        missing.

        Traced as a ``barrier.wait`` span per host: the wait time is the
        straggler signal the run-dir analyzer attributes offline (the
        host that waits ~0 arrived last — it made everyone else wait),
        the SPMD analogue of per-mesh-axis communication-time accounting
        (arxiv 1811.02084). A timeout/abort lands as ``ok=false`` with
        the exception type."""
        get_fault_plan().fire("barrier.timeout", path=name)
        with span("barrier.wait", barrier=name, host=self.host_id):
            self._barrier_wait(name, timeout_s, poll_s)

    def _barrier_wait(self, name: str, timeout_s: float, poll_s: float) -> None:
        self._arrive(name)
        deadline = time.monotonic() + timeout_s
        next_hb = time.monotonic() + 1.0
        # each poll costs two backend round trips (arrivals + abort
        # flag) — on the TCP backend, two connections. Lockstep peers
        # arrive near-simultaneously, so the fast path resolves in the
        # first poll or two at full responsiveness; a LONG wait (a slow
        # peer's multi-minute checkpoint write ahead of the commit
        # barrier) backs off toward 1s so N parked hosts don't hammer
        # the serial coordinator for the whole write
        sleep_s = poll_s
        while True:
            arrived = self._arrived_count(name)
            if arrived >= self.num_hosts:
                return
            if self.get_flag(ABORT_FLAG) is not None:
                raise JobAborted(
                    f"abort flag raised while waiting at barrier {name!r} "
                    f"({arrived}/{self.num_hosts} arrived)"
                )
            if time.monotonic() >= deadline:
                raise BarrierTimeout(
                    f"barrier {name!r} timed out after {timeout_s}s: "
                    f"{arrived}/{self.num_hosts} hosts arrived "
                    "(a peer is dead, hung, or partitioned)"
                )
            if time.monotonic() >= next_hb:
                # waiting at a barrier is ALIVE — keep the supervisor's
                # staleness detector pointed at truly wedged hosts
                self._publish_heartbeat(HostHeartbeat(
                    self.host_id, self._last_step, f"barrier:{name}",
                    time.time(),
                ))
                next_hb = time.monotonic() + 1.0
            time.sleep(sleep_s)
            sleep_s = min(sleep_s * 1.5, 1.0)


# ---------------------------------------------------------------- file
class FileControlPlane(ControlPlane):
    """Shared-directory backend: atomic file replaces carry every record.

    Layout under ``root``::

        heartbeat/host<K>.json   newest heartbeat per host (atomic replace)
        barrier/<name>/host<K>   arrival marker files
        flags/<name>             flag value file

    Writers only ever replace whole files via ``os.replace``, so readers
    never observe torn records. Works on any filesystem with atomic
    rename (local disk, NFS close-to-open is fine for these tiny files).

    Every backend op rides :func:`retry_io` (same resilience rule the
    TCP client applies to its requests): on the documented NFS-backed
    pod use of this backend, one transient ESTALE/EIO during a
    per-iteration heartbeat must not crash a healthy worker and burn a
    restart-budget slot. All ops are idempotent whole-file replaces or
    reads, so a repeat is safe.
    """

    def __init__(self, root: Path | str, host_id: int, num_hosts: int):
        super().__init__(host_id, num_hosts)
        self.root = Path(root)
        for sub in ("heartbeat", "barrier", "flags"):
            (self.root / sub).mkdir(parents=True, exist_ok=True)

    def _atomic_write(self, path: Path, text: str) -> None:
        # pid AND thread id: the async checkpoint writer refreshes the
        # heartbeat from a barrier wait while the main loop publishes its
        # own — same process, two threads, must never share a temp path
        tmp = path.with_name(
            f".{path.name}.tmp{os.getpid()}.{threading.get_ident()}"
        )
        tmp.write_text(text)
        os.replace(tmp, path)

    def _publish_heartbeat(self, record: HostHeartbeat) -> None:
        retry_io(
            lambda: self._atomic_write(
                self.root / "heartbeat" / f"host{record.host}.json",
                json.dumps(record.to_dict()),
            ),
            what="heartbeat publish",
        )

    def _read_heartbeats(self) -> Dict[int, HostHeartbeat]:
        return retry_io(self._read_heartbeats_once, what="heartbeat read")

    def _read_heartbeats_once(self) -> Dict[int, HostHeartbeat]:
        out: Dict[int, HostHeartbeat] = {}
        for f in (self.root / "heartbeat").glob("host*.json"):
            try:
                rec = json.loads(f.read_text())
                # staleness must not compare the PUBLISHER's wall clock
                # against the reader's: the file mtime comes from ONE
                # clock (the FS server's) for every record, so
                # per-publisher skew drops out of the age math — only
                # the single reader<->server offset remains (NTP-sized)
                rec["wall"] = f.stat().st_mtime
                out[int(rec["host"])] = HostHeartbeat(**rec)
            except (OSError, ValueError, KeyError, TypeError) as e:
                # a reader racing the writer's very first publish; the
                # atomic replace makes this transient, never torn
                logger.debug(f"unreadable heartbeat {f}: {e!r}")
        return out

    def _barrier_dir(self, name: str) -> Path:
        # flatten: barrier names may carry ':' / '/' (commit:step-6)
        safe = name.replace("/", "_").replace(":", "_")
        return self.root / "barrier" / safe

    def _arrive(self, name: str) -> None:
        def op():
            d = self._barrier_dir(name)
            d.mkdir(parents=True, exist_ok=True)
            self._atomic_write(d / f"host{self.host_id}", "1")

        retry_io(op, what=f"barrier arrival {name!r}")

    def _arrived_count(self, name: str) -> int:
        def op():
            d = self._barrier_dir(name)
            if not d.is_dir():
                return 0
            return sum(1 for _ in d.glob("host*"))

        return retry_io(op, what=f"barrier count {name!r}")

    def _prune_barrier(self, name: str) -> None:
        # concurrent pruners race benignly: whoever loses sees ENOENT
        shutil.rmtree(self._barrier_dir(name), ignore_errors=True)

    def set_flag(self, name: str, value: str = "1") -> None:
        retry_io(
            lambda: self._atomic_write(self.root / "flags" / name, value),
            what=f"flag set {name!r}",
        )

    def get_flag(self, name: str) -> Optional[str]:
        def op():
            try:
                return (self.root / "flags" / name).read_text()
            except FileNotFoundError:
                return None  # absent flag — the common case, not an error

        return retry_io(op, what=f"flag read {name!r}")

    def clock_offset(self) -> float:
        """Local wall clock vs the FS server's: write a probe and
        compare its mtime (stamped by the ONE server clock all hosts'
        heartbeat walls already come from) to local ``time.time()``.
        Includes the write latency — NTP-sized accuracy, which is what
        cross-host trace ordering needs, not perfection."""
        def op():
            probe = self.root / "heartbeat" / f".clock{self.host_id}"
            self._atomic_write(probe, "1")
            return time.time() - probe.stat().st_mtime

        try:
            return retry_io(op, what="clock probe")
        except OSError:
            return 0.0  # alignment is best-effort, never fatal


# ----------------------------------------------------------------- tcp
class TcpControlPlaneServer:
    """Coordinator-side state holder for :class:`TcpControlPlane`.

    One connection per request, newline-delimited JSON in both
    directions — trivially robust, and the request rate (a heartbeat +
    a few barrier polls per host per step; long barrier waits back off
    to ~1s between polls) is far below any socket limit.
    Run it on the supervisor or host 0; workers connect with the
    address from ``SCALING_TPU_CONTROL_ADDR``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._hb: Dict[int, dict] = {}
        self._barriers: Dict[str, set] = {}
        self._flags: Dict[str, str] = {}
        self._capacity: Dict[str, Dict[str, dict]] = {}
        self._lock = threading.Lock()
        # stays raw: one-time server bind at startup — a port conflict
        # or bad address is a config error that must abort loudly, not
        # retry (client REQUESTS ride retry_io; see _request)
        self._sock = socket.socket(  # sta: disable=STA011
            socket.AF_INET, socket.SOCK_STREAM
        )
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="controlplane-server", daemon=True
        )
        self._thread.start()

    # requests are sub-KiB JSON lines; anything bigger is garbage (a
    # client streaming bytes with no newline must not buffer unboundedly)
    MAX_REQUEST_BYTES = 64 * 1024

    def _serve(self) -> None:
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed under us during shutdown
            # one short-lived thread per connection: an idle prober that
            # connects and sends nothing otherwise parks the SERIAL
            # accept loop for its full 5s read timeout, freezing every
            # host's heartbeat publish — repeated probes could push a
            # healthy host past heartbeat_timeout. Threads are bounded
            # by the read timeout, so a flood drains itself.
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(5.0)
                data = conn.makefile("r").readline(self.MAX_REQUEST_BYTES)
                if len(data) >= self.MAX_REQUEST_BYTES and not data.endswith("\n"):
                    raise ValueError(
                        f"request line exceeds {self.MAX_REQUEST_BYTES} bytes"
                    )
                reply = self._handle(json.loads(data))
                conn.sendall((json.dumps(reply) + "\n").encode())
        except Exception as e:
            # every handler must survive ANY malformed request (stray
            # port scanner, version-skewed worker sending json without
            # the expected keys): an uncaught error here would kill the
            # thread silently and drop the client's reply with no
            # diagnosis
            logger.warning(f"control-plane request failed: {e!r}")

    def _handle(self, req: dict) -> dict:
        with self._lock:
            op = req.get("op")
            if op == "hb":
                rec = dict(req["record"])
                # receipt-stamp with the SERVER clock: staleness math
                # must never compare a worker's wall clock against the
                # supervisor's (skew > heartbeat_timeout would make a
                # healthy host read as hung forever)
                rec["wall"] = time.time()
                self._hb[int(req["host"])] = rec
                return {"ok": True}
            if op == "peers":
                # `now` (server clock) lets the client translate record
                # walls into its own clock before computing ages
                return {"ok": True, "peers": list(self._hb.values()),
                        "now": time.time()}
            if op == "arrive":
                self._barriers.setdefault(req["name"], set()).add(
                    int(req["host"])
                )
                return {"ok": True}
            if op == "count":
                return {
                    "ok": True,
                    "count": len(self._barriers.get(req["name"], ())),
                }
            if op == "prune":
                self._barriers.pop(req["name"], None)
                return {"ok": True}
            if op == "set_flag":
                self._flags[req["name"]] = req["value"]
                return {"ok": True}
            if op == "get_flag":
                return {"ok": True, "value": self._flags.get(req["name"])}
            # capacity rails (resilience.capacity.TcpCapacityChannel):
            # kind-scoped key/value records — announcements, the fleet
            # demand heartbeat, and the lease journal — receipt-stamped
            # with the server clock like heartbeats, so staleness math
            # never mixes publisher clocks
            if op == "cap_set":
                rec = dict(req["record"])
                rec["wall"] = time.time()
                self._capacity.setdefault(req["kind"], {})[req["name"]] = rec
                return {"ok": True}
            if op == "cap_list":
                return {
                    "ok": True,
                    "records": list(
                        self._capacity.get(req["kind"], {}).values()
                    ),
                    "now": time.time(),
                }
            if op == "cap_del":
                self._capacity.get(req["kind"], {}).pop(req["name"], None)
                return {"ok": True}
            return {"ok": False, "error": f"unknown op {op!r}"}

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError as e:
            logger.debug(f"control-plane server close: {e!r}")
        self._thread.join(timeout=5)


class TcpControlPlane(ControlPlane):
    """Client for :class:`TcpControlPlaneServer` (``address`` =
    ``host:port``)."""

    def __init__(self, address: str, host_id: int, num_hosts: int,
                 connect_timeout_s: float = 5.0):
        super().__init__(host_id, num_hosts)
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = connect_timeout_s

    def _request_once(self, req: dict) -> dict:
        with socket.create_connection(self._addr, self._timeout) as conn:
            conn.sendall((json.dumps(req) + "\n").encode())
            return json.loads(conn.makefile("r").readline())

    def _request(self, req: dict) -> dict:
        # every heartbeat/flag check/barrier poll is a fresh connection
        # against a serial coordinator — a momentary accept-backlog
        # overflow or reset during a rendezvous burst must not kill a
        # healthy host (resilience rule: transient I/O gets a bounded
        # retry). Protocol errors (ok=false) are NOT transient and are
        # never retried.
        reply = retry_io(
            lambda: self._request_once(req),
            retry_on=(OSError, ValueError),
            what=f"control-plane request {req.get('op')!r}",
        )
        if not reply.get("ok"):
            raise RuntimeError(f"control-plane request {req} failed: {reply}")
        return reply

    def _publish_heartbeat(self, record: HostHeartbeat) -> None:
        self._request(
            {"op": "hb", "host": record.host, "record": record.to_dict()}
        )

    def _read_heartbeats(self) -> Dict[int, HostHeartbeat]:
        reply = self._request({"op": "peers"})
        # record walls are server-clock receipt stamps; shift them into
        # THIS clock so HostHeartbeat.age() against local time is sane
        offset = time.time() - float(reply.get("now") or time.time())
        out: Dict[int, HostHeartbeat] = {}
        for r in reply["peers"]:
            rec = HostHeartbeat(**r)
            rec.wall += offset
            out[int(rec.host)] = rec
        return out

    def _arrive(self, name: str) -> None:
        self._request({"op": "arrive", "name": name, "host": self.host_id})

    def _arrived_count(self, name: str) -> int:
        return int(self._request({"op": "count", "name": name})["count"])

    def _prune_barrier(self, name: str) -> None:
        self._request({"op": "prune", "name": name})

    def set_flag(self, name: str, value: str = "1") -> None:
        # flag writes are rare, high-signal control events (abort /
        # preempt broadcast) — worth a span each
        with span("cp.set_flag", flag=name, host=self.host_id,
                  level="debug"):
            self._request({"op": "set_flag", "name": name, "value": value})

    def get_flag(self, name: str) -> Optional[str]:
        return self._request({"op": "get_flag", "name": name})["value"]

    def clock_offset(self) -> float:
        """Local wall clock vs the coordinator's: the ``peers`` reply
        already ships the server's ``now`` (the stamp heartbeat
        staleness is computed against); the request round trip bounds
        the error."""
        try:
            with span("cp.clock_probe", host=self.host_id, level="debug"):
                reply = self._request({"op": "peers"})
            return time.time() - float(reply.get("now") or time.time())
        except (RuntimeError, OSError):
            return 0.0  # alignment is best-effort, never fatal

    # -- elastic-capacity records (resilience.capacity rails) -----------
    # Sends live HERE, next to the server's dispatch table, so the
    # STA013 contract check sees client and handler together; the
    # capacity channel composes these instead of hand-rolling op dicts.
    def capacity_set(self, kind: str, name: str, record: dict) -> None:
        with span("cp.cap_set", kind=kind, key=name, level="debug"):
            self._request({"op": "cap_set", "kind": kind, "name": name,
                           "record": record})

    def capacity_list(self, kind: str) -> dict:
        """Reply dict: ``records`` (each stamped with server-receipt
        ``wall``) plus ``now``, the server clock at read time — the pair
        callers need to translate freshness into their own clock."""
        with span("cp.cap_list", kind=kind, level="debug"):
            reply = self._request({"op": "cap_list", "kind": kind})
        return {"records": reply["records"], "now": reply["now"]}

    def capacity_del(self, kind: str, name: str) -> None:
        with span("cp.cap_del", kind=kind, key=name, level="debug"):
            self._request({"op": "cap_del", "kind": kind, "name": name})


# ------------------------------------------------------------- helpers
def controlplane_from_env() -> Optional[ControlPlane]:
    """Build the control plane a launcher described in the environment.

    ``SCALING_TPU_CONTROL_DIR`` selects the file backend,
    ``SCALING_TPU_CONTROL_ADDR`` (``host:port``) the TCP backend; both
    need ``SCALING_TPU_HOST_ID`` + ``SCALING_TPU_NUM_HOSTS``. Returns
    None when nothing is configured (single-host runs pay nothing)."""
    control_dir = os.environ.get(ENV_CONTROL_DIR)
    control_addr = os.environ.get(ENV_CONTROL_ADDR)
    if not control_dir and not control_addr:
        return None
    host_id = int(os.environ.get(ENV_HOST_ID, "0"))
    num_hosts = int(os.environ.get(ENV_NUM_HOSTS, "1"))
    if control_dir:
        cp = FileControlPlane(control_dir, host_id, num_hosts)
    else:
        cp = TcpControlPlane(control_addr, host_id, num_hosts)
    # every env-launched participant stamps its skew into the run dir
    # once at startup, so obs trace can clock-align its records
    log_clock_offset(cp)
    return cp


def log_clock_offset(cp: ControlPlane) -> None:
    """Emit one ``clock-offset`` event: this host's wall clock minus the
    control plane's reference clock. ``obs trace`` subtracts it from the
    host's record timestamps, mapping every host's events onto the one
    shared clock (the skew-immune stamp the heartbeat staleness math
    already trusts) — finite, ordered cross-host timelines."""
    logger.log_event(
        "clock-offset", _level="debug", host=cp.host_id,
        offset_s=round(cp.clock_offset(), 6),
    )


def straggler_table(
    heartbeats: Dict[int, HostHeartbeat],
    num_hosts: int,
    stale_after_s: float,
    now: Optional[float] = None,
) -> "StragglerReport":
    """Classify every expected host from its newest heartbeat.

    A host with no heartbeat at all or one older than ``stale_after_s``
    is *dead* (SIGKILLed processes stop publishing; hung ones stop
    progressing); the rest are ranked by staleness so the watchdog can
    tell "peer host 2 is dead" apart from "we are the straggler"."""
    now = now if now is not None else time.time()
    rows = []
    dead = []
    for host in range(num_hosts):
        hb = heartbeats.get(host)
        if hb is None:
            rows.append((host, None, None, "never-heartbeat"))
            dead.append(host)
            continue
        age = hb.age(now)
        state = "dead" if age > stale_after_s else hb.status
        if age > stale_after_s:
            dead.append(host)
        rows.append((host, hb.step, age, state))
    return StragglerReport(rows=rows, dead_hosts=dead)


@dataclasses.dataclass
class StragglerReport:
    rows: list  # (host, step|None, age_s|None, state)
    dead_hosts: list

    def render(self) -> str:
        lines = [f"{'host':>4} {'step':>6} {'hb_age_s':>9} state"]
        for host, step, age, state in self.rows:
            lines.append(
                f"{host:>4} {step if step is not None else '-':>6} "
                f"{f'{age:.1f}' if age is not None else '-':>9} {state}"
            )
        return "\n".join(lines)
