"""Deterministic fault injection.

Production code paths call ``get_fault_plan().fire("<point>")`` at named
injection points; with no plan configured that is a counter bump (the
hooks are no-ops in production). Tests arm a plan — via the
``SCALING_TPU_FAULTS`` environment variable (inherited by training
subprocesses, so a parent test can kill a child at an exact write) or
programmatically with :func:`set_fault_plan` — to fail, kill, or corrupt
at precise, reproducible moments.

Named injection points wired into the framework:

==================  =====================================================
point               fired
==================  =====================================================
``ckpt.write``      once per checkpoint file write, BEFORE the bytes land
                    (``checkpoint._write_npz``); ``corrupt`` applies to
                    the file AFTER the write completes
``ckpt.manifest``   before ``MANIFEST.json`` is written
                    (``CheckpointCommit.finalize``)
``ckpt.rename``     after the manifest, before the atomic
                    tmp-dir -> final-dir rename
``data.read``       once per dataloader micro-batch read
                    (``DataLoader.__next__`` — the single retry/fault
                    layer for dataset reads, memory-mapped included)
``step.nan_grads``  once per train step after the jitted step returns;
                    the ``nan`` action poisons the OBSERVED loss
                    (params stay clean — it emulates a transient
                    hardware NaN burst for the non-finite policy)
``signal.sigterm``  at the top of every ``run_training`` loop iteration;
                    the ``sigterm`` action delivers a real SIGTERM to
                    this process (exercises the preemption path)
``host.kill``       at the top of every ``run_training`` loop iteration
                    (next to ``signal.sigterm``); arm ``kill`` with an
                    ``@host=K`` selector to crash exactly one host of a
                    supervised pod at an exact step boundary
``host.hang``       same site; arm ``hang`` to wedge one host's loop
                    forever (the supervisor's stale-heartbeat detection
                    is the only thing that notices)
``barrier.timeout`` on every control-plane ``barrier()`` entry, BEFORE
                    this host registers its arrival — ``kill`` here dies
                    between the host's work and the rendezvous (the
                    commit-barrier crash window)
``ckpt.commit_barrier``  in ``save_checkpoint`` after this host's shard
                    commit (manifest + rename done), before entering the
                    ``commit:step-N`` barrier — the precise "committed
                    my shard, never told the others" window
``ckpt.reshard``    once per ENGAGED reshard restore (the checkpoint's
                    ``MESH.json`` topology differs from the restoring
                    one), before any leaf is re-sliced onto the new
                    mesh (``resilience.reshard.fire_reshard_point``)
``restore.assemble``  once per checkpoint artifact file opened for leaf
                    assembly during restore
                    (``checkpoint._load_artifact`` and the mesh-free
                    ``reshard.iter_global_leaves`` reader); ``fail``
                    here is an OSError inside the trainer's bounded-
                    retry load layer — transient failures retry, a
                    persistent one demotes the candidate and restore
                    falls back to the newest valid checkpoint
``serve.tick``      at the top of every serving-engine tick
                    (``serve.engine.ServeEngine.tick``) — ``kill`` here
                    is the crash-replay drill's mid-tick crash; the
                    request journal plus a supervised relaunch replay
                    the incomplete requests token-exactly
``serve.admit``     once per ``ServeEngine.submit`` call, before the
                    admission/backpressure decision
``serve.journal``   once per request-journal append
                    (``serve.journal.RequestJournal``); ``fail`` is an
                    IOError at the journal write
``serve.pool``      once per KV-block allocation batch
                    (``serve.scheduler.ContinuousBatchingScheduler``'s
                    block grants — admission, growth, CoW forks)
``serve.replica.spawn``  HOST-side, once per replica subprocess launch
                    (``serve.replica_proc`` — initial spawns, supervised
                    relaunches, autoscale spawns); ``fail`` here is an
                    OSError the fleet supervisor's budgeted backoff
                    absorbs
``serve.replica.rpc``  WORKER-side, at the top of every handled RPC
                    request (submit/poll/stats/drain); ``fail`` drops
                    that reply — the host's ``retry_io`` layer retries,
                    which is exactly the at-least-once window the
                    idempotent ops are designed for. Network sub-actions
                    (advisory, applied by the handler): ``delay`` sleeps
                    ~0.25s before serving (slow link), ``partition``
                    drops the REQUEST before it is processed (the op
                    never happened), ``drop`` serves the request and
                    then drops the REPLY — the precise admitted-but-
                    unacknowledged window idempotent submit exists for
``serve.replica.net_partition``  WORKER-side, before every handled RPC
                    is even looked at; arm ``partition@N xM @host=K`` to
                    cut one fake host off the network for a window of M
                    RPCs — the host-mode partition drill (retries, zero
                    duplicate admissions)
``serve.replica.rendezvous``  on every rendezvous-file op: the worker's
                    address publish, the host's reads while waiting for
                    a spawned replica, and the atomic worker-config
                    write (``serve.replica_proc``); ``fail`` is an
                    OSError inside the ``retry_io`` layer all sides ride
``serve.replica.teardown``  HOST-side, before force-killing one replica
                    worker (bench teardown reaching through ssh for
                    remote replicas); ``fail`` aborts that kill — the
                    drill for a teardown that cannot reach its host
``serve.replica.kill``  WORKER-side, before each engine tick while the
                    replica has work; ``kill@N@host=K`` (workers export
                    ``SCALING_TPU_HOST_ID=<replica_id>``, or the fake
                    host id in host mode) SIGKILLs exactly one replica —
                    or every replica of one host — mid-stream: the chaos
                    e2e's journal-exact failover drill
``capacity.upsize``  supervisor-side, when announced capacity MATURES
                    through the upsize hysteresis, before the drain is
                    relayed (``resilience.capacity.SupervisorCapacity``);
                    ``kill`` here dies between the decision and the
                    coordinated save — the relaunched supervisor simply
                    re-observes the still-announcing host
``capacity.lease``  both sides of the train->serve handoff: before the
                    supervisor's lease-grant journal write
                    (``SupervisorCapacity.grant``, path ``grant:<host>``)
                    and before the fleet's activation write
                    (``FleetCapacityClient.activate``, path
                    ``activate:<host>``). A kill at either write leaves
                    the journal in the PRIOR state, which arbitrates the
                    handoff: no grant -> training keeps the host;
                    granted-but-never-active -> the manager expires the
                    lease back to training after ``lease_timeout_s`` —
                    no orphaned host either way
``capacity.reclaim``  before the reclaim/expiry journal write
                    (``reclaiming`` on sustained fleet idle, path
                    ``idle:<host>``; ``released`` on a dead-client
                    expiry, path ``expire:<host>``); a kill leaves the
                    lease in its prior state, which either side resumes
                    from (granted re-expires, active re-reclaims)
==================  =====================================================

Spec grammar (comma list): ``point=action[@N][xM][@host=K][@epoch=E]``
— fire ``action`` on hits ``N .. N+M-1`` of ``point`` (1-based; ``N``
defaults to 1, ``M`` to 1, ``x*`` means every hit from ``N`` on). The
same point may appear in SEVERAL entries (e.g. two ``host.kill`` rules
scoped to different hosts — the chaos downsize drill's 3→2→1 script);
every rule sees every hit and the first armed match fires. ``@host=K``
scopes the rule to the host whose ``SCALING_TPU_HOST_ID`` environment
variable equals ``K`` (supervised multi-host runs export it per worker);
``@epoch=E`` scopes it to supervisor relaunch epoch ``E``
(``SCALING_TPU_COORD_EPOCH``). On non-matching hosts/epochs — or
outside a supervised launch — the rule never fires, though hits are
still counted. Actions:

- ``kill``    SIGKILL this process (no cleanup runs — a real crash)
- ``fail``    raise :class:`InjectedFault` (an ``IOError``, so the
              bounded-retry guards treat it as transient)
- ``sigterm`` deliver SIGTERM to this process
- ``hang``    block this thread forever (emulates a wedged host: a hung
              collective, a dead storage mount — only heartbeat
              staleness can detect it)
- ``corrupt`` advisory: returned to the call site, which truncates the
              file it just wrote (write-time corruption; manifest
              digests are computed from the intended bytes, so restore
              detects it)
- ``nan``     advisory: returned to the call site, which poisons the
              observed loss
- ``drop``    advisory: the RPC handler serves the request, then drops
              the reply on the floor (reply lost in the partition)
- ``delay``   advisory: the RPC handler sleeps before serving (a slow
              or congested link)
- ``partition``  advisory: the RPC handler discards the request before
              processing (the packet never arrived)

Example: ``SCALING_TPU_FAULTS="ckpt.write=kill@13,data.read=fail@1x2"``;
host-scoped: ``SCALING_TPU_FAULTS="host.kill=kill@5@host=1"``.
"""

from __future__ import annotations

import os
import re
import signal
from typing import Dict, List, Optional

from ..logging import logger

ENV_VAR = "SCALING_TPU_FAULTS"

ACTIONS = ("kill", "fail", "sigterm", "hang", "corrupt", "nan",
           "drop", "delay", "partition")

# actions fire() executes itself; "corrupt"/"nan" are advisory returns
_EXECUTED = ("kill", "fail", "sigterm", "hang")

HOST_ID_ENV = "SCALING_TPU_HOST_ID"
EPOCH_ENV = "SCALING_TPU_COORD_EPOCH"

_SPEC_RE = re.compile(
    r"^(?P<point>[a-z_.]+)=(?P<action>[a-z]+)"
    r"(?:@(?P<first>\d+))?(?:x(?P<count>\d+|\*))?"
    r"(?:@host=(?P<host>\d+))?(?:@epoch=(?P<epoch>\d+))?$"
)


class InjectedFault(IOError):
    """A deliberately injected transient I/O failure (retryable)."""


class _Rule:
    __slots__ = ("action", "first", "count", "host", "epoch")

    def __init__(self, action: str, first: int, count: Optional[int],
                 host: Optional[int] = None, epoch: Optional[int] = None):
        self.action = action
        self.first = first
        self.count = count  # None -> every hit from `first` on
        self.host = host  # None -> any host
        self.epoch = epoch  # None -> any supervisor epoch

    def matches(self, hit: int) -> bool:
        if self.host is not None:
            # read at fire time, not parse time: tests flip host identity
            # without rebuilding the plan
            here = os.environ.get(HOST_ID_ENV)
            if here is None or int(here) != self.host:
                return False
        if self.epoch is not None:
            now = os.environ.get(EPOCH_ENV)
            if now is None or int(now) != self.epoch:
                return False
        if hit < self.first:
            return False
        return self.count is None or hit < self.first + self.count


class FaultPlan:
    """Parsed injection plan + per-point hit counters."""

    def __init__(self, spec: str = ""):
        self.spec = spec
        # several rules may arm the SAME point (host-/epoch-scoped chaos
        # scripts); each hit consults them in spec order
        self._rules: Dict[str, List[_Rule]] = {}
        self._hits: Dict[str, int] = {}
        for entry in filter(None, (s.strip() for s in spec.split(","))):
            m = _SPEC_RE.match(entry)
            if not m:
                raise ValueError(
                    f"bad fault spec entry {entry!r}; expected "
                    "point=action[@N][xM] (e.g. ckpt.write=kill@13)"
                )
            action = m.group("action")
            if action not in ACTIONS:
                raise ValueError(
                    f"unknown fault action {action!r} in {entry!r}; "
                    f"one of {ACTIONS}"
                )
            count = m.group("count")
            host = m.group("host")
            epoch = m.group("epoch")
            self._rules.setdefault(m.group("point"), []).append(_Rule(
                action,
                int(m.group("first") or 1),
                None if count == "*" else int(count or 1),
                int(host) if host is not None else None,
                int(epoch) if epoch is not None else None,
            ))

    def hits(self, point: str) -> int:
        return self._hits.get(point, 0)

    def fire(self, point: str, path=None) -> Optional[str]:
        """Count a hit at ``point``; execute/return the armed action.

        Returns the action name for advisory actions (``corrupt``,
        ``nan``) so the call site applies them, None when nothing fired.
        ``fail`` raises, ``kill``/``sigterm`` signal this process.
        """
        hit = self._hits.get(point, 0) + 1
        self._hits[point] = hit
        rule = next(
            (r for r in self._rules.get(point, ()) if r.matches(hit)), None
        )
        if rule is None:
            return None
        if rule.action in _EXECUTED:
            logger.warning(
                f"FAULT INJECTION: {rule.action} at {point} (hit {hit}"
                f"{f', path={path}' if path else ''})"
            )
        if rule.action == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        if rule.action == "hang":
            # a wedged host: no exception, no exit — only the missing
            # heartbeats give it away to the supervisor
            import time

            while True:
                time.sleep(60)
        if rule.action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            return None
        if rule.action == "fail":
            raise InjectedFault(
                f"injected fault at {point} (hit {hit}"
                f"{f', path={path}' if path else ''})"
            )
        return rule.action  # advisory: "corrupt" / "nan"

    @staticmethod
    def corrupt_file(path) -> None:
        """Truncate ``path`` to half its size (write-time corruption)."""
        from pathlib import Path

        p = Path(path)
        size = p.stat().st_size
        # stays raw: the fault injector IS the fault source — wrapping
        # the deliberate corruption in retry/fault plumbing would make
        # the chaos tests depend on the machinery they exist to test
        with open(p, "r+b") as f:  # sta: disable=STA011
            f.truncate(max(size // 2, 1))
        logger.warning(f"FAULT INJECTION: corrupted {p} ({size} -> {max(size // 2, 1)} B)")


_plan: Optional[FaultPlan] = None


def get_fault_plan() -> FaultPlan:
    """The process-wide plan; parsed once from ``SCALING_TPU_FAULTS``."""
    global _plan
    if _plan is None:
        _plan = FaultPlan(os.environ.get(ENV_VAR, ""))
        if _plan._rules:
            logger.warning(f"fault injection armed: {_plan.spec}")
    return _plan


def set_fault_plan(plan: Optional[FaultPlan]) -> None:
    """Install (tests) or clear (None re-reads the env on next use)."""
    global _plan
    _plan = plan
