"""Bounded auto-resume: restart from the newest valid checkpoint.

``run_with_resume`` wraps the build-and-train cycle the way a pod
supervisor would: on a *recoverable* failure it rebuilds the trainer —
whose ``load_checkpoint`` fallback restores the newest checkpoint that
passes integrity verification — and continues, up to ``restart_budget``
restarts. Exactness is preserved by construction: a resumed run replays
the exact loss trajectory of an uninterrupted one (the data stream is a
pure function of (seed, consumed_samples) and the RNG of (seed, step);
pinned by ``test_checkpoint_resume_loss_exactness`` and the crash e2e).

Recoverable by default is transient I/O (``OSError`` — storage blips,
injected faults). Deliberately NOT recoverable by default:
``NonFiniteLossError`` (a diverged run restarts into the same
divergence — an operator decision), assertion/config errors, and OOMs.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Type

from ..logging import logger


def run_with_resume(
    trainer_factory: Callable[[], "object"],
    restart_budget: int = 3,
    recoverable: Tuple[Type[BaseException], ...] = (OSError,),
    log_metrics_fn: Optional[Callable] = None,
):
    """Run training to completion, restarting on recoverable failures.

    ``trainer_factory`` must build a FRESH trainer each call with
    ``load_dir`` pointing at the run's ``save_dir`` (so every restart
    resumes from the newest valid checkpoint) and
    ``assert_checkpoint_loaded=False`` for the cold start. Returns the
    trainer that finished; re-raises the last failure once the budget
    is exhausted.
    """
    restarts = 0
    while True:
        trainer = trainer_factory()
        try:
            trainer.run_training(log_metrics_fn=log_metrics_fn)
            return trainer
        except recoverable as e:
            restarts += 1
            if restarts > restart_budget:
                logger.error(
                    f"restart budget exhausted ({restart_budget}); "
                    f"giving up on {type(e).__name__}: {e}"
                )
                raise
            logger.warning(
                f"recoverable failure ({type(e).__name__}: {e}); "
                f"restart {restarts}/{restart_budget} from the newest "
                "valid checkpoint"
            )
