"""Entry point: set up the virtual 8-device CPU mesh BEFORE jax loads.

The audit lowers real mesh layouts (pp=2/dp=2/mp=2) on CPU, so the same
environment the test conftest builds must exist here — and XLA_FLAGS only
takes effect if exported before the first jax import, which is why this
lives in ``__main__`` and ``analysis/__init__`` stays jax-free.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass
# repeat runs (the CI gate, local loops) hit the compile cache instead of
# re-paying the lowering; shares the test suite's cache by default.
# SCALING_TPU_TEST_CACHE=off disables it (the shared contract lives in
# resolve_test_cache_dir)
from . import resolve_test_cache_dir  # noqa: E402

_cache_dir = resolve_test_cache_dir()
if _cache_dir is not None:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass

from .cli import main  # noqa: E402

sys.exit(main())
