"""CLI: ``python -m scaling_tpu.analysis [lint|audit|protocol|all]``.

Emits a human table on stdout and, with ``--json``, a machine-readable
report. Exit code 0 == clean tree (no unsuppressed lint findings, no
golden drift); non-zero == the gate fired. ``audit --repin`` /
``protocol --repin`` rewrite the respective goldens from the current
tree (commit the diff deliberately).

One :class:`~.callgraph.CallGraph` is built per run and shared by every
whole-program consumer — the lint's STA009-STA015 and the ``protocol``
inventory — so ``all`` pays the AST walk once.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]


def _lint(args, graph=None) -> tuple[int, dict]:
    from .lint import RULES, lint_paths

    paths = [Path(p) for p in (args.paths or [REPO_ROOT / "scaling_tpu"])]
    findings = lint_paths(paths, root=args.root or REPO_ROOT, graph=graph)
    active = [f for f in findings if not f.suppressed]
    for f in findings:
        print(str(f))
    print(
        f"lint: {len(active)} finding(s) "
        f"({len(findings) - len(active)} suppressed) over {len(paths)} path(s)"
    )
    # per-rule summary in STABLE rule-id order (a list, so JSON keeps the
    # ordering): the tier-1 gate diffs this structurally — every rule the
    # analyzer knows appears exactly once, clean rules at zero
    rules_summary = [
        {
            "rule": rule,
            "severity": RULES[rule][0],
            "findings": sum(1 for f in findings if f.rule == rule),
            "unsuppressed": sum(1 for f in active if f.rule == rule),
        }
        for rule in sorted(RULES)
    ]
    payload = {
        "findings": [f.to_dict() for f in findings],
        "rules": rules_summary,
        "unsuppressed": len(active),
    }
    return (1 if active else 0), payload


def _ensure_virtual_mesh() -> None:
    """Best-effort 8-device CPU setup for programmatic ``main()`` callers.

    ``python -m scaling_tpu.analysis`` does this properly in ``__main__``
    (XLA_FLAGS must precede the first jax import); from an interpreter
    where jax is already up, ``jax_num_cpu_devices`` still works before
    backend init. If neither took, fail with a clear message instead of a
    confusing Topology device-count error mid-audit."""
    import os

    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        pass
    if len(jax.devices()) < 8:
        raise SystemExit(
            "audit needs the 8-device virtual CPU mesh; run it as "
            "`python -m scaling_tpu.analysis audit` (jax was already "
            f"initialized here with {len(jax.devices())} device(s))"
        )


def _audit(args) -> tuple[int, dict]:
    _ensure_virtual_mesh()
    from . import hlo_audit

    sections = args.sections.split(",") if args.sections else None
    golden_dir = Path(args.goldens) if args.goldens else None
    reports = hlo_audit.run_audit(sections)
    drift: list[str] = []
    for name, report in reports.items():
        mesh = ",".join(f"{k}={v}" for k, v in report["mesh"].items() if v > 1)
        print(f"== audit section {name} ({mesh or 'single device'}) ==")
        for rec in report["collectives"]:
            print(
                f"  {rec['op']:<20} axis={rec['axis']:<14} "
                f"x{rec['count']:<3} {rec['bytes']:>12} B"
            )
        if not report["collectives"]:
            print("  (no collectives)")
        print(
            f"  dots={report['dot_general_count']} "
            f"bf16->f32 dot upcasts={report['bf16_to_f32_dot_upcasts']} "
            f"host callbacks={report['host_callbacks']} "
            f"infeed/outfeed={report['infeed_outfeed']} "
            f"rng ops={report['rng_ops']}"
        )
        print(f"  recompile key {report['recompile_key']['hash']} "
              f"({report['recompile_key']['leaves']} leaves)")
        if args.repin:
            path = hlo_audit.write_golden(name, report, golden_dir)
            print(f"  repinned -> {path}")
        else:
            section_drift = hlo_audit.compare_to_golden(
                name, report, golden_dir
            )
            drift.extend(section_drift)
            print(f"  golden: {'OK' if not section_drift else 'DRIFT'}")
    for line in drift:
        print(f"DRIFT: {line}")
    payload = {"sections": reports, "drift": drift, "repinned": bool(args.repin)}
    return (1 if drift else 0), payload


def _protocol(args, graph) -> tuple[int, dict]:
    """The goldens-pinned protocol inventory: barrier name templates +
    participating functions, per-module RPC op tables. jax-free —
    rides the shared call graph. Golden compare is skipped on a
    ``--paths``-scoped run (the pinned surface is the whole tree)."""
    from .protocol import (
        ProtocolModel,
        build_inventory,
        compare_inventory,
        write_inventory,
    )

    model = ProtocolModel(graph)
    inv = build_inventory(graph, model)
    for name, rec in inv["barriers"].items():
        print(f"barrier {name:<24} waits={len(rec['waits'])} "
              f"arrives={len(rec['arrives'])}")
    for modname, rec in inv["rpc"].items():
        for op, info in rec["ops"].items():
            handler = ",".join(info["handler"]) or "-"
            print(f"rpc {modname}:{op:<12} clients={len(info['clients'])} "
                  f"handler={handler} "
                  f"replies={{{','.join(info['reply_keys'])}}}")
    golden_dir = Path(args.goldens) if args.goldens else None
    drift: list[str] = []
    if args.repin:
        path = write_inventory(inv, golden_dir)
        print(f"protocol: repinned -> {path}")
    elif args.paths:
        print("protocol: golden compare skipped (--paths-scoped run)")
    else:
        drift = compare_inventory(inv, golden_dir)
        for line in drift:
            print(f"DRIFT: {line}")
        print(f"protocol: golden {'OK' if not drift else 'DRIFT'}")
    payload = {"inventory": inv, "drift": drift,
               "repinned": bool(args.repin)}
    return (1 if drift else 0), payload


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scaling_tpu.analysis",
        description="JAX-aware static lint + lowered-HLO audit",
    )
    parser.add_argument("command",
                        choices=["lint", "audit", "protocol", "all"])
    parser.add_argument("--json", metavar="FILE",
                        help="also write a machine-readable report")
    parser.add_argument("--paths", nargs="*",
                        help="lint targets (default: scaling_tpu/)")
    parser.add_argument("--root", help="path findings are reported relative to")
    parser.add_argument("--sections",
                        help="comma list of audit sections "
                             "(default: all; see hlo_audit.SECTIONS)")
    parser.add_argument("--goldens", help="override the golden-report directory")
    parser.add_argument("--repin", action="store_true",
                        help="rewrite audit goldens from the current tree")
    args = parser.parse_args(argv)

    rc = 0
    # bumped whenever the JSON report's structure changes (ISSUE 15:
    # version 2 added schema_version itself + the ordered lint["rules"]
    # per-rule summary; ISSUE 17: version 3 added the protocol rules
    # STA012-STA015 to lint["rules"] and the "protocol" section —
    # inventory + drift; ISSUE 20's STA016 rides version 3, a new
    # per-rule row is additive); consumers diff structurally against
    # this
    payload: dict = {"schema_version": 3}
    graph = None
    if args.command in ("lint", "protocol", "all"):
        # ONE call graph per run, shared by lint's whole-program rules
        # and the protocol inventory
        from .callgraph import CallGraph

        graph_paths = [Path(p) for p in
                       (args.paths or [REPO_ROOT / "scaling_tpu"])]
        graph = CallGraph.build(graph_paths, root=args.root or REPO_ROOT)
    if args.command in ("lint", "all"):
        lint_rc, lint_payload = _lint(args, graph=graph)
        rc = max(rc, lint_rc)
        payload["lint"] = lint_payload
    if args.command in ("protocol", "all"):
        proto_rc, proto_payload = _protocol(args, graph)
        rc = max(rc, proto_rc)
        payload["protocol"] = proto_payload
    if args.command in ("audit", "all"):
        audit_rc, audit_payload = _audit(args)
        rc = max(rc, audit_rc)
        payload["audit"] = audit_payload
    payload["exit_code"] = rc
    if args.json:
        Path(args.json).write_text(json.dumps(payload, indent=1) + "\n")
    print(f"analysis: {'CLEAN' if rc == 0 else 'GATE FIRED'} (exit {rc})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
