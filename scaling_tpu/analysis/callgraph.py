"""Intra-package call-graph engine for the whole-program lint rules.

The per-file AST pass (lint.py) sees one module at a time; the
concurrency and hot-path contract rules (STA009-STA011, concurrency.py)
need to answer *reachability* questions — "is this ``os.replace`` ever
executed under a ``retry_io`` wrapper?", "does the serve tick reach a
``block_until_ready``?", "which methods run on the heartbeat thread?".
This module builds the graph those questions run over:

- every ``.py`` under the analyzed paths is parsed once; module dotted
  names derive from the path (``scaling_tpu/serve/engine.py`` ->
  ``scaling_tpu.serve.engine``), so relative imports resolve;
- functions are indexed by qualified name, including methods and
  *nested closures* (``worker`` inside ``_start_prefetch`` — thread
  targets are routinely closures);
- call edges resolve: module-level functions, imported package
  functions, ``self.method``, ``ClassName(...)`` constructors,
  ``self.attr.method(...)`` via attribute-type inference
  (``self.scheduler = ContinuousBatchingScheduler(...)`` in
  ``__init__`` types the attr), local-variable types
  (``x = ClassName(...)``), and module-aliased attributes
  (``self._jax = jax`` makes ``self._jax.device_put`` resolve to
  ``jax.device_put``);
- calls that cannot be resolved statically (dict-of-programs dispatch,
  duck-typed parameters) are recorded as unresolved and never crash
  the analysis — soundness degrades to "unknown", not to an exception;
- ``threading.Thread(target=...)`` spawn sites are collected with
  their resolved targets: they are the thread entry points STA009
  partitions a class's methods by.

Best-effort by design: the graph under-approximates (unresolved
dynamic calls add no edges) — acceptable for lint rules whose
findings are triaged and annotated, wrong for anything that must be
complete. No jax import; pure stdlib ``ast``.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

# attribute chains on these roots never resolve further (runtime objects)
_UNRESOLVED = None


def _iter_py_files(paths: Iterable[Path | str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    return files


def module_dotted_name(rel: str) -> str:
    """``scaling_tpu/serve/engine.py`` -> ``scaling_tpu.serve.engine``;
    package ``__init__.py`` maps to the package itself."""
    parts = Path(rel).with_suffix("").parts
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _ImportMap:
    """Module-level name -> dotted target, with RELATIVE imports
    resolved against the module's own dotted name (lint's ``_Aliases``
    skips them; the call graph cannot — ``from .scheduler import X``
    is how the package wires itself together)."""

    def __init__(self, tree: ast.Module, modname: str,
                 is_package: bool = False):
        self.map: Dict[str, str] = {}
        pkg_parts = modname.split(".") if modname else []
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.map[a.asname] = a.name
                    else:
                        self.map[a.name.split(".")[0]] = a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    base = node.module or ""
                else:
                    # relative: strip (level) trailing components of the
                    # IMPORTING module's dotted path — one fewer for a
                    # package __init__, whose modname IS its package —
                    # then append node.module
                    strip = node.level - 1 if is_package else node.level
                    up = pkg_parts[: len(pkg_parts) - strip] \
                        if strip <= len(pkg_parts) else []
                    base = ".".join(up + ([node.module] if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    target = f"{base}.{a.name}" if base else a.name
                    self.map[a.asname or a.name] = target

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain through the imports."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return _UNRESOLVED
        root = self.map.get(node.id, node.id)
        return ".".join([root] + list(reversed(parts)))


@dataclasses.dataclass
class FunctionInfo:
    qualname: str  # "<modname>:<Class>.<method>" / "<modname>:<fn>.<locals>.<inner>"
    name: str  # simple name
    dotted: str  # class-qualified suffix, e.g. "ServeEngine.tick" or "fn"
    module: "ModuleInfo"
    node: ast.AST
    class_name: Optional[str] = None
    parent: Optional[str] = None  # enclosing function qualname (closures)
    is_traced: bool = False  # decorated with / passed into a jax transform

    def __repr__(self) -> str:  # compact for test failure output
        return f"<fn {self.qualname}>"


@dataclasses.dataclass
class ClassInfo:
    name: str
    qualname: str  # "<modname>:<Class>"
    dotted: str  # "<modname>.<Class>"
    module: "ModuleInfo"
    node: ast.ClassDef
    methods: Dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    # self.<attr> = ClassName(...)  ->  attr -> ClassInfo.dotted
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    # self.<attr> = <module>  ->  attr -> module dotted name ("jax", "numpy")
    attr_modules: Dict[str, str] = dataclasses.field(default_factory=dict)
    # self.<attr> = self.<method>  ->  attr -> method simple name (a
    # self-stored callback: ``self._cb = self._on_done; self._cb()``)
    attr_callbacks: Dict[str, str] = dataclasses.field(default_factory=dict)
    bases: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ModuleInfo:
    path: Path
    rel: str
    modname: str
    tree: ast.Module
    source: str
    imports: _ImportMap
    functions: Dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ThreadSpawn:
    """One ``threading.Thread(target=...)`` site."""

    function: FunctionInfo  # the spawning function
    target: Optional[FunctionInfo]  # resolved entry point (None = dynamic)
    node: ast.Call


_TRACING_TAILS = (
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "scan", "while_loop", "cond", "fori_loop", "shard_map", "pallas_call",
    "custom_vjp", "custom_jvp", "defvjp", "defjvp", "eval_shape",
)


def own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested ``def``s or
    classes (each nested function is its own graph node). Lambdas ARE
    descended into: they are never indexed as graph nodes of their own,
    so their bodies — callback I/O, a sync hidden in a key function —
    belong to the enclosing function or the rules never see them."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    """The package-wide graph: functions, classes, call edges, thread
    spawn sites, and reachability over them."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}  # modname -> info
        self.functions: Dict[str, FunctionInfo] = {}  # qualname -> info
        # global lookups
        self._by_dotted: Dict[str, FunctionInfo] = {}  # modname.Class.meth / modname.fn
        self.classes: Dict[str, ClassInfo] = {}  # dotted -> info
        self.edges: Dict[str, Set[str]] = {}  # caller qualname -> callees
        self.unresolved: Dict[str, List[ast.Call]] = {}  # caller -> dynamic calls
        self.thread_spawns: List[ThreadSpawn] = []
        # abstract method qualname -> override qualnames in subclasses.
        # Kept SEPARATE from ``edges``: the concurrency rules
        # (STA009-STA011) are pinned on exact static edges; the protocol
        # rules opt in via ``descendants(..., virtual=True)`` so a call
        # on the abstract ControlPlane surface flows into both backends.
        self.override_edges: Dict[str, Set[str]] = {}
        self._local_types_cache: Dict[str, Dict[str, str]] = {}
        self._alias_cache: Dict[str, Dict[str, str]] = {}

    # -------------------------------------------------------------- build
    @classmethod
    def build(cls, paths: Iterable[Path | str],
              root: Optional[Path | str] = None) -> "CallGraph":
        root = Path(root) if root else Path.cwd()
        graph = cls()
        for f in _iter_py_files(paths):
            try:
                rel = str(f.resolve().relative_to(root.resolve()))
            except ValueError:
                rel = str(f)
            try:
                source = f.read_text()
                tree = ast.parse(source, filename=str(f))
            except (SyntaxError, OSError):
                continue  # per-file lint reports syntax errors; skip here
            modname = module_dotted_name(rel)
            mod = ModuleInfo(
                path=f, rel=rel, modname=modname, tree=tree, source=source,
                imports=_ImportMap(tree, modname,
                                   is_package=f.name == "__init__.py"),
            )
            graph.modules[modname] = mod
            graph._index_module(mod)
        graph._infer_attr_types()
        graph._resolve_calls()
        graph._infer_overrides()
        return graph

    # ---------------------------------------------------------- indexing
    def _register(self, fn: FunctionInfo) -> None:
        self.functions[fn.qualname] = fn
        self._by_dotted.setdefault(
            f"{fn.module.modname}.{fn.dotted}" if fn.module.modname
            else fn.dotted,
            fn,
        )

    def _index_module(self, mod: ModuleInfo) -> None:
        def index_function(node, dotted_prefix: str, class_name, parent):
            dotted = (f"{dotted_prefix}.{node.name}" if dotted_prefix
                      else node.name)
            qual = f"{mod.modname}:{dotted}"
            fn = FunctionInfo(
                qualname=qual, name=node.name, dotted=dotted, module=mod,
                node=node, class_name=class_name, parent=parent,
            )
            fn.is_traced = self._decorated_traced(mod, node)
            self._register(fn)
            if class_name is None or parent is not None:
                mod.functions[dotted] = fn
            # nested defs (closures): graph nodes of their own
            for child in ast.walk(node):
                if child is node:
                    continue
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if self._enclosing_def(node, child) is node:
                        index_function(child, dotted, class_name, qual)
            return fn

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                index_function(node, "", None, None)
            elif isinstance(node, ast.ClassDef):
                cinfo = ClassInfo(
                    name=node.name, qualname=f"{mod.modname}:{node.name}",
                    dotted=(f"{mod.modname}.{node.name}" if mod.modname
                            else node.name),
                    module=mod, node=node,
                    bases=[mod.imports.resolve(b) or "" for b in node.bases],
                )
                mod.classes[node.name] = cinfo
                self.classes[cinfo.dotted] = cinfo
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        fn = index_function(item, node.name, node.name, None)
                        cinfo.methods[item.name] = fn

    @staticmethod
    def _enclosing_def(outer: ast.AST, target: ast.AST) -> Optional[ast.AST]:
        """The innermost function whose body (transitively, through
        non-function nodes) contains ``target``."""
        result = [None]

        def walk(node, current):
            for child in ast.iter_child_nodes(node):
                if child is target:
                    result[0] = current
                    return
                nxt = child if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) else current
                walk(child, nxt)

        walk(outer, outer)
        return result[0]

    def _decorated_traced(self, mod: ModuleInfo, node) -> bool:
        decs = getattr(node, "decorator_list", [])
        for d in decs:
            target = d.func if isinstance(d, ast.Call) else d
            name = mod.imports.resolve(target)
            if name and name.rsplit(".", 1)[-1] in _TRACING_TAILS:
                return True
            if isinstance(d, ast.Call):
                fn = mod.imports.resolve(d.func)
                if fn in ("functools.partial", "partial") and d.args:
                    inner = mod.imports.resolve(d.args[0])
                    if inner and inner.rsplit(".", 1)[-1] in _TRACING_TAILS:
                        return True
        return False

    # --------------------------------------------------- attribute typing
    def _follow_export(self, dotted: Optional[str], depth: int = 0
                       ) -> Optional[str]:
        """Resolve a dotted name through package re-exports: a name
        imported from ``scaling_tpu.resilience`` may be DEFINED in
        ``scaling_tpu.resilience.commit`` and re-exported by the
        package ``__init__`` — follow that chain to the definition."""
        if not dotted or depth > 4:
            return dotted
        if dotted in self.classes or dotted in self._by_dotted:
            return dotted
        if "." not in dotted:
            return dotted
        prefix, name = dotted.rsplit(".", 1)
        pkg = self.modules.get(prefix)
        if pkg is None:
            return dotted
        target = pkg.imports.map.get(name)
        if target and target != dotted:
            return self._follow_export(target, depth + 1)
        return dotted

    def _lookup_class(self, mod: ModuleInfo, name: Optional[str]
                      ) -> Optional[ClassInfo]:
        if not name:
            return None
        if name in mod.classes:  # same module, simple name
            return mod.classes[name]
        dotted = mod.imports.resolve(ast.Name(id=name)) if "." not in name \
            else name
        dotted = self._follow_export(dotted)
        if dotted and dotted in self.classes:
            return self.classes[dotted]
        # imported: resolve "pkg.mod.Class" directly
        if name in self.classes:
            return self.classes[name]
        return None

    def _value_class(self, mod: ModuleInfo, value: ast.AST
                     ) -> Optional[ClassInfo]:
        """The ClassInfo an expression constructs, if resolvable."""
        if isinstance(value, ast.Call):
            name = self._follow_export(mod.imports.resolve(value.func))
            if name and name in self.classes:
                return self.classes[name]
            if name and "." not in name:
                return self._lookup_class(mod, name)
            # imported-from: map alias through imports
            if isinstance(value.func, ast.Name):
                dotted = self._follow_export(
                    mod.imports.map.get(value.func.id)
                )
                if dotted and dotted in self.classes:
                    return self.classes[dotted]
        return None

    def _annotation_class(self, mod: ModuleInfo, ann: ast.AST
                          ) -> Optional[ClassInfo]:
        """The ClassInfo an annotation names — ``Foo``, ``"Foo"``,
        ``mod.Foo``, ``Optional[Foo]`` (one peel). Feeds the
        ``self.x = <annotated param>`` attr-typing below: constructor
        injection (``def __init__(self, client: ReplicaProcClient)``)
        is how this codebase wires the protocol objects together, and
        without it every RPC/barrier call through an injected handle
        is a resolution dead end."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Subscript):
            base = ann.value
            base_name = base.attr if isinstance(base, ast.Attribute) \
                else getattr(base, "id", None)
            if base_name == "Optional":
                return self._annotation_class(mod, ann.slice)
            return None
        if isinstance(ann, ast.Attribute):
            dotted = self._follow_export(mod.imports.resolve(ann))
            if dotted and dotted in self.classes:
                return self.classes[dotted]
            return None
        if isinstance(ann, ast.Name):
            return self._lookup_class(mod, ann.id)
        return None

    def _infer_attr_types(self) -> None:
        """``self.x = ClassName(...)`` types attr ``x``; ``self.x = jax``
        (a module alias) records a module attr — both feed call and name
        resolution inside the class's methods."""
        for cinfo in self.classes.values():
            mod = cinfo.module
            for meth in cinfo.methods.values():
                margs = meth.node.args
                param_ann = {
                    a.arg: a.annotation
                    for a in (margs.posonlyargs + margs.args
                              + margs.kwonlyargs)
                    if a.annotation is not None
                }
                for node in ast.walk(meth.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for tgt in node.targets:
                        if not (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            continue
                        attr = tgt.attr
                        klass = self._value_class(mod, node.value)
                        if klass is not None:
                            cinfo.attr_types.setdefault(attr, klass.dotted)
                            continue
                        # self.<attr> = self.<method>: a stored callback
                        # (``self._cb = self._on_done``) — later
                        # ``self._cb()`` calls resolve to the method
                        if (
                            isinstance(node.value, ast.Attribute)
                            and isinstance(node.value.value, ast.Name)
                            and node.value.value.id == "self"
                        ):
                            cinfo.attr_callbacks.setdefault(
                                attr, node.value.attr
                            )
                            continue
                        # self.<attr> = <param> where the parameter is
                        # class-annotated (constructor injection)
                        if (
                            isinstance(node.value, ast.Name)
                            and node.value.id in param_ann
                        ):
                            klass = self._annotation_class(
                                mod, param_ann[node.value.id]
                            )
                            if klass is not None:
                                cinfo.attr_types.setdefault(
                                    attr, klass.dotted
                                )
                                continue
                        if isinstance(node.value, ast.Name):
                            dotted = mod.imports.map.get(node.value.id)
                            if dotted and dotted not in self.classes and (
                                dotted.split(".")[0] not in self.modules
                                or dotted in self.modules
                            ):
                                # a module object handle (self._jax = jax)
                                cinfo.attr_modules.setdefault(attr, dotted)

    # ----------------------------------------------------- call resolution
    def resolve_name(self, fn: FunctionInfo, node: ast.AST) -> Optional[str]:
        """Dotted name of an expression, resolving local module aliases
        (``np = self._np``) and module-typed self attributes
        (``self._jax.device_put`` -> ``jax.device_put``)."""
        mod = fn.module
        cinfo = (mod.classes.get(fn.class_name)
                 if fn.class_name else None)
        # peel the attribute chain down to its root name
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return _UNRESOLVED
        root = cur.id
        chain = list(reversed(parts))
        if root == "self" and cinfo is not None and chain:
            if chain[0] in cinfo.attr_modules:
                return ".".join([cinfo.attr_modules[chain[0]]] + chain[1:])
            return _UNRESOLVED if len(chain) > 1 else None
        # local alias of a module-typed attribute: np = self._np
        alias = self._local_module_alias(fn, root)
        if alias is not None:
            return ".".join([alias] + chain)
        return mod.imports.resolve(node)

    def _local_module_alias(self, fn: FunctionInfo, name: str
                            ) -> Optional[str]:
        # One AST walk per function, memoized: resolve_name runs per
        # call site, and re-walking the body for every lookup turns the
        # whole-package pass quadratic (the analyzer's own wall budget
        # is pinned in tier-1).
        cached = self._alias_cache.get(fn.qualname)
        if cached is None:
            cached = {}
            cinfo = (fn.module.classes.get(fn.class_name)
                     if fn.class_name else None)
            if cinfo is not None and cinfo.attr_modules:
                for node in own_nodes(fn.node):
                    if (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Attribute)
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id == "self"
                        and node.value.attr in cinfo.attr_modules
                    ):
                        cached.setdefault(
                            node.targets[0].id,
                            cinfo.attr_modules[node.value.attr],
                        )
            self._alias_cache[fn.qualname] = cached
        return cached.get(name)

    def _method_of(self, class_dotted: str, name: str
                   ) -> Optional[FunctionInfo]:
        """Method lookup with best-effort single-level base walk inside
        the package."""
        seen: Set[str] = set()
        stack = [class_dotted]
        while stack:
            d = stack.pop()
            if d in seen:
                continue
            seen.add(d)
            cinfo = self.classes.get(d)
            if cinfo is None:
                continue
            if name in cinfo.methods:
                return cinfo.methods[name]
            for b in cinfo.bases:
                if b:
                    if b in self.classes:
                        stack.append(b)
                    else:
                        # base named in the same module / simple name
                        k = self._lookup_class(cinfo.module, b.split(".")[-1])
                        if k is not None:
                            stack.append(k.dotted)
        return None

    def _local_types(self, fn: FunctionInfo) -> Dict[str, str]:
        """Local var -> class dotted, from ``x = ClassName(...)``,
        ``x = self.attr`` of a typed attribute, and parameter
        annotations naming a package class (``commit: CheckpointCommit``).

        Memoized per function: every rule that scans call sites asks for
        this map, and the answer is fixed once the graph is built — the
        cache turns the analyzer's dominant repeated AST walk into a
        dict hit (the STA009-STA014 passes share one graph per run)."""
        cached = self._local_types_cache.get(fn.qualname)
        if cached is not None:
            return cached
        out = self._local_types_uncached(fn)
        self._local_types_cache[fn.qualname] = out
        return out

    def _local_types_uncached(self, fn: FunctionInfo) -> Dict[str, str]:
        mod = fn.module
        cinfo = (mod.classes.get(fn.class_name)
                 if fn.class_name else None)
        out: Dict[str, str] = {}
        args = getattr(fn.node, "args", None)
        if args is not None:
            for a in (list(args.args) + list(args.posonlyargs)
                      + list(args.kwonlyargs)):
                ann = a.annotation
                if isinstance(ann, ast.Constant) and isinstance(ann.value,
                                                                str):
                    klass = self._lookup_class(mod, ann.value)
                elif isinstance(ann, (ast.Name, ast.Attribute)):
                    name = mod.imports.resolve(ann)
                    klass = (self.classes.get(name)
                             or self._lookup_class(mod, name)) if name \
                        else None
                else:
                    klass = None
                if klass is not None:
                    out[a.arg] = klass.dotted
        for node in own_nodes(fn.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            tgt = node.targets[0].id
            klass = self._value_class(mod, node.value)
            if klass is not None:
                out[tgt] = klass.dotted
            elif (
                cinfo is not None
                and isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"
                and node.value.attr in cinfo.attr_types
            ):
                out[tgt] = cinfo.attr_types[node.value.attr]
        return out

    def resolve_callable(self, fn: FunctionInfo, func: ast.AST,
                         local_types: Optional[Dict[str, str]] = None
                         ) -> Optional[FunctionInfo]:
        """Resolve the callee expression of a Call in ``fn``'s body to a
        FunctionInfo, or None for dynamic/out-of-package calls."""
        mod = fn.module
        cinfo = (mod.classes.get(fn.class_name)
                 if fn.class_name else None)
        if local_types is None:
            local_types = self._local_types(fn)
        if isinstance(func, ast.Name):
            name = func.id
            # nested def in this function
            nested = self.functions.get(f"{fn.qualname}.{name}")
            if nested is None and fn.parent:
                nested = self.functions.get(f"{fn.parent}.{name}")
            if nested is not None:
                return nested
            # module-level function in the same module
            if name in mod.functions:
                return mod.functions[name]
            # class constructor
            klass = self._lookup_class(mod, name)
            if klass is not None:
                return klass.methods.get("__init__")
            # imported function from another analyzed module
            dotted = self._follow_export(mod.imports.map.get(name))
            if dotted:
                if dotted in self._by_dotted:
                    return self._by_dotted[dotted]
                if dotted in self.classes:
                    return self.classes[dotted].methods.get("__init__")
            return None
        if isinstance(func, ast.Attribute):
            # self.method(...)
            if isinstance(func.value, ast.Name) and func.value.id == "self" \
                    and cinfo is not None:
                m = self._method_of(cinfo.dotted, func.attr)
                if m is not None:
                    return m
                # self-stored callback: self._cb = self._on_done
                cb = cinfo.attr_callbacks.get(func.attr)
                if cb is not None:
                    return self._method_of(cinfo.dotted, cb)
                return None
            # self.attr.method(...) via attribute type
            if (
                isinstance(func.value, ast.Attribute)
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id == "self"
                and cinfo is not None
                and func.value.attr in cinfo.attr_types
            ):
                return self._method_of(
                    cinfo.attr_types[func.value.attr], func.attr
                )
            # localvar.method(...) via local type
            if isinstance(func.value, ast.Name) \
                    and func.value.id in local_types:
                return self._method_of(local_types[func.value.id], func.attr)
            # module.function(...) from an analyzed module
            dotted = self._follow_export(self.resolve_name(fn, func))
            if dotted and dotted in self._by_dotted:
                return self._by_dotted[dotted]
            if dotted and dotted in self.classes:
                return self.classes[dotted].methods.get("__init__")
            return None
        return None

    def _resolve_spawn_target(self, fn: FunctionInfo, value: ast.AST,
                              local_types: Dict[str, str]
                              ) -> Optional[FunctionInfo]:
        """A ``Thread(target=...)`` entry point: a plain callable, or a
        ``functools.partial(<callable>, ...)`` wrapping one (the standard
        way to hand a thread entry bound arguments)."""
        if isinstance(value, ast.Call):
            name = self.resolve_name(fn, value.func)
            if name in ("functools.partial", "partial") and value.args:
                return self.resolve_callable(fn, value.args[0], local_types)
            return None
        return self.resolve_callable(fn, value, local_types)

    def _resolve_calls(self) -> None:
        for fn in list(self.functions.values()):
            callees: Set[str] = set()
            unresolved: List[ast.Call] = []
            local_types = self._local_types(fn)
            for node in own_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                target = self.resolve_callable(fn, node.func, local_types)
                if target is not None:
                    callees.add(target.qualname)
                else:
                    unresolved.append(node)
                # thread spawn site?
                name = self.resolve_name(fn, node.func)
                if name and name.rsplit(".", 1)[-1] == "Thread" and (
                    name.startswith("threading.") or name == "Thread"
                ):
                    tgt = None
                    for kw in node.keywords:
                        if kw.arg == "target":
                            tgt = self._resolve_spawn_target(
                                fn, kw.value, local_types
                            )
                    self.thread_spawns.append(
                        ThreadSpawn(function=fn, target=tgt, node=node)
                    )
                    if tgt is not None:
                        callees.add(tgt.qualname)  # runs concurrently, but
                        # reachability-wise the spawn reaches the target
                # functions passed by name into jax transforms are traced
                tail = name.rsplit(".", 1)[-1] if name else None
                if tail in _TRACING_TAILS:
                    for arg in node.args:
                        passed = self.resolve_callable(fn, arg, local_types) \
                            if isinstance(arg, (ast.Name, ast.Attribute)) \
                            else None
                        if passed is not None:
                            passed.is_traced = True
            self.edges[fn.qualname] = callees
            if unresolved:
                self.unresolved[fn.qualname] = unresolved

    # ------------------------------------------------------ overrides
    @staticmethod
    def _is_abstract(fn: FunctionInfo) -> bool:
        """A method whose body is only ``raise`` / ``pass`` / ``...`` /
        a docstring — the package's abstract-surface idiom (the
        ``ControlPlane`` backend hooks). Calls resolving to one of these
        tell the static edges nothing; the override edges carry the
        dispatch into the concrete backends."""
        body = list(getattr(fn.node, "body", []))
        if body and isinstance(body[0], ast.Expr) and isinstance(
            body[0].value, ast.Constant
        ) and isinstance(body[0].value.value, str):
            body = body[1:]
        if not body:
            return True
        for stmt in body:
            if isinstance(stmt, (ast.Raise, ast.Pass)):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant
            ) and stmt.value.value is Ellipsis:
                continue
            return False
        return True

    def _infer_overrides(self) -> None:
        """``override_edges``: abstract method -> same-named methods of
        every subclass in the package (single-level base resolution, the
        same best effort :meth:`_method_of` applies upward)."""
        # class dotted -> direct subclasses (dotted)
        subclasses: Dict[str, List[str]] = {}
        for cinfo in self.classes.values():
            for b in cinfo.bases:
                base = self.classes.get(b)
                if base is None and b:
                    base = self._lookup_class(cinfo.module, b.split(".")[-1])
                if base is not None:
                    subclasses.setdefault(base.dotted, []).append(
                        cinfo.dotted
                    )
        for class_dotted, subs in subclasses.items():
            cinfo = self.classes[class_dotted]
            for name, meth in cinfo.methods.items():
                if not self._is_abstract(meth):
                    continue
                stack = list(subs)
                seen: Set[str] = set()
                while stack:
                    sub = stack.pop()
                    if sub in seen:
                        continue
                    seen.add(sub)
                    sub_info = self.classes.get(sub)
                    if sub_info is None:
                        continue
                    override = sub_info.methods.get(name)
                    if override is not None and override is not meth:
                        self.override_edges.setdefault(
                            meth.qualname, set()
                        ).add(override.qualname)
                    stack.extend(subclasses.get(sub, ()))

    # ------------------------------------------------------- reachability
    def find(self, spec: str) -> List[FunctionInfo]:
        """Functions whose class-qualified dotted name ends with ``spec``
        (match at a dot boundary): ``"ServeEngine.tick"`` finds the tick
        method wherever the class lives; ``"run_training"`` finds every
        function of that name."""
        out = []
        for fn in self.functions.values():
            d = fn.dotted
            if d == spec or d.endswith("." + spec):
                out.append(fn)
        return out

    def reachable(self, roots: Iterable[FunctionInfo],
                  stops: Iterable[str] = ()) -> List[FunctionInfo]:
        """BFS over call edges from ``roots``. Functions whose simple
        name or dotted suffix matches an entry in ``stops`` are neither
        scanned nor expanded (the documented off-hot-path subtrees)."""
        stop_set = set(stops)

        def stopped(fn: FunctionInfo) -> bool:
            return fn.name in stop_set or any(
                fn.dotted == s or fn.dotted.endswith("." + s)
                for s in stop_set
            )

        seen: Set[str] = set()
        order: List[FunctionInfo] = []
        queue = [f for f in roots if not stopped(f)]
        for f in queue:
            seen.add(f.qualname)
        while queue:
            fn = queue.pop(0)
            order.append(fn)
            for callee in sorted(self.edges.get(fn.qualname, ())):
                if callee in seen:
                    continue
                target = self.functions.get(callee)
                if target is None or stopped(target):
                    continue
                seen.add(callee)
                queue.append(target)
        return order

    def descendants(self, seeds: Iterable[str],
                    virtual: bool = False) -> Set[str]:
        """Qualnames reachable from ``seeds`` (qualnames), seeds
        included. ``virtual=True`` additionally follows
        :attr:`override_edges` — a call on an abstract surface reaches
        every backend override (the protocol rules' dispatch model;
        the concurrency rules keep the exact static edges)."""
        seen: Set[str] = set()
        queue = [s for s in seeds if s in self.functions]
        seen.update(queue)
        while queue:
            q = queue.pop(0)
            callees: Set[str] = set(self.edges.get(q, ()))
            if virtual:
                callees |= self.override_edges.get(q, set())
            for callee in sorted(callees):
                if callee not in seen and callee in self.functions:
                    seen.add(callee)
                    queue.append(callee)
        return seen
