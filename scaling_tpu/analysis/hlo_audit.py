"""Lowered-HLO auditor: compile-time invariants of the jitted hot paths.

AOT-lowers the real jitted train step (single-device and pp=2/mp=2/dp=2
mesh layouts) and the fused decode loop on the virtual CPU mesh —
``jax.jit(...).lower(...)`` — then walks both text forms of the program:

- the **StableHLO** (pre-optimization: what the user's program actually
  says) for precision hygiene — ``convert`` chains that widen bf16->f32
  into a ``dot_general`` operand, host callbacks / infeed / outfeed,
  rng-bit-generator counts;
- the **optimized HLO** (post SPMD partitioning: what the chip runs) for
  the collective inventory — all-reduce / all-gather / reduce-scatter /
  collective-permute / all-to-all counts and byte estimates per mesh
  axis, attributed by matching each op's replica groups against the
  topology's device grid.

A recompile-key signature (abstract input shapes + static step config)
rounds out each section so shape-signature drift shows up as a diff, not
a silent second compile on the chip.

The structured report is pinned against goldens in ``analysis/goldens/``
(exact on counts/signatures, banded on bytes/flops for XLA version
noise); ``python -m scaling_tpu.analysis audit --repin`` re-baselines.
"""

from __future__ import annotations

import hashlib
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

GOLDEN_DIR = Path(__file__).resolve().parent / "goldens"

# relative slack on byte/flop pins (XLA version noise; counts stay exact)
BYTES_RTOL = 0.15

_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)
# '= <result shapes> <op>(' — result may be a single 'f32[8,16]{1,0}' or a
# variadic tuple '(f32[100]{0}, f32[200]{0})'; dropping the tuple case
# would silently uncount fused gradient syncs (migrated from
# tests/transformer/test_hlo_cost_pins.py).
_COLLECTIVE_RE = re.compile(
    r"=\s+((?:\([^)]*\))|(?:[a-z0-9]+\[[0-9,]*\][^ ]*))\s+"
    r"(" + "|".join(_COLLECTIVE_OPS) + r")(-start)?\("
)
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
                "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                "pred": 1}

_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[0-9,{} ]*\})\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)
_PAIRS_RE = re.compile(r"source_target_pairs=\{([0-9,{} ]*)\}")


def _shape_bytes(shapes_text: str, skip_first: bool = False) -> int:
    """Bytes of the result shape(s). ``skip_first`` drops the leading
    tuple element — async ``-start`` ops return ``(operand, result, ...)``,
    and counting the aliased operand would double the payload versus the
    same collective in sync form."""
    shapes = _SHAPE_RE.findall(shapes_text)
    if skip_first and len(shapes) > 1:
        shapes = shapes[1:]
    total = 0
    for dtype, shape in shapes:
        n = 1
        for dim in shape.split(","):
            if dim:
                n *= int(dim)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def _parse_replica_groups(line: str) -> Optional[List[List[int]]]:
    m = _GROUPS_LITERAL_RE.search(line)
    if m:
        return [
            [int(x) for x in grp.split(",") if x.strip()]
            for grp in re.findall(r"\{([0-9, ]*)\}", m.group(0))
        ]
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        total = 1
        for d in dims:
            total *= d
        ids: List[int] = list(range(total))
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            # transpose(reshape(iota, dims), perm).flatten()
            import itertools

            strides = [0] * len(dims)
            acc = 1
            for i in range(len(dims) - 1, -1, -1):
                strides[i] = acc
                acc *= dims[i]
            out = []
            for idx in itertools.product(*[range(dims[p]) for p in perm]):
                flat = sum(idx[k] * strides[perm[k]] for k in range(len(perm)))
                out.append(flat)
            ids = out
        return [
            ids[g * group_size:(g + 1) * group_size] for g in range(n_groups)
        ]
    return None


def _parse_pairs(line: str) -> Optional[List[Tuple[int, int]]]:
    m = _PAIRS_RE.search(line)
    if not m:
        return None
    return [
        (int(a), int(b))
        for a, b in re.findall(r"\{(\d+),(\d+)\}", m.group(0))
    ]


class MeshAxes:
    """Attribute collectives to mesh axes by matching their replica groups
    against the topology's device grid (arange(world).reshape(sizes))."""

    def __init__(self, axis_names: Sequence[str], axis_sizes: Sequence[int]):
        self.names = list(axis_names)
        self.sizes = list(axis_sizes)
        self.world = 1
        for s in self.sizes:
            self.world *= s
        self._by_groups: Dict[frozenset, str] = {}
        n = len(self.sizes)
        # every non-empty axis subset gets its canonical grouping (a grad
        # sync over data+context is one fused all-reduce spanning both)
        for mask in range(1, 1 << n):
            subset = [i for i in range(n) if mask & (1 << i)]
            if any(self.sizes[i] == 1 for i in subset):
                continue  # size-1 axes never appear in real groups
            groups = self._axis_groups(subset)
            name = "+".join(self.names[i] for i in subset)
            self._by_groups.setdefault(groups, name)

    def _coords(self, flat: int) -> List[int]:
        coords = []
        rem = flat
        for size in reversed(self.sizes):
            coords.append(rem % size)
            rem //= size
        return list(reversed(coords))

    def _axis_groups(self, subset: List[int]) -> frozenset:
        groups: Dict[tuple, List[int]] = {}
        for flat in range(self.world):
            coords = self._coords(flat)
            fixed = tuple(c for i, c in enumerate(coords) if i not in subset)
            groups.setdefault(fixed, []).append(flat)
        return frozenset(frozenset(g) for g in groups.values())

    def axis_of_groups(self, groups: List[List[int]]) -> str:
        key = frozenset(frozenset(g) for g in groups)
        if key in self._by_groups:
            return self._by_groups[key]
        if all(len(g) == self.world for g in groups):
            return "world"
        if all(len(g) == 1 for g in groups):
            return "self"
        return "unknown"

    def axis_of_pairs(self, pairs: List[Tuple[int, int]]) -> str:
        axes = set()
        for src, dst in pairs:
            cs, cd = self._coords(src), self._coords(dst)
            for i, (a, b) in enumerate(zip(cs, cd)):
                if a != b:
                    axes.add(self.names[i])
        return "+".join(sorted(axes)) if axes else "self"


def collective_inventory(
    hlo_text: str, mesh: Optional[MeshAxes] = None
) -> List[dict]:
    """Per-(op, axis) collective counts and byte estimates from optimized
    HLO text. Bytes are the per-partition result bytes (the same
    accounting the HLO cost pins calibrated their bands against)."""
    agg: Dict[Tuple[str, str], dict] = {}
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        if "-done(" in line:
            continue  # async pair: count the -start, not the -done
        shapes_text, op, is_start = m.group(1), m.group(2), bool(m.group(3))
        axis = "unattributed"
        if mesh is not None:
            groups = _parse_replica_groups(line)
            pairs = _parse_pairs(line)
            if groups:
                axis = mesh.axis_of_groups(groups)
            elif pairs:
                axis = mesh.axis_of_pairs(pairs)
        rec = agg.setdefault(
            (op, axis), {"op": op, "axis": axis, "count": 0, "bytes": 0}
        )
        rec["count"] += 1
        rec["bytes"] += _shape_bytes(shapes_text, skip_first=is_start)
    return sorted(agg.values(), key=lambda r: (r["op"], r["axis"]))


def collective_bytes(compiled) -> Dict[str, int]:
    """Back-compat surface for the HLO cost pins: total per-partition bytes
    moved by each collective op kind in a ``.compile()``d step."""
    out: Dict[str, int] = {}
    for rec in collective_inventory(compiled.as_text()):
        out[rec["op"]] = out.get(rec["op"], 0) + rec["bytes"]
    return out


# ------------------------------------------------------- StableHLO audit
_SH_CONVERT_RE = re.compile(
    r"%(\S+) = stablehlo\.convert %(\S+) : "
    r"\(tensor<[^>]*xbf16>\) -> tensor<[^>]*xf32>"
)
_SH_OPERAND_RE = re.compile(r"%([\w#.]+)")


def stablehlo_precision_audit(text: str) -> dict:
    """Walk the lowered (pre-optimization) StableHLO: bf16->f32 converts
    that feed dot_general operands (an fp32 matmul hiding in a bf16 path
    doubles its MXU cost), plus host-callback / infeed-outfeed presence
    and rng op counts. Value names are function-scoped, so the convert
    table resets at each ``func.func``."""
    upcast_feeds_dot = 0
    dots = 0
    converts_bf16_f32: set = set()
    rng = 0
    callbacks = 0
    infeed_outfeed = 0
    for line in text.splitlines():
        if re.search(r"^\s*func\.func\b", line):
            converts_bf16_f32 = set()
        m = _SH_CONVERT_RE.search(line)
        if m:
            converts_bf16_f32.add(m.group(1))
        if "stablehlo.dot_general" in line:
            dots += 1
            ops = _SH_OPERAND_RE.findall(
                line.split("stablehlo.dot_general", 1)[1]
            )[:2]
            if any(o in converts_bf16_f32 for o in ops):
                upcast_feeds_dot += 1
        if "stablehlo.rng_bit_generator" in line or "stablehlo.rng " in line:
            rng += 1
        if "stablehlo.custom_call" in line and "callback" in line:
            callbacks += 1
        if "stablehlo.infeed" in line or "stablehlo.outfeed" in line:
            infeed_outfeed += 1
    return {
        "dot_general_count": dots,
        "bf16_to_f32_dot_upcasts": upcast_feeds_dot,
        "host_callbacks": callbacks,
        "infeed_outfeed": infeed_outfeed,
        "rng_ops": rng,
    }


# --------------------------------------------------------- recompile key
def recompile_signature(args, static_config: dict) -> dict:
    """Stable signature of a jitted step's input avals + static config:
    shape-signature drift (a new static argnum, a changed batch layout)
    changes the hash and is caught as golden drift."""
    import jax

    lines: List[str] = [json.dumps(static_config, sort_keys=True)]
    flat, _ = jax.tree_util.tree_flatten_with_path(args)
    for path, leaf in flat:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        lines.append(f"{jax.tree_util.keystr(path)} {shape} {dtype}")
    text = "\n".join(lines)
    return {
        "hash": "sha256:" + hashlib.sha256(text.encode()).hexdigest()[:16],
        "leaves": len(flat),
        "static": static_config,
    }


# ------------------------------------------------------ section builders
def make_train_config(pp=1, dp=1, mp=1, gas=1, zero=False, seq=64, mbs=2,
                      hidden=128, layers=2, vocab=512, kv_heads=None,
                      mlp_factor=2.0, remat=None, vpp=1, slices=1):
    """The ONE GQA+RoPE+SwiGLU+RMS train-config builder shared by the
    audit sections (tiny defaults) and the HLO cost pins (which pass the
    bench-flagship shape) — a field added here reaches both, so the pins
    and the goldens keep measuring the same program family."""
    from scaling_tpu.models.transformer import TransformerConfig

    d = {
        "topology": {
            "model_parallel_size": mp, "pipe_parallel_size": pp,
            "data_parallel_size": dp, "micro_batch_size": mbs,
            "gradient_accumulation_steps": gas,
            "pipe_virtual_size": vpp, "pipe_token_slices": slices,
        },
        "transformer_architecture": {
            "vocab_size": vocab, "hidden_size": hidden, "num_layers": layers,
            "num_attention_heads": hidden // 64,
            "attention_num_kv_heads": (
                hidden // 64 if kv_heads is None else kv_heads
            ),
            "sequence_length": seq, "precision": "bfloat16",
            "mlp_type": "swiglu", "mlp_factor": mlp_factor, "norm_type": "rms",
            "relative_position_embedding_type": "rotary", "causal": True,
            "masked_softmax": {"kernel": "torch"},
            "weight_tying": False, "attention_qkv_in_one": False,
            "dropout_embedding": 0.0, "dropout_attention_probs": 0.0,
            "dropout_after_attention": 0.0, "dropout_after_mlp": 0.0,
        },
        "optimizer": {"gradient_clipping": 1.0, "zero": zero,
                      "loss_scaler": {"enable": False}},
        "learning_rate_scheduler": {"learning_rate": 3e-4,
                                    "learning_rate_warmup_steps": 10,
                                    "learning_rate_decay_iters": 1000},
        "trainer": {"train_iterations": 10, "seed": 0},
        "data": {}, "logger": {"log_dir": None},
    }
    if remat:
        d["topology"]["activation_checkpointing_type"] = remat
    return TransformerConfig.from_dict(d)


def lower_train_step(config):
    """Build + AOT-lower the real jitted train step for ``config`` with a
    synthetic stacked batch; returns ``(lowered, args, topology)``. The
    ONE copy of this recipe — the HLO cost pins' ``compile_step`` wraps
    it, so the audit goldens pin the same program the pins measure."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scaling_tpu.models.transformer.model import (
        init_model, init_optimizer, loss_function,
    )
    from scaling_tpu.topology import Topology

    topology = Topology(config.topology)
    module = init_model(config, topology)
    optimizer = init_optimizer(config, module, topology)
    key = jax.random.PRNGKey(0)
    params = module.shard_params(module.init_params(key))
    opt_state = optimizer.init_state(params)
    step = module.build_train_step(optimizer, loss_function)
    arch = config.transformer_architecture
    topo = config.topology
    b = topo.micro_batch_size * topo.data_parallel_size
    gas, seq = topo.gradient_accumulation_steps, arch.sequence_length
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, arch.vocab_size, size=(gas, b, seq), dtype=np.int64)
    batch = module.shard_batch(
        {
            "token_ids": jnp.asarray(tokens, jnp.int32),
            "target_token_ids": jnp.asarray(np.roll(tokens, -1, -1), jnp.int32),
            "position_ids": jnp.asarray(
                np.broadcast_to(np.arange(seq, dtype=np.int32), (gas, b, seq))
            ),
            "segment_ids": jnp.zeros((gas, b, seq), jnp.int32),
            "loss_weights": jnp.ones((gas, b, seq), jnp.float32),
        },
        stacked=True,
    )
    args = (params, opt_state, batch, key)
    lowered = step.lower(*args)
    return lowered, args, topology


def _audit_lowered(lowered, args, static_config: dict,
                   mesh: Optional[MeshAxes]) -> dict:
    compiled = lowered.compile()
    report = stablehlo_precision_audit(lowered.as_text())
    report["collectives"] = collective_inventory(compiled.as_text(), mesh)
    report["recompile_key"] = recompile_signature(args, static_config)
    try:
        an = compiled.cost_analysis()
        an = an[0] if isinstance(an, list) else an
        flops = an.get("flops")
        # a vanished key is 'cost analysis died', not 'zero flops' — keep
        # the distinction so the golden gate can flag it
        report["flops"] = None if flops is None else float(flops)
    except Exception:
        report["flops"] = None
    return report


def audit_train_section(pp=1, dp=1, mp=1, gas=1, zero=False, vpp=1,
                        slices=1, layers=2) -> dict:
    config = make_train_config(pp=pp, dp=dp, mp=mp, gas=gas, zero=zero,
                               vpp=vpp, slices=slices, layers=layers)
    lowered, args, topology = lower_train_step(config)
    mesh = MeshAxes(topology.mesh.axis_names, topology.mesh.devices.shape)
    static = {
        "kind": "train_step",
        "pp": pp, "dp": dp, "mp": mp, "gas": gas, "zero": zero,
        "donate_argnums": [0, 1],
    }
    # new schedule knobs enter the signature only when active, so the
    # legacy sections' pinned recompile-key hashes stay byte-identical
    if vpp > 1:
        static["vpp"] = vpp
    if slices > 1:
        static["token_slices"] = slices
    if layers != 2:
        static["layers"] = layers
    report = _audit_lowered(lowered, args, static, mesh)
    report["mesh"] = dict(
        zip(topology.mesh.axis_names, topology.mesh.devices.shape)
    )
    return report


def audit_decode_section(prompt_len=4, max_tokens=4) -> dict:
    """The fused decode loop (one ``lax.while_loop`` device program per
    generation): a host callback or a per-step sync sneaking into it is
    exactly the regression that turns decode latency into RTT-bound."""
    import jax
    import jax.numpy as jnp

    from scaling_tpu.models.transformer.inference import (
        TransformerInferenceModule, sample_argmax,
    )
    from scaling_tpu.models.transformer.model import init_model

    config = make_train_config()
    module = init_model(config, None)
    params = module.init_params(jax.random.PRNGKey(0))
    inf = TransformerInferenceModule(config, module, params)
    prompt = jnp.arange(1, prompt_len + 1, dtype=jnp.int32)[None]
    logits, caches = inf._prefill(prompt, prompt_len + max_tokens)
    tok0 = sample_argmax(logits[:, -1])
    steps = max(0, max_tokens - 1)
    loop = jax.jit(inf._build_decode_loop(sample_argmax, (), steps))
    args = (params, caches, tok0, logits[:, -1],
            jnp.asarray(prompt_len, jnp.int32), jax.random.PRNGKey(0))
    lowered = loop.lower(*args)
    static = {
        "kind": "fused_decode", "prompt_len": prompt_len,
        "max_tokens": max_tokens, "steps": steps,
    }
    report = _audit_lowered(lowered, args, static, mesh=None)
    report["mesh"] = {}
    return report


def _count_pallas_custom_calls(text: str) -> int:
    """Pallas kernels lower to ``tpu_custom_call`` custom-calls on a real
    chip; off-TPU (interpret mode) the kernel body inlines as plain HLO
    and the count is 0. Pinning the count makes a silent fall-off-the-
    kernel regression (someone reroutes decode through the gather path on
    chip) golden drift, not a quiet 2x HBM-traffic surprise."""
    return len(re.findall(r"stablehlo\.custom_call\s*@tpu_custom_call", text))


def audit_serve_decode_section(num_slots=2, block_size=4,
                               max_blocks=4, prefill_chunk=8,
                               spec_k=3, mp=1) -> dict:
    """The serving engine's single MIXED program (serve/engine.py,
    ISSUE 11): ONE jitted step per tick covers the whole slot set —
    decode rows (last token + up to ``spec_k`` speculative drafts) and
    prefill-chunk rows alike, tagged purely by traced per-row lengths.
    Its recompile-key signature is the no-recompile-storm contract: the
    key bakes the (chunk, draft-length) width signature plus the engine
    shape config, and NOTHING per-request — a scheduler change that
    moves prompt lengths, prefill offsets, or draft contents into the
    signature shows up as golden drift here, not as a compile storm on
    the chip. The static config also pins the paged-attention back-end
    and the legacy prefill bucket ladder's floor (policy drift moves the
    hash even though legacy prefill lowers per bucket), and
    ``pallas_custom_calls`` counts the paged-attention kernel's custom
    calls in the lowered HLO (0 off-TPU where the kernel runs
    interpreted).

    ``mp > 1`` lowers the SHARDED mixed program (ISSUE 14): the engine's
    KV pools shard over the model axis, the program partitions SPMD over
    the serving mesh, and the collective inventory pins the model-axis
    activation all-reduces the sharded tick pays — plus the recompile
    key grows an ``mp`` entry (only when sharded, so the mp=1 section's
    pinned hash stays byte-identical)."""
    import jax
    import jax.numpy as jnp

    from scaling_tpu.models.transformer.inference import (
        TransformerInferenceModule,
    )
    from scaling_tpu.models.transformer.model import init_model
    from scaling_tpu.serve.engine import (
        MIN_PREFILL_BUCKET, EngineConfig, ServeEngine,
    )

    config = make_train_config(mp=mp)
    topology = None
    if mp > 1:
        from scaling_tpu.topology import Topology

        topology = Topology(config.topology)
    module = init_model(config, topology)
    params = module.init_params(jax.random.PRNGKey(0))
    if topology is not None:
        params = module.shard_params(params)
    inf = TransformerInferenceModule(config, module, params)
    engine = ServeEngine(inf, EngineConfig(
        num_slots=num_slots, block_size=block_size,
        num_blocks=2 * max_blocks + 1, max_blocks_per_seq=max_blocks,
        token_budget=64, prefill_chunk=prefill_chunk, spec_k=spec_k,
    ))
    base_key = engine._dev(jax.random.PRNGKey(0))
    width = engine.config.mixed_width
    mixed = engine._build_mixed_fn(width)
    args = (
        params, engine._pool_state(),
        *engine._dev((
            jnp.zeros((num_slots, max_blocks), jnp.int32),  # block tables
            jnp.zeros((num_slots,), jnp.int32),     # context lengths
            jnp.zeros((num_slots, width), jnp.int32),  # tokens
            jnp.ones((num_slots,), jnp.int32),      # real per row
            jnp.zeros((num_slots,), jnp.float32),   # temperatures
            jnp.zeros((num_slots,), jnp.float32),   # top-ps
            jnp.zeros((num_slots,), jnp.int32),     # top-ks
            jnp.zeros((num_slots,), jnp.int32),     # request ids
            jnp.zeros((num_slots,), jnp.int32),     # key-fold bases
        )),
        base_key,
    )
    lowered = mixed.lower(*args)
    static = {
        "kind": "serve_mixed_step", "num_slots": num_slots,
        "block_size": block_size, "max_blocks_per_seq": max_blocks,
        "kv_dtype": engine.config.kv_dtype,
        "min_prefill_bucket": MIN_PREFILL_BUCKET,
        "paged_kernel": engine.config.paged_kernel,
        "prefill_chunk": prefill_chunk,
        "spec_k": spec_k,
        "mixed_width": width,
        # positions gathered per row before the vocab projection — a
        # change that silently re-projects every width position shows
        # up as golden drift, not a quiet FLOPs regression
        "sample_width": engine.config.sample_width,
    }
    mesh = None
    if mp > 1:
        # mp joins the recompile key ONLY when sharded: the mp=1
        # section's pinned hash stays byte-identical
        static["mp"] = mp
        mesh = MeshAxes(
            topology.mesh.axis_names, topology.mesh.devices.shape
        )
    report = _audit_lowered(lowered, args, static, mesh=mesh)
    report["mesh"] = (
        dict(zip(topology.mesh.axis_names, topology.mesh.devices.shape))
        if mp > 1 else {}
    )
    report["pallas_custom_calls"] = _count_pallas_custom_calls(
        lowered.as_text()
    )
    return report


SECTIONS = {
    "train_single": lambda: audit_train_section(),
    "train_pp2_mp2": lambda: audit_train_section(pp=2, dp=2, mp=2, zero=True),
    # interleaved virtual stages: v x more pipe-axis collective-permutes
    # for ~v x less fill/drain garbage — the inventory pins that trade
    # (ISSUE 7; layers=4 so the 4 chunks hold one layer each)
    "train_pp2_vpp2": lambda: audit_train_section(
        pp=2, dp=2, mp=2, zero=True, gas=2, vpp=2, layers=4
    ),
    # TeraPipe token slicing: same permute family over S x more, thinner
    # work items, plus the KV-cache attention path
    "train_pp2_tokenslice": lambda: audit_train_section(
        pp=2, dp=2, mp=2, zero=True, gas=2, slices=2
    ),
    "decode_fused": lambda: audit_decode_section(),
    # continuous-batching serving: the paged decode step (ISSUE 9)
    "serve_decode": lambda: audit_serve_decode_section(),
    # mp=2 sharded serving: the SAME mixed program partitioned over the
    # model axis — per-axis collective inventory + mp in the recompile
    # key (ISSUE 14; the mp=1 section above stays byte-identical)
    "serve_decode_mp2": lambda: audit_serve_decode_section(mp=2),
}


def run_audit(sections: Optional[Sequence[str]] = None) -> dict:
    names = list(sections) if sections else list(SECTIONS)
    unknown = [n for n in names if n not in SECTIONS]
    if unknown:
        raise ValueError(f"unknown audit sections {unknown}; have {list(SECTIONS)}")
    return {name: SECTIONS[name]() for name in names}


# ----------------------------------------------------- layout cost summary
def cost_summary_from_report(report: dict) -> dict:
    """Reusable per-layout cost summary from an audit section report (or
    a committed golden's JSON — same schema): per-axis and per-op totals
    of the collective inventory plus the compiled FLOPs and mesh. The
    exported surface the auto-sharding tuner (``scaling_tpu.tune``)
    consumes, so downstream cost models never reach into the
    audit-internal record lists."""
    per_axis: Dict[str, dict] = {}
    per_op: Dict[str, dict] = {}
    for rec in report.get("collectives") or []:
        for key, table in ((rec["axis"], per_axis), (rec["op"], per_op)):
            slot = table.setdefault(key, {"bytes": 0, "count": 0})
            slot["bytes"] += int(rec["bytes"])
            slot["count"] += int(rec["count"])
    return {
        "per_axis": per_axis,
        "per_op": per_op,
        "collectives": list(report.get("collectives") or []),
        "flops": report.get("flops"),
        "mesh": dict(report.get("mesh") or {}),
    }


def layout_cost_summary(pp=1, dp=1, mp=1, gas=1, zero=False, vpp=1,
                        slices=1, layers=2) -> dict:
    """Lower the real jitted train step for this layout (tiny audit
    shapes) and summarize its collective traffic per mesh axis — the
    artifact-fed ingredient of the tuner's cost model (docs/TUNING.md).
    Needs enough devices for the mesh (the 8-device virtual CPU mesh in
    CI)."""
    return cost_summary_from_report(
        audit_train_section(pp=pp, dp=dp, mp=mp, gas=gas, zero=zero,
                            vpp=vpp, slices=slices, layers=layers)
    )


def golden_cost_summary(name: str,
                        golden_dir: Optional[Path] = None) -> dict:
    """The committed golden's cost summary — per-axis collective bytes
    from a REAL lowered program, readable without jax or a mesh (the
    goldens are artifacts of past audits)."""
    path = golden_path(name, golden_dir)
    return cost_summary_from_report(json.loads(path.read_text()))


# ------------------------------------------------------------- golden pin
def golden_path(name: str, golden_dir: Optional[Path] = None) -> Path:
    return (golden_dir or GOLDEN_DIR) / f"{name}.json"


def compare_to_golden(
    name: str, report: dict, golden_dir: Optional[Path] = None,
    rtol: float = BYTES_RTOL,
) -> List[str]:
    """Drift lines (empty == clean). Counts, axes, signatures and op kinds
    compare exactly; bytes and flops within ``rtol`` (XLA version noise —
    the same philosophy as the HLO cost-pin bands)."""
    path = golden_path(name, golden_dir)
    if not path.is_file():
        return [f"{name}: no golden at {path} (run audit --repin)"]
    golden = json.loads(path.read_text())
    drift: List[str] = []

    def exact(field, a, b):
        if a != b:
            drift.append(f"{name}.{field}: golden {a!r} != current {b!r}")

    for field in (
        "bf16_to_f32_dot_upcasts", "host_callbacks", "infeed_outfeed",
        "rng_ops", "dot_general_count", "mesh",
        # serving sections only (None == None elsewhere): the paged
        # kernel's custom-call presence is part of the hot-path contract
        "pallas_custom_calls",
    ):
        exact(field, golden.get(field), report.get(field))
    exact("recompile_key.hash", golden.get("recompile_key", {}).get("hash"),
          report.get("recompile_key", {}).get("hash"))
    # serving sections pin a second program (chunked prefill) per golden
    exact("chunk_program.hash",
          (golden.get("chunk_program") or {}).get("hash"),
          (report.get("chunk_program") or {}).get("hash"))
    exact("chunk_program.pallas_custom_calls",
          (golden.get("chunk_program") or {}).get("pallas_custom_calls"),
          (report.get("chunk_program") or {}).get("pallas_custom_calls"))

    def inv_map(inv):
        return {(r["op"], r["axis"]): r for r in inv or []}

    g_inv, c_inv = inv_map(golden.get("collectives")), inv_map(
        report.get("collectives")
    )
    for key in sorted(set(g_inv) | set(c_inv)):
        g, c = g_inv.get(key), c_inv.get(key)
        if g is None:
            drift.append(f"{name}: NEW collective {key} x{c['count']} "
                         f"({c['bytes']} B)")
        elif c is None:
            drift.append(f"{name}: collective {key} vanished "
                         f"(golden x{g['count']})")
        else:
            if g["count"] != c["count"]:
                drift.append(
                    f"{name}: collective {key} count {g['count']} -> "
                    f"{c['count']}"
                )
            gb, cb = g["bytes"], c["bytes"]
            if gb and abs(cb - gb) > rtol * gb:
                drift.append(
                    f"{name}: collective {key} bytes {gb} -> {cb} "
                    f"(> {rtol:.0%} band)"
                )
    gf, cf = golden.get("flops"), report.get("flops")
    if (gf is None) != (cf is None):
        # cost analysis silently dying must not silently un-enforce the pin
        drift.append(f"{name}: flops availability changed {gf!r} -> {cf!r}")
    elif gf is not None and abs(cf - gf) > rtol * max(abs(gf), 1.0):
        drift.append(f"{name}: flops {gf:.3g} -> {cf:.3g} (> {rtol:.0%} band)")
    return drift


def write_golden(name: str, report: dict,
                 golden_dir: Optional[Path] = None) -> Path:
    path = golden_path(name, golden_dir)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(report, indent=1, sort_keys=True) + "\n")
    return path
