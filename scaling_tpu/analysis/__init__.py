"""Static-analysis subsystem: JAX-aware AST lint + lowered-HLO audit.

Two complementary compile-time gates over the training/decode hot path
(ISSUE 2; the Megatron-LM / Mesh-TensorFlow practice of inspecting the
lowered program to keep collective and layout invariants honest):

- ``lint``: visitor-based AST pass over ``scaling_tpu/`` source with
  JAX-specific rules (tracer branches, host syncs, PRNG key reuse, ...).
  Rule IDs are stable (``STA001``..); suppress per line with
  ``# sta: disable=STA003``.
- ``hlo_audit``: AOT-lowers the jitted train step and the fused decode
  step on the virtual CPU mesh and walks the StableHLO / optimized-HLO
  text into a structured report (collective inventory per mesh axis,
  bf16->f32 upcasts feeding dots, host callbacks, rng ops, recompile-key
  signature), pinned against committed goldens.

CLI: ``python -m scaling_tpu.analysis [lint|audit|all] --json out.json``.

This module must stay import-light (no jax): the CLI sets up the virtual
device environment before anything pulls jax in.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = [
    "main", "lint_paths", "Finding", "RULES", "resolve_test_cache_dir",
]


def resolve_test_cache_dir(
    default: str = "/tmp/scaling_tpu_test_jaxcache",
) -> Optional[str]:
    """The SCALING_TPU_TEST_CACHE contract, in one place.

    Returns the persistent XLA compile-cache directory every consumer
    (tests/conftest.py, the analysis CLI, bench subprocesses) should
    use, or None when the cache is disabled via the ``off``/``none``/
    ``0``/empty sentinels — on some containers executables DESERIALIZED
    from this cache mis-execute, and a sentinel value must never become
    a literal ``./off`` cache directory."""
    value = os.environ.get("SCALING_TPU_TEST_CACHE", default)
    if value.lower() in ("off", "none", "0", ""):
        return None
    return value


def main(argv=None) -> int:
    from .cli import main as _main

    return _main(argv)


def __getattr__(name):
    # lazy re-exports so `import scaling_tpu.analysis` stays jax-free
    if name in ("lint_paths", "Finding", "RULES"):
        from . import lint as _lint

        return getattr(_lint, name)
    raise AttributeError(name)
