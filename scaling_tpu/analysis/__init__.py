"""Static-analysis subsystem: JAX-aware AST lint + lowered-HLO audit.

Two complementary compile-time gates over the training/decode hot path
(ISSUE 2; the Megatron-LM / Mesh-TensorFlow practice of inspecting the
lowered program to keep collective and layout invariants honest):

- ``lint``: visitor-based AST pass over ``scaling_tpu/`` source with
  JAX-specific rules (tracer branches, host syncs, PRNG key reuse, ...).
  Rule IDs are stable (``STA001``..); suppress per line with
  ``# sta: disable=STA003``.
- ``hlo_audit``: AOT-lowers the jitted train step and the fused decode
  step on the virtual CPU mesh and walks the StableHLO / optimized-HLO
  text into a structured report (collective inventory per mesh axis,
  bf16->f32 upcasts feeding dots, host callbacks, rng ops, recompile-key
  signature), pinned against committed goldens.

CLI: ``python -m scaling_tpu.analysis [lint|audit|all] --json out.json``.

This module must stay import-light (no jax): the CLI sets up the virtual
device environment before anything pulls jax in.
"""

from __future__ import annotations

__all__ = ["main", "lint_paths", "Finding", "RULES"]


def main(argv=None) -> int:
    from .cli import main as _main

    return _main(argv)


def __getattr__(name):
    # lazy re-exports so `import scaling_tpu.analysis` stays jax-free
    if name in ("lint_paths", "Finding", "RULES"):
        from . import lint as _lint

        return getattr(_lint, name)
    raise AttributeError(name)
