"""JAX-aware static lint over the package source (AST pass, no jax import).

Rules — stable IDs, severities, and the contexts they fire in:

========  ========  ==========================================================
ID        severity  meaning
========  ========  ==========================================================
STA001    error     Python ``if``/``while``/``bool()`` branching on a
                    traced-array expression inside a traced context (a
                    retrace hazard / ConcretizationTypeError on the chip).
STA002    error     ``numpy`` host op applied to a traced value inside a
                    traced context (silently falls off the device).
STA003    error     host sync inside a traced context: ``.item()`` /
                    ``float()`` / ``int()`` / ``bool()`` / ``np.asarray()``
                    on array values (stalls the dispatch pipeline).
STA004    error     PRNG key reuse: the same key variable consumed by two
                    ``jax.random.*`` draws with no ``split``/``fold_in``
                    reassignment in between (correlated randomness).
STA005    warning   mutable default argument value.
STA006    warning   dtype literal that bypasses the configured precision
                    policy (hardcoded f16/f64 in model code; the policy
                    admits bf16/f32 via ``precision`` config only).
STA007    error     swallowed exception in resilience-critical code
                    (``trainer/``, ``checkpoint/``, ``data/``,
                    ``resilience/``, ``runner/``, ``obs/``): a bare ``except:`` /
                    ``except Exception`` / ``except BaseException``
                    handler that neither re-raises, logs, nor uses the
                    bound exception — a fault-masking black hole in the
                    exact layer whose job is surfacing faults.
STA008    error     stage-shift ``jnp.concatenate`` in a traced context:
                    one operand expanded (``x[None]`` /
                    ``jnp.expand_dims``) concatenated with a partial
                    slice (``s[:-1]`` / ``s[1:]``) of another array —
                    the exact idiom jax 0.4.37's XLA SPMD partitioner
                    MISCOMPILED under model-parallel params riding a
                    vmapped stage dimension (PR 7: every pp x mp
                    MULTICHIP arm computed wrong activations, max error
                    ~11 vs sequential). Use roll-then-overwrite
                    (``jnp.roll(s, 1, 0).at[0].set(inp)``) instead —
                    exact, and partitions correctly.
STA009    error     lock-discipline race: an instance attribute mutated
                    on one thread (a ``threading.Thread(target=...)``
                    entry point's reachable set) and read/written on
                    another (the class's main-thread public API)
                    without a common ``with self.<lock>:`` guard on
                    both paths. Whole-program rule (concurrency.py);
                    ``# sta: lock(<attr>)`` declares deliberate
                    lock-free fields.
STA010    error     device sync on the hot path: ``block_until_ready``
                    / ``device_get`` / ``effects_barrier`` / ``.item()``
                    / ``float()``/``np.asarray()`` on device values in
                    code reachable from the trainer step dispatch, the
                    serve tick, or the fleet router dispatch. The
                    static complement of test_step_path.py's runtime
                    booby-trap. Whole-program rule (concurrency.py).
STA011    error     raw I/O (``open``/``os.replace``/``os.write``/
                    sockets/``Path.read_text``-family) in the gated
                    subsystems (resilience/, serve/, runner/, obs/,
                    checkpoint/) not reachable under ``retry_io`` or a
                    ``FaultPlan`` point — the ROADMAP's "new I/O paths
                    take a fault point + retry" contract, enforced
                    mechanically. Whole-program rule (concurrency.py).
STA012    error     barrier-divergence: an exit path (return /
                    fall-through) skips a named control-plane barrier
                    that another path rendezvouses on, AFTER a shared
                    side-effect in their common prefix — the PR 4
                    split-exit deadlock shape (one host enters
                    ``commit:step-N``, a peer exits early; the barrier
                    never fills). ``raise``/``sys.exit`` exits, abort-
                    flag-checked drains, ``cp.arrive`` paths, and
                    ``# sta: barrier-exempt(<name>)`` are sanctioned.
                    Whole-program rule (protocol.py).
STA013    error     RPC-contract mismatch between a module's client
                    send sites (dict literals with an ``"op"`` key)
                    and its server dispatch table: an op with no
                    handler, a dead handler no client sends, a reply
                    key a client reads that no handler path returns.
                    Whole-program rule (protocol.py).
STA014    error     protocol-edge coverage: an RPC send, named-barrier
                    wait, or replica spawn/kill site in the gated
                    subsystems (+ trainer/) not under a ``FaultPlan``
                    point / ``retry_io`` guard or not inside/beneath an
                    ``obs.span`` — STA011's contract extended to the
                    protocol layer. Whole-program rule (protocol.py).
STA015    warning   stale suppression: a ``# sta: disable=...`` comment
                    on a line where no (suppressed) finding fires, or a
                    ``# sta: lock(attr)`` annotation suppressing no
                    cross-thread hazard. Stale suppressions pre-silence
                    the next real finding on that line/field. Emitted
                    by the whole-program pass only (a per-file-only run
                    cannot tell which program-rule suppressions are
                    live).
STA016    error     trace-propagation: an RPC request dict literal (an
                    ``"op"`` key) in serve/ without a literal
                    ``"trace"`` key. The serving fleet's distributed-
                    tracing contract (docs/OBSERVABILITY.md, Tracing):
                    every envelope crossing a process boundary carries
                    the ambient trace context — even as None — or a
                    failover re-dispatch silently severs the request's
                    timeline. Control-plane envelopes (resilience/)
                    are exempt: their cross-host identity is DERIVED
                    (``derive_trace_id``) at both ends, not carried.
                    Whole-program rule (protocol.py).
========  ========  ==========================================================

Suppress a finding on its line with ``# sta: disable=STA003`` (a comma
rule list, ``# sta: disable=STA009,STA011``, suppresses exactly those
rules) or a bare ``# sta: disable`` (every rule on the line). Suppressed
findings are still reported (with ``suppressed: true``) but do not fail
the gate. STA015 itself is deliberately NOT silenced by the bare form
(a stale bare disable would self-suppress); an explicit
``# sta: disable=STA015`` in the comment's rule list is honored.

*Traced context* (where STA001-STA003 apply) is detected structurally:
functions decorated with ``jax.jit`` / ``jax.checkpoint`` / ``jax.vmap`` /
``jax.grad`` / ``jax.custom_vjp``-style transforms (including through
``functools.partial``), functions passed by name into ``jax.jit`` /
``jax.lax.scan`` / ``while_loop`` / ``cond`` / ``fori_loop`` / ``vmap`` /
``grad`` / ``checkpoint``, ``__call__`` methods of layer classes in the
traced-module allowlist (``nn/``, ``parallel/``, ``ops/``,
``models/transformer/layers/``), and anything nested inside those.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

RULES = {
    "STA001": ("error", "python branch on a traced-array expression"),
    "STA002": ("error", "numpy host op on a traced value"),
    "STA003": ("error", "host sync inside a traced context"),
    "STA004": ("error", "PRNG key consumed twice without split/fold_in"),
    "STA005": ("warning", "mutable default argument"),
    "STA006": ("warning", "dtype literal bypasses the precision policy"),
    "STA007": ("error", "swallowed exception (broad except without "
                        "re-raise/logging/use)"),
    "STA008": ("error", "stage-shift concatenate (expand + partial slice) "
                        "in a traced context — XLA SPMD miscompile hazard"),
    "STA009": ("error", "cross-thread attribute access without a common "
                        "lock guard on both paths"),
    "STA010": ("error", "device sync reachable from the trainer step / "
                        "serve tick hot path"),
    "STA011": ("error", "raw I/O in a gated subsystem outside every "
                        "retry_io / FaultPlan guard"),
    "STA012": ("error", "exit path skips a barrier another path "
                        "rendezvouses on after shared side-effects"),
    "STA013": ("error", "RPC op/reply contract mismatch between client "
                        "sends and the server dispatch table"),
    "STA014": ("error", "protocol edge (rpc send / barrier wait / replica "
                        "spawn-kill) missing fault/retry guard or span"),
    "STA015": ("warning", "stale suppression: a '# sta:' annotation that "
                          "no longer suppresses any finding"),
    "STA016": ("error", "serve/ RPC request dict without a literal "
                        "'trace' key — the envelope must carry the "
                        "ambient trace context"),
}

# Module allowlist for traced-context rules (ISSUE 2: nn/, parallel/, ops/;
# the transformer layer stack is the same traced surface).
TRACED_MODULE_DIRS = (
    "nn",
    "parallel",
    "ops",
    "models/transformer/layers",
)

# Directory allowlist for STA007 (ISSUE 3; runner/ added by ISSUE 4): the
# layers that stand between a fault and a lost run — an exception silently
# eaten here is exactly how a torn checkpoint, a dead data mount, or a
# worker failure the supervisor should have relaunched goes unnoticed.
SWALLOW_SCOPE_DIRS = (
    "trainer",
    "checkpoint",
    "data",
    "resilience",
    "runner",
    # ISSUE 5: telemetry that silently eats its own failures is telemetry
    # you cannot trust during the post-mortem that needed it
    "obs",
    # ISSUE 9: the serving engine is a production loop — a swallowed
    # scheduler/pool/device error here is a request that silently never
    # completes (the exact failure mode the TTFT gates exist to catch)
    "serve",
    # ISSUE 15: the tuner grew CLI/serving-layout I/O (stale-capture
    # records, emitted configs, goldens) — a swallowed read there turns
    # a corrupt calibration file into a silently wrong placement
    "tune",
)

# calls that count as "the handler surfaced the problem"
_LOG_CALL_ATTRS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "print_exc", "print_exception",
}

# jax transforms whose function argument (or decorated function) is traced
_TRACING_TRANSFORMS = {
    "jax.jit",
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.custom_vjp",
    "jax.custom_jvp",
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.cond",
    "jax.lax.fori_loop",
    "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.experimental.shard_map.shard_map",
    "jax.eval_shape",
}

# jax.random draws that CONSUME their key (reusing it correlates streams);
# split/fold_in/PRNGKey/key/key_data/wrap_key_data derive, they don't draw.
_KEY_CONSUMERS = {
    "ball", "bernoulli", "beta", "binomial", "bits", "categorical", "cauchy",
    "chisquare", "choice", "dirichlet", "double_sided_maxwell", "exponential",
    "f", "gamma", "generalized_normal", "geometric", "gumbel", "laplace",
    "loggamma", "logistic", "lognormal", "maxwell", "multivariate_normal",
    "normal", "orthogonal", "pareto", "permutation", "poisson", "rademacher",
    "randint", "rayleigh", "t", "triangular", "truncated_normal", "uniform",
    "wald", "weibull_min",
}

_SUPPRESS_RE = re.compile(r"#\s*sta:\s*disable(?:=([A-Za-z0-9_, ]+))?")


def iter_comments(source: str) -> List[Tuple[int, str]]:
    """(lineno, text) for every actual COMMENT token. Annotation scans
    (``# sta: disable`` / ``lock(...)`` / ``barrier-exempt(...)``) go
    through here so a docstring QUOTING an annotation — this package's
    own docs are full of them — neither suppresses anything nor trips
    the stale-suppression audit. Falls back to a whole-line scan only
    when the source does not tokenize (the syntax-error path, where
    nothing downstream runs anyway)."""
    import io
    import tokenize

    out: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return [(i, text) for i, text in
                enumerate(source.splitlines(), start=1) if "#" in text]
    return out


def parse_suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """line -> suppressed rule set (None = bare disable, every rule).
    Shared by the per-file pass and the whole-program rules
    (concurrency.py) so ``# sta: disable=STA009,STA011`` means the same
    thing everywhere. Only real comments count (see iter_comments)."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, text in iter_comments(source):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        if m.group(1):
            out[i] = {r.strip().upper() for r in m.group(1).split(",")
                      if r.strip()}
        else:
            out[i] = None  # bare disable: every rule
    return out


@dataclasses.dataclass
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        sup = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}{sup}"


# --------------------------------------------------------------- name maps
class _Aliases:
    """Canonicalize attribute chains through the module's imports:
    ``jnp.where`` -> ``jax.numpy.where``, ``np.asarray`` ->
    ``numpy.asarray``, ``partial`` -> ``functools.partial``."""

    def __init__(self, tree: ast.Module):
        self.map: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.map[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    self.map[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted canonical name of a Name/Attribute chain, or None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.map.get(node.id, node.id)
        return ".".join([root] + list(reversed(parts)))


def _is_jax_array_call(aliases: _Aliases, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = aliases.resolve(node.func)
    return bool(
        name
        and (
            name.startswith("jax.numpy.")
            or name.startswith("jax.lax.")
            or name.startswith("jax.nn.")
            or name.startswith("jax.random.")
            or name.startswith("jax.scipy.")
        )
    )


def _contains(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


# Metadata that is static under tracing: `x.shape`-derived ints are host
# values by design, so `int(s * factor)` or `np.zeros(seg.shape, ...)` on
# them is NOT a host sync (float0 cotangents, capacity planning, ...).
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size", "itemsize", "aval",
                 "sharding")


def _walk_skip_static(node: ast.AST):
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            continue  # don't descend: `x.shape` never carries device data
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _contains_traced(aliases: _Aliases, node: ast.AST, names: Set[str]) -> bool:
    """Does ``node`` reference a traced name or jax array call, ignoring
    static-metadata attribute chains?"""
    return any(
        (isinstance(n, ast.Name) and n.id in names)
        or _is_jax_array_call(aliases, n)
        for n in _walk_skip_static(node)
    )


# ------------------------------------------------------------ module lint
class _ModuleLint:
    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=str(path))
        self.aliases = _Aliases(self.tree)
        self.findings: List[Finding] = []
        self.suppressions = self._parse_suppressions(source)
        norm = rel.replace("\\", "/")
        self.in_traced_dir = any(
            f"/{d}/" in f"/{norm}" or norm.startswith(f"scaling_tpu/{d}/")
            for d in TRACED_MODULE_DIRS
        )
        self.in_swallow_scope = any(
            f"/{d}/" in f"/{norm}" or norm.startswith(f"scaling_tpu/{d}/")
            for d in SWALLOW_SCOPE_DIRS
        )
        self.is_config_module = Path(rel).name == "config.py"
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    @staticmethod
    def _parse_suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
        return parse_suppressions(source)

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        rules_at = self.suppressions.get(line, "absent")
        suppressed = rules_at is None or (
            isinstance(rules_at, set) and rule in rules_at
        )
        severity = RULES[rule][0]
        self.findings.append(
            Finding(rule, severity, self.rel, line,
                    getattr(node, "col_offset", 0), message, suppressed)
        )

    # ------------------------------------------------- traced-context set
    def _traced_functions(self) -> Set[ast.AST]:
        funcs = [
            n for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        traced: Set[ast.AST] = set()

        def _transform_target(name: Optional[str]) -> bool:
            # .defvjp/.defjvp catch the fwd/bwd registered on a custom_vjp
            return bool(name) and (
                name in _TRACING_TRANSFORMS
                or name.rsplit(".", 1)[-1]
                in ("shard_map", "pallas_call", "defvjp", "defjvp")
            )

        def _decorator_traces(dec: ast.AST) -> bool:
            name = self.aliases.resolve(dec)
            if _transform_target(name):
                return True
            if isinstance(dec, ast.Call):
                fn = self.aliases.resolve(dec.func)
                if _transform_target(fn):
                    return True
                if fn in ("functools.partial", "partial"):
                    return bool(dec.args) and _transform_target(
                        self.aliases.resolve(dec.args[0])
                    )
            return False

        # (a) decorated with a tracing transform
        for fn in funcs:
            if any(_decorator_traces(d) for d in fn.decorator_list):
                traced.add(fn)
        # (b) passed by name into a tracing transform
        passed: Set[str] = set()
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            if _transform_target(self.aliases.resolve(call.func)):
                for arg in call.args:
                    if isinstance(arg, ast.Name):
                        passed.add(arg.id)
        for fn in funcs:
            if fn.name in passed:
                traced.add(fn)
        # (c) __call__ / forward methods of classes in traced modules
        if self.in_traced_dir:
            for fn in funcs:
                if fn.name in ("__call__", "forward") and isinstance(
                    self._parents.get(fn), ast.ClassDef
                ):
                    traced.add(fn)
        # (d) closure: anything nested inside a traced function
        changed = True
        while changed:
            changed = False
            for fn in funcs:
                if fn in traced:
                    continue
                p = self._parents.get(fn)
                while p is not None:
                    if p in traced:
                        traced.add(fn)
                        changed = True
                        break
                    p = self._parents.get(p)
        return traced

    # ------------------------------------------------------- rule drivers
    def run(self) -> List[Finding]:
        traced = self._traced_functions()
        for fn in traced:
            self._check_traced_function(fn, traced)
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_mutable_defaults(node)
                self._check_key_reuse(node)
        if self.in_traced_dir and not self.is_config_module:
            self._check_dtype_policy()
        if self.in_swallow_scope:
            self._check_swallowed_exceptions()
        return self.findings

    # ------------------------------------------------------ STA007 driver
    def _check_swallowed_exceptions(self) -> None:
        """A broad handler must do SOMETHING with the exception: re-raise,
        log it (any ``logger``-style method, ``warnings.warn``, ``print``,
        ``traceback.print_exc``), or at least reference the bound name
        (propagating it by other means, e.g. queueing it for a consumer).
        """
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad_catch(node.type):
                continue
            if not self._handler_surfaces(node):
                caught = (
                    "bare except" if node.type is None
                    else f"except {self.aliases.resolve(node.type) or '...'}"
                )
                self._emit(
                    "STA007", node,
                    f"{caught} swallows the exception (no re-raise, no "
                    "logging, bound name unused); faults in this layer "
                    "must surface",
                )

    def _is_broad_catch(self, type_node: Optional[ast.AST]) -> bool:
        if type_node is None:
            return True  # bare except:
        types = (
            list(type_node.elts)
            if isinstance(type_node, ast.Tuple)
            else [type_node]
        )
        for t in types:
            name = self.aliases.resolve(t)
            if name and name.rsplit(".", 1)[-1] in ("Exception", "BaseException"):
                return True
        return False

    def _handler_surfaces(self, handler: ast.ExceptHandler) -> bool:
        bound = handler.name
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                fname = self.aliases.resolve(n.func)
                if fname in ("print", "warnings.warn", "traceback.print_exc"):
                    return True
                if (
                    isinstance(n.func, ast.Attribute)
                    and n.func.attr in _LOG_CALL_ATTRS
                ):
                    return True
            if (
                bound
                and isinstance(n, ast.Name)
                and n.id == bound
                and isinstance(n.ctx, ast.Load)
            ):
                return True
        return False

    # ------------------------------------------------ traced-context rules
    def _own_nodes(self, fn: ast.AST) -> Iterable[ast.AST]:
        """Walk ``fn``'s body without descending into nested functions
        (each traced nested function is checked on its own)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _traced_names(self, fn) -> Set[str]:
        """Parameters + anything (transitively) assigned from them or from
        a jax call — tuple unpacking included, so ``a, b = res`` taints
        both halves."""
        names = {
            a.arg
            for a in list(fn.args.args) + list(fn.args.kwonlyargs)
            + list(fn.args.posonlyargs)
            if a.arg not in ("self", "cls")
        }

        def tainted(value: ast.AST) -> bool:
            return _contains_traced(self.aliases, value, names)

        changed = True
        while changed:
            changed = False
            for node in self._own_nodes(fn):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign) and tainted(node.value):
                    targets = list(node.targets)
                elif (
                    isinstance(node, (ast.AnnAssign, ast.AugAssign))
                    and node.value is not None
                    and tainted(node.value)
                ):
                    targets = [node.target]
                elif isinstance(node, ast.For) and tainted(node.iter):
                    targets = [node.target]
                for tgt in targets:
                    for el in ast.walk(tgt):
                        if isinstance(el, ast.Name) and el.id not in names:
                            names.add(el.id)
                            changed = True
        return names

    def _check_traced_function(self, fn, traced: Set[ast.AST]) -> None:
        traced_names = self._traced_names(fn)

        def expr_is_traced(node: ast.AST) -> bool:
            return _contains_traced(self.aliases, node, traced_names)

        for node in self._own_nodes(fn):
            # STA001: branch whose test computes on device
            if isinstance(node, (ast.If, ast.While)):
                if self._test_computes_on_device(node.test, traced_names):
                    self._emit(
                        "STA001", node,
                        "python control flow on a traced-array expression "
                        "(retrace/concretization hazard); use jnp.where / "
                        "lax.cond",
                    )
            if isinstance(node, ast.Call):
                fname = self.aliases.resolve(node.func)
                # STA001 (bool() concretization)
                if (
                    fname == "bool"
                    and node.args
                    and expr_is_traced(node.args[0])
                ):
                    self._emit(
                        "STA001", node,
                        "bool() on a traced value concretizes the tracer",
                    )
                # STA003: float()/int() host syncs
                elif (
                    fname in ("float", "int")
                    and node.args
                    and expr_is_traced(node.args[0])
                ):
                    self._emit(
                        "STA003", node,
                        f"{fname}() on a traced value blocks on a "
                        "device->host transfer",
                    )
                # STA003: np.asarray/np.array pulls the value to host
                elif (
                    fname in ("numpy.asarray", "numpy.array")
                    and node.args
                    and expr_is_traced(node.args[0])
                ):
                    self._emit(
                        "STA003", node,
                        f"{fname.replace('numpy', 'np')}() on a traced value "
                        "is a host sync; use jnp.asarray",
                    )
                # STA002: any other numpy op fed a traced value
                elif (
                    fname
                    and fname.startswith("numpy.")
                    and fname not in ("numpy.dtype", "numpy.ndarray")
                    and any(expr_is_traced(a) for a in node.args)
                ):
                    self._emit(
                        "STA002", node,
                        f"{fname} applied to a traced value runs on host; "
                        "use the jnp equivalent",
                    )
                # STA003: .item()
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    self._emit(
                        "STA003", node,
                        ".item() inside a traced context is a host sync",
                    )
                # STA008: stage-shift concatenate (the PR 7 SPMD
                # miscompile idiom: concatenate([inp[None], s[:-1]]))
                elif (
                    fname in ("jax.numpy.concatenate", "jax.lax.concatenate")
                    and node.args
                    and isinstance(node.args[0], (ast.List, ast.Tuple))
                    and self._is_stage_shift_concat(node.args[0].elts)
                ):
                    self._emit(
                        "STA008", node,
                        "concatenate of an expanded operand with a partial "
                        "slice builds a shifted array; XLA SPMD miscompiles "
                        "this under model-parallel params on a vmapped "
                        "stage dim (PR 7) — use roll-then-overwrite "
                        "(jnp.roll(...).at[0].set(...))",
                    )

    # ------------------------------------------------------ STA008 helpers
    def _is_stage_shift_concat(self, elts) -> bool:
        """True when the operand list pairs an EXPANDED array (``x[None]``
        / ``x[None, ...]`` / ``jnp.expand_dims(x, 0)``) with a PARTIAL
        slice of another (``s[:-1]`` / ``s[1:]``) — together they build a
        shifted copy, the shape XLA SPMD mis-partitions when a stage
        vmap carries model-parallel params."""

        def is_expand(e: ast.AST) -> bool:
            if isinstance(e, ast.Subscript):
                idx = e.slice
                parts = idx.elts if isinstance(idx, ast.Tuple) else [idx]
                return any(
                    isinstance(p, ast.Constant) and p.value is None
                    for p in parts
                )
            if isinstance(e, ast.Call):
                name = self.aliases.resolve(e.func)
                return bool(name) and name.rsplit(".", 1)[-1] == "expand_dims"
            return False

        def is_partial_slice(e: ast.AST) -> bool:
            if not isinstance(e, ast.Subscript):
                return False
            idx = e.slice
            parts = idx.elts if isinstance(idx, ast.Tuple) else [idx]
            return any(
                isinstance(p, ast.Slice)
                and (p.lower is not None or p.upper is not None)
                for p in parts
            )

        return any(is_expand(e) for e in elts) and any(
            is_partial_slice(e) and not is_expand(e) for e in elts
        )

    def _test_computes_on_device(self, test: ast.AST, traced_names) -> bool:
        """A branch test is device-valued when it CALLS into jax (jnp.any,
        lax reductions) or reduces a traced name via .any()/.all()/.sum()/
        .max()/.min(); bare name/attribute tests (``if mask is None``,
        ``if self.causal``) stay host-static and legal."""
        for n in ast.walk(test):
            if _is_jax_array_call(self.aliases, n):
                return True
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("any", "all", "sum", "max", "min", "mean")
                and _contains(
                    n.func.value,
                    lambda m: isinstance(m, ast.Name) and m.id in traced_names,
                )
            ):
                return True
        return False

    # ------------------------------------------------------ STA004 driver
    def _check_key_reuse(self, fn) -> None:
        """Statement-aware scan: a draw's USES evaluate before the
        statement's own ASSIGNS (``key = normal(key)`` is a reuse after a
        prior draw), and mutually exclusive if/else branches each get
        their own copy of the consumed-key state (one draw per branch is
        fine; a draw in either branch conflicts with a later one)."""
        self._scan_key_stmts(list(fn.body), {})

    def _key_expr_events(self, node: ast.AST, last_use: Dict[str, int],
                         with_assigns: bool = False) -> None:
        uses: List[Tuple[int, int, str]] = []
        assigns: List[str] = []
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested functions scanned on their own
            if isinstance(n, ast.Call):
                name = self.aliases.resolve(n.func)
                if (
                    name
                    and name.startswith("jax.random.")
                    and name.rsplit(".", 1)[-1] in _KEY_CONSUMERS
                    and n.args
                    and isinstance(n.args[0], ast.Name)
                ):
                    uses.append((n.lineno, n.col_offset, n.args[0].id))
            targets: List[ast.AST] = []
            if with_assigns and isinstance(n, ast.Assign):
                targets = list(n.targets)
            elif with_assigns and isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            elif isinstance(n, ast.NamedExpr):
                targets = [n.target]
            for tgt in targets:
                for el in ast.walk(tgt):
                    if isinstance(el, ast.Name):
                        assigns.append(el.id)
            stack.extend(ast.iter_child_nodes(n))
        for line, col, name in sorted(uses):
            if name in last_use:
                self._emit(
                    "STA004",
                    _Loc(line, col),
                    f"PRNG key {name!r} already consumed at line "
                    f"{last_use[name]}; split/fold_in before drawing again",
                )
            else:
                last_use[name] = line
        for name in assigns:  # RHS evaluates first: assigns clear AFTER uses
            last_use.pop(name, None)

    def _assign_targets(self, tgt: ast.AST, last_use: Dict[str, int]) -> None:
        for el in ast.walk(tgt):
            if isinstance(el, ast.Name):
                last_use.pop(el.id, None)

    def _scan_key_stmts(
        self, stmts: List[ast.AST], last_use: Dict[str, int]
    ) -> Dict[str, int]:
        for st in stmts:
            if isinstance(
                st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(st, ast.If):
                self._key_expr_events(st.test, last_use)
                b1 = self._scan_key_stmts(list(st.body), dict(last_use))
                b2 = self._scan_key_stmts(list(st.orelse), dict(last_use))
                last_use = {**b1, **b2}
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._key_expr_events(st.iter, last_use)
                self._assign_targets(st.target, last_use)
                last_use = self._scan_key_stmts(list(st.body), last_use)
                last_use = self._scan_key_stmts(list(st.orelse), last_use)
            elif isinstance(st, ast.While):
                self._key_expr_events(st.test, last_use)
                last_use = self._scan_key_stmts(list(st.body), last_use)
                last_use = self._scan_key_stmts(list(st.orelse), last_use)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._key_expr_events(item.context_expr, last_use)
                    if item.optional_vars is not None:
                        self._assign_targets(item.optional_vars, last_use)
                last_use = self._scan_key_stmts(list(st.body), last_use)
            elif isinstance(st, ast.Try):
                merged = self._scan_key_stmts(list(st.body), dict(last_use))
                for h in st.handlers:
                    merged = {
                        **merged,
                        **self._scan_key_stmts(list(h.body), dict(last_use)),
                    }
                last_use = self._scan_key_stmts(list(st.orelse), merged)
                last_use = self._scan_key_stmts(list(st.finalbody), last_use)
            else:
                self._key_expr_events(st, last_use, with_assigns=True)
        return last_use

    # ------------------------------------------------------ STA005 driver
    def _check_mutable_defaults(self, fn) -> None:
        for default in list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None
        ]:
            mutable = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set", "bytearray")
            )
            if mutable:
                self._emit(
                    "STA005", default,
                    f"mutable default argument in {fn.name}(); "
                    "default to None and construct inside",
                )

    # ------------------------------------------------------ STA006 driver
    def _check_dtype_policy(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute):
                name = self.aliases.resolve(node)
                if name in (
                    "jax.numpy.float16", "jax.numpy.float64",
                    "numpy.float16", "numpy.float64",
                ):
                    self._emit(
                        "STA006", node,
                        f"hardcoded {name.rsplit('.', 1)[-1]} bypasses the "
                        "configured precision policy (config.precision "
                        "decides bf16/f32)",
                    )
            elif isinstance(node, ast.Call):
                is_astype = (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                )
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value in ("float16", "float64")
                    ):
                        self._emit(
                            "STA006", kw.value,
                            f"dtype string {kw.value.value!r} bypasses the "
                            "precision policy",
                        )
                if is_astype and node.args:
                    a = node.args[0]
                    if isinstance(a, ast.Constant) and a.value in (
                        "float16", "float64"
                    ):
                        self._emit(
                            "STA006", a,
                            f"astype({a.value!r}) bypasses the precision "
                            "policy",
                        )


class _Loc:
    """Synthetic location carrier for findings not tied to one node."""

    def __init__(self, lineno: int, col_offset: int):
        self.lineno = lineno
        self.col_offset = col_offset


# ------------------------------------------------------------- public API
def lint_file(path: Path, root: Optional[Path] = None) -> List[Finding]:
    root = root or Path.cwd()
    try:
        rel = str(path.resolve().relative_to(root.resolve()))
    except ValueError:
        rel = str(path)
    source = path.read_text()
    try:
        return _ModuleLint(path, rel, source).run()
    except SyntaxError as e:
        return [
            Finding("STA000", "error", rel, e.lineno or 0, e.offset or 0,
                    f"syntax error: {e.msg}")
        ]


def _stale_disables(
    files: List[Path], root: Path, findings: List[Finding]
) -> List[Finding]:
    """STA015 (disable half): every ``# sta: disable[=rules]`` comment
    must suppress at least one finding that actually fires on its line
    (restricted to the listed rules when a list is given). Emitted
    unsuppressed by design — a stale bare disable must not silence its
    own staleness finding; an explicit ``disable=STA015`` is honored
    (and marks the comment intentional)."""
    by_loc: Dict[Tuple[str, int], Set[str]] = {}
    for f in findings:
        if f.suppressed:
            by_loc.setdefault((f.path, f.line), set()).add(f.rule)
    out: List[Finding] = []
    for path in files:
        try:
            rel = str(path.resolve().relative_to(root.resolve()))
        except ValueError:
            rel = str(path)
        try:
            source = path.read_text()
        except OSError:
            continue
        for line, rules in sorted(parse_suppressions(source).items()):
            if rules is not None and "STA015" in rules:
                continue  # explicitly opted out / self-referential
            fired = by_loc.get((rel, line), set())
            live = fired if rules is None else (fired & rules)
            if live:
                continue
            listed = "" if rules is None else "=" + ",".join(sorted(rules))
            out.append(Finding(
                "STA015", RULES["STA015"][0], rel, line, 0,
                f"stale '# sta: disable{listed}': no finding fires on "
                "this line any more — remove the comment so it cannot "
                "pre-suppress the next real finding here",
                False,
            ))
    return out


def lint_paths(
    paths: Iterable[Path | str],
    root: Optional[Path] = None,
    program: bool = True,
    graph=None,
) -> List[Finding]:
    """Lint every ``.py`` under ``paths`` (files or directories).

    Runs the per-file AST rules (STA001-STA008) plus — unless
    ``program=False`` — the whole-program call-graph rules
    (STA009-STA014, concurrency.py + protocol.py) and the
    stale-suppression audit (STA015) over the same path set as one
    analysis unit. Pass ``graph`` (a prebuilt ``CallGraph`` over the
    same paths) to skip the rebuild — the CLI constructs one graph per
    run and shares it across commands. Ordering is stable:
    (path, line, col, rule)."""
    root = Path(root) if root else Path.cwd()
    # materialize once: a generator argument would be exhausted by the
    # per-file loop and silently hand check_program an EMPTY path set
    paths = [Path(p) for p in paths]
    findings: List[Finding] = []
    seen_files: List[Path] = []
    for p in paths:
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            findings.extend(lint_file(f, root))
            seen_files.append(f)
    if program:
        from .concurrency import check_program

        findings.extend(check_program(paths, root=root, graph=graph))
        # stale-disable audit LAST: it needs the complete finding set
        # (per-file + whole-program) to judge what a comment suppresses
        findings.extend(_stale_disables(seen_files, root, findings))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
