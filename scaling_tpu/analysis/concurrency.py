"""Whole-program concurrency & hot-path contract rules (STA009-STA011).

Three gate rules over the :mod:`callgraph` engine, each encoding a
contract the framework previously enforced only by live debugging:

**STA009 — lock-discipline race lint.** For every class that spawns a
``threading.Thread(target=...)`` onto one of its own methods (or a
closure inside one), partition the class's code into *sides*: each
thread entry's reachable method set, plus the main-thread side (the
public API). An instance attribute MUTATED on one side and read or
written on another must share a common ``with self.<lock>:`` guard on
both paths — the PR 4 file-backend temp-name race (async writer vs
heartbeat loop), the PR 5 mid-snapshot registry races, and the PR 14
submit-vs-tick convoy were all exactly this shape. Deliberately
lock-free fields (GIL-atomic scalar handoffs like a watchdog's
``_last_beat``) are declared with an ``# sta: lock(<attr>, ...)``
annotation anywhere in the class body, with a comment saying WHY.

**STA010 — device-sync-on-hot-path.** The static complement of
``test_step_path.py``'s runtime booby-trap: walking the call graph from
the trainer step dispatch (``run_training`` / ``train_step``), the
serving tick (``ServeEngine.tick``), and the fleet router dispatch
(``FleetRouter.submit``), flag every device-sync primitive —
``jax.block_until_ready`` / ``jax.device_get`` / ``jax.effects_barrier``
by name, ``.item()`` on anything, and ``float()`` / ``int()`` /
``bool()`` / ``np.asarray()`` applied to a value the intra-function
taint analysis traces back to a device computation (a ``jax.*`` call, a
``device_put``, or a call into a function whose return is
device-tainted — including unresolvable program-handle calls fed
device operands). The documented sync windows (checkpoint save, eval,
preemption exit, stall forensics) are pruned via ``HOT_PATH_STOPS``;
the remaining deliberate syncs (the log-interval fetch, the tick's
token landing) carry per-line suppressions with justifying comments.
Traced (jitted) functions are skipped — inside a traced context these
ops are not host syncs, and STA001-003 already police that surface.

**STA011 — unguarded-I/O audit.** The ROADMAP resilience contract
("new I/O paths take a FaultPlan point + retry") enforced mechanically:
raw ``open`` / ``os.replace`` / ``os.rename`` / ``os.write`` /
``socket.*`` / ``Path.read_text``-family calls inside the gated
subsystems (``resilience/``, ``serve/``, ``runner/``, ``obs/``,
``checkpoint/``) must be *reachable under* a guard — a function that
fires a :class:`FaultPlan` point, or a callable passed into
``retry_io`` (closures and lambdas included); everything such a
function transitively calls inherits the guard (the retry/fault layer
wraps the whole operation). Anything else is a new I/O path dodging
the contract — wire it through ``retry_io``/a fault point, or suppress
with a comment explaining why the path must stay raw (e.g. obs cannot
import resilience without inverting the layering).

All three ride the standard lint plumbing: per-line
``# sta: disable=STA0xx`` suppression, findings in the same JSON
schema, clean tree pinned at zero unsuppressed findings.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import CallGraph, ClassInfo, FunctionInfo, own_nodes

# ---------------------------------------------------------------- config

# Hot-path roots (STA010), matched as dotted-name suffixes against the
# analyzed tree: the trainer's step dispatch, the serving engine's tick,
# and the fleet router's dispatch path.
HOT_PATH_ROOTS = (
    "run_training",
    "train_step",
    "ServeEngine.tick",
    "ServeEngine.run_until_done",
    "FleetRouter.submit",
)

# Subtrees pruned from the hot path: these are the DOCUMENTED sync
# windows (checkpointing and eval drain the device by design, the
# preemption/stall paths run off the steady-state loop). A sync inside
# them is policy, not a regression.
HOT_PATH_STOPS = (
    "save_checkpoint",
    "_save_checkpoint_inner",
    "load_checkpoint",
    "_load_step_dir",
    "eval_step",
    "_eval_step_inner",
    "_preemption_exit",
    "_on_step_stall",
    "_run_checkpoint_hooks",
    "finalize_checkpoints",
    "stop_prefetch",
)

# Device-sync primitives flagged by NAME wherever they appear on the hot
# path (exactly the set the runtime booby-trap in
# tests/core/test_obs/test_step_path.py monkeypatches to explode).
SYNC_PRIMITIVES = {
    "jax.block_until_ready",
    "jax.device_get",
    "jax.effects_barrier",
}

# host conversions flagged when fed a device-tainted value
_HOST_CONVERSIONS = {"float", "int", "bool"}
_HOST_PULLS = {"numpy.asarray", "numpy.array"}

# Directory scope of the unguarded-I/O audit (STA011): the subsystems
# whose I/O the resilience gate owns.
IO_SCOPE_DIRS = ("resilience", "serve", "runner", "obs", "checkpoint")

# raw I/O callables by resolved dotted name
_RAW_IO_NAMES = {
    "open", "os.open", "os.replace", "os.rename", "os.write",
    "socket.socket", "socket.create_connection",
}
# raw I/O method calls by attribute name (Path-object file I/O)
_RAW_IO_ATTRS = {"write_text", "write_bytes", "read_text", "read_bytes"}

# Process-lifecycle fault points: they model step/process faults (a
# kill at the loop top, an injected NaN), NOT I/O coverage — a function
# firing one does not make the checkpoint/journal writes it transitively
# reaches "guarded" (the whole save tree hangs off the train loop).
PROCESS_FAULT_POINTS = {
    "signal.sigterm", "host.kill", "host.hang", "step.nan_grads",
    # the serve worker's mid-tick SIGKILL drill: fired at the replica
    # tick-loop top, same class of point as host.kill — the engine tick
    # tree hanging off it is compute, not I/O
    "serve.replica.kill",
}

# lock-free-field annotation: ``# sta: lock(attr_a, attr_b)`` in a class
# body declares those instance attributes' lock-free sharing deliberate
_LOCKFREE_RE = re.compile(r"#\s*sta:\s*lock\(([^)]*)\)")


def _annotation_comments(mod, node) -> List[Tuple[int, str]]:
    """Real COMMENT tokens within a node's lexical range (docstrings
    quoting an annotation never count — see lint.iter_comments)."""
    from .lint import iter_comments

    end = getattr(node, "end_lineno", node.lineno)
    return [(i, t) for i, t in iter_comments(mod.source)
            if node.lineno <= i <= end]

# attribute types that are themselves synchronization/thread-safe
_SAFE_ATTR_CONSTRUCTORS = (
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "collections.deque",
)
_LOCK_CONSTRUCTORS = ("threading.Lock", "threading.RLock",
                      "threading.Condition")

# collection mutators: calling one of these ON an attribute mutates it
_MUTATING_METHODS = {
    "append", "appendleft", "add", "remove", "discard", "pop", "popleft",
    "popitem", "clear", "extend", "extendleft", "insert", "update",
    "setdefault", "sort", "reverse", "rotate",
}


# ---------------------------------------------------------------- shared
class _Emitter:
    """Finding construction + per-line suppression, shared by the three
    rules (same contract as the per-file lint)."""

    def __init__(self) -> None:
        from .lint import Finding, RULES  # lazy: lint imports us lazily too

        self._Finding = Finding
        self._rules = RULES
        self.findings: List = []
        self._suppressions: Dict[str, Dict[int, Optional[Set[str]]]] = {}

    def _file_suppressions(self, mod) -> Dict[int, Optional[Set[str]]]:
        if mod.rel not in self._suppressions:
            from .lint import parse_suppressions

            self._suppressions[mod.rel] = parse_suppressions(mod.source)
        return self._suppressions[mod.rel]

    def emit(self, rule: str, mod, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        rules_at = self._file_suppressions(mod).get(line, "absent")
        suppressed = rules_at is None or (
            isinstance(rules_at, set) and rule in rules_at
        )
        self.findings.append(self._Finding(
            rule, self._rules[rule][0], mod.rel, line,
            getattr(node, "col_offset", 0), message, suppressed,
        ))


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.<attr>`` -> attr name (one level only)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


# ================================================================ STA009
class _ClassConcurrency:
    """Per-class lock/thread model: lock attrs, safe attrs, lock-free
    annotations, and the attribute-access inventory per side."""

    def __init__(self, graph: CallGraph, cinfo: ClassInfo):
        self.graph = graph
        self.cinfo = cinfo
        self.lock_attrs: Set[str] = set()
        self.safe_attrs: Set[str] = set()
        self.lockfree: Set[str] = set()
        self._scan_attr_kinds()
        self._scan_annotations()

    def _scan_attr_kinds(self) -> None:
        mod = self.cinfo.module
        for meth in self.cinfo.methods.values():
            for node in ast.walk(meth.node):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                name = mod.imports.resolve(node.value.func)
                if name is None:
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    if name in _LOCK_CONSTRUCTORS:
                        self.lock_attrs.add(attr)
                        self.safe_attrs.add(attr)
                    elif name in _SAFE_ATTR_CONSTRUCTORS:
                        self.safe_attrs.add(attr)

    def _scan_annotations(self) -> None:
        for _, text in _annotation_comments(self.cinfo.module,
                                            self.cinfo.node):
            m = _LOCKFREE_RE.search(text)
            if m:
                self.lockfree.update(
                    a.strip() for a in m.group(1).split(",") if a.strip()
                )

    # ---------------------------------------------------------- accesses
    def class_functions(self) -> Set[str]:
        """Qualnames of this class's methods and their nested closures
        (both see ``self``)."""
        out: Set[str] = set()
        for fn in self.graph.functions.values():
            if fn.module is not self.cinfo.module:
                continue
            top = fn.dotted.split(".")[0]
            if top == self.cinfo.name:
                out.add(fn.qualname)
        return out

    def side_functions(self, entry: FunctionInfo,
                       stops: Iterable[str] = ()) -> Set[str]:
        """The subset of this class's functions reachable from ``entry``
        (the thread's — or the public API's — footprint inside the
        class). ``stops`` cuts traversal at the named functions: the
        main-thread side passes the thread entries' dotted names so a
        helper reachable ONLY through a spawn target stays on the
        thread's side (a shared helper, also called from a main-side
        path, still lands on both)."""
        in_class = self.class_functions()
        reach = self.graph.reachable([entry], stops=stops)
        return {f.qualname for f in reach if f.qualname in in_class}

    def accesses(self, funcs: Set[str], skip_init: bool = True
                 ) -> Dict[str, List[Tuple[str, FunctionInfo, ast.AST,
                                           frozenset]]]:
        """attr -> [(kind, function, node, locks_held)] over ``funcs``.
        ``kind`` is 'read' or 'write'. ``locks_held`` is the set of this
        class's lock attributes lexically held (``with self.<lock>:``)
        at the access, plus locks held at every call site on all paths
        into the function from the side's entry (computed by the
        caller via :meth:`entry_locks`)."""
        out: Dict[str, List[Tuple[str, FunctionInfo, ast.AST, frozenset]]] = {}
        for qual in sorted(funcs):
            fn = self.graph.functions[qual]
            if skip_init and fn.dotted.endswith("__init__"):
                continue
            for attr, kind, node, locks in self._scan_function(fn):
                out.setdefault(attr, []).append((kind, fn, node, locks))
        return out

    def _scan_function(self, fn: FunctionInfo):
        """Yield (attr, kind, node, lexical_locks) for every self-attr
        access in ``fn``, tracking the ``with self.<lock>:`` stack."""
        results: List[Tuple[str, str, ast.AST, frozenset]] = []

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return  # closures scanned as their own functions
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = list(held)
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.lock_attrs:
                        acquired.append(attr)
                for item in node.items:
                    walk(item.context_expr, held)
                for child in node.body:
                    walk(child, tuple(acquired))
                return
            attr = _self_attr(node)
            if attr is not None:
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    results.append((attr, "write", node, frozenset(held)))
                else:
                    results.append((attr, "read", node, frozenset(held)))
            # self.attr[i] = v / self.attr += v mutate the attr
            if isinstance(node, ast.Subscript):
                a = _self_attr(node.value)
                if a is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
                    results.append((a, "write", node, frozenset(held)))
            if isinstance(node, ast.AugAssign):
                a = _self_attr(node.target)
                if a is not None:
                    results.append((a, "write", node.target, frozenset(held)))
                sub = node.target
                if isinstance(sub, ast.Subscript):
                    a = _self_attr(sub.value)
                    if a is not None:
                        results.append((a, "write", sub, frozenset(held)))
            # mutating method call: self.attr.append(...)
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr in _MUTATING_METHODS:
                a = _self_attr(node.func.value)
                if a is not None:
                    results.append((a, "write", node, frozenset(held)))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for child in ast.iter_child_nodes(fn.node):
            walk(child, ())
        return results

    def entry_locks(self, entry: FunctionInfo, side: Set[str]
                    ) -> Dict[str, frozenset]:
        """For each function of the side, the set of locks held on EVERY
        call path from ``entry`` (meet-over-paths: intersection). A
        method only ever invoked inside ``with self._lock:`` inherits
        the guard."""
        # call sites are invariant across fixed-point iterations — scan
        # each side function's AST once, not once per iteration
        sites = {qual: self._call_sites(self.graph.functions[qual])
                 for qual in side}
        state: Dict[str, Optional[frozenset]] = {entry.qualname: frozenset()}
        changed = True
        while changed:
            changed = False
            for qual in sorted(side):
                locks = state.get(qual)
                if locks is None:
                    continue
                for callee, call_locks in sites[qual]:
                    if callee not in side:
                        continue
                    merged = locks | call_locks
                    prev = state.get(callee)
                    new = merged if prev is None else (prev & merged)
                    if new != prev:
                        state[callee] = new
                        changed = True
        return {q: (s or frozenset()) for q, s in state.items()
                if s is not None}

    def _call_sites(self, fn: FunctionInfo
                    ) -> List[Tuple[str, frozenset]]:
        """(callee qualname, lexical locks at the call) pairs inside
        ``fn``."""
        sites: List[Tuple[str, frozenset]] = []
        local_types = self.graph._local_types(fn)

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = list(held)
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.lock_attrs:
                        acquired.append(attr)
                for child in node.body:
                    walk(child, tuple(acquired))
                return
            if isinstance(node, ast.Call):
                target = self.graph.resolve_callable(
                    self.graph.functions[fn.qualname], node.func, local_types
                )
                if target is not None:
                    sites.append((target.qualname, frozenset(held)))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for child in ast.iter_child_nodes(fn.node):
            walk(child, ())
        return sites


def check_lock_discipline(
    graph: CallGraph,
    lock_usage: Optional[Set[Tuple[str, str]]] = None,
) -> List:
    """STA009 over every class that spawns threads onto its own code.

    ``lock_usage`` (when given) collects ``(class_dotted, attr)`` pairs
    whose ``# sta: lock(attr)`` annotation suppressed a real hazard —
    the stale-suppression audit's ground truth."""
    em = _Emitter()
    # class dotted -> [(side label, entry FunctionInfo)]
    per_class: Dict[str, List[Tuple[str, FunctionInfo]]] = {}
    for spawn in graph.thread_spawns:
        tgt = spawn.target
        if tgt is None:
            continue
        # the thread target must belong to a class of the same module:
        # a method, or a closure nested inside one
        owner = tgt.dotted.split(".")[0]
        cinfo = tgt.module.classes.get(owner)
        if cinfo is None:
            continue
        per_class.setdefault(cinfo.dotted, [])
        label = f"thread '{tgt.name}'"
        if (label, tgt) not in per_class[cinfo.dotted]:
            per_class[cinfo.dotted].append((label, tgt))

    for class_dotted in sorted(per_class):
        cinfo = graph.classes[class_dotted]
        model = _ClassConcurrency(graph, cinfo)
        entries = per_class[class_dotted]
        thread_entry_names = {e.qualname for _, e in entries}

        sides: List[Tuple[str, Dict[str, List]]] = []
        for label, entry in entries:
            side = model.side_functions(entry)
            locks = model.entry_locks(entry, side)
            acc = model.accesses(side)
            sides.append((label, _with_entry_locks(acc, locks)))

        # the main-thread side: the public API and everything it reaches
        # WITHOUT traversing into a spawn target — a helper reachable
        # only through the thread entry belongs to the thread's side,
        # not the main side (else a thread-exclusive field reads as a
        # race of the worker against itself)
        thread_stops = [e.dotted for _, e in entries]
        main_entries = [
            m for name, m in sorted(cinfo.methods.items())
            if not name.startswith("_") and m.qualname
            not in thread_entry_names
        ]
        main_acc_merged: Dict[str, List] = {}
        for m in main_entries:
            side = model.side_functions(m, stops=thread_stops)
            side -= thread_entry_names  # spawn target runs on ITS thread
            locks = model.entry_locks(m, side)
            for attr, lst in _with_entry_locks(
                model.accesses(side), locks
            ).items():
                main_acc_merged.setdefault(attr, []).extend(lst)
        if main_acc_merged:
            sides.append(("the main-thread public API", main_acc_merged))

        _report_races(em, cinfo, model, sides, lock_usage)
    return em.findings


def _with_entry_locks(acc: Dict[str, List], locks: Dict[str, frozenset]
                      ) -> Dict[str, List]:
    out: Dict[str, List] = {}
    for attr, lst in acc.items():
        out[attr] = [
            (kind, fn, node, held | locks.get(fn.qualname, frozenset()))
            for kind, fn, node, held in lst
        ]
    return out


def _report_races(em: _Emitter, cinfo: ClassInfo, model: _ClassConcurrency,
                  sides: List[Tuple[str, Dict[str, List]]],
                  lock_usage: Optional[Set[Tuple[str, str]]] = None) -> None:
    attrs: Set[str] = set()
    for _, acc in sides:
        attrs |= set(acc)
    for attr in sorted(attrs):
        if attr in model.safe_attrs:
            continue
        # collect (side, access) pairs; hazard = a WRITE on one side and
        # any access on another with no common lock between them
        hazard = None
        hazard_key = None
        for i, (label_w, acc_w) in enumerate(sides):
            for kind, fn_w, node_w, locks_w in acc_w.get(attr, ()):
                if kind != "write":
                    continue
                for j, (label_o, acc_o) in enumerate(sides):
                    if i == j:
                        continue
                    for okind, fn_o, node_o, locks_o in acc_o.get(attr, ()):
                        if locks_w & locks_o:
                            continue
                        key = (node_w.lineno, node_o.lineno, label_w,
                               label_o)
                        if hazard_key is None or key < hazard_key:
                            hazard_key = key
                            hazard = (label_w, fn_w, node_w,
                                      label_o, fn_o, node_o, okind)
        if hazard is None:
            continue
        # the lockfree check sits AFTER hazard detection so the stale-
        # suppression audit (STA015) can tell a load-bearing
        # `# sta: lock(attr)` from one whose hazard no longer exists
        if attr in model.lockfree:
            if lock_usage is not None:
                lock_usage.add((cinfo.dotted, attr))
            continue
        label_w, fn_w, node_w, label_o, fn_o, node_o, okind = hazard
        em.emit(
            "STA009", cinfo.module, node_w,
            f"{cinfo.name}.{attr} is written on {label_w} "
            f"({fn_w.name}, line {node_w.lineno}) and "
            f"{'written' if okind == 'write' else 'read'} on {label_o} "
            f"({fn_o.name}, line {node_o.lineno}) with no common "
            f"'with self.<lock>:' guard — a cross-thread race. Guard "
            f"both paths with one lock, or declare the field "
            f"deliberately lock-free with '# sta: lock({attr})' and a "
            f"comment saying why (e.g. GIL-atomic scalar handoff)",
        )


# ================================================================ STA010
class _TaintScan:
    """Intra-function device-value taint with cross-function return
    propagation: which names carry (possibly) device-resident arrays."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.returns_device: Dict[str, bool] = {}

    def _call_is_device(self, fn: FunctionInfo, node: ast.Call,
                        tainted: Set[str], local_types) -> bool:
        name = self.graph.resolve_name(fn, node.func)
        if name:
            if name in _HOST_PULLS:
                return False  # np.asarray lands on host (the sync itself
                # is flagged at the call site, its result is host data)
            if name.split(".")[0] == "jax":
                return True
        target = self.graph.resolve_callable(fn, node.func, local_types)
        if target is not None:
            return self.returns_device.get(target.qualname, False)
        # unresolvable callable (jitted program handle, dict dispatch):
        # device operands in -> assume device results out
        return any(
            self._expr_tainted(fn, a, tainted, local_types)
            for a in list(node.args) + [kw.value for kw in node.keywords]
        )

    def _expr_tainted(self, fn: FunctionInfo, node: ast.AST,
                      tainted: Set[str], local_types) -> bool:
        """Does the expression carry a device value? Host pulls
        (``np.asarray(x)``) land on host: the walk does not descend into
        them — their RESULT is host data (the pull itself is flagged at
        its own call site, once)."""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Name) and n.id in tainted:
                return True
            if isinstance(n, ast.Call):
                name = self.graph.resolve_name(fn, n.func)
                if name in _HOST_PULLS:
                    continue  # result is a host array; don't descend
                if self._call_is_device(fn, n, tainted, local_types):
                    return True
            stack.extend(ast.iter_child_nodes(n))
        return False

    @staticmethod
    def _name_targets(tgt: ast.AST) -> List[str]:
        """Plain names BOUND by an assignment target. Attribute and
        subscript stores (``self.x[i] = v``) mutate objects — they do
        not make the base name a device value."""
        if isinstance(tgt, ast.Name):
            return [tgt.id]
        if isinstance(tgt, (ast.Tuple, ast.List)):
            out: List[str] = []
            for el in tgt.elts:
                out.extend(_TaintScan._name_targets(el))
            return out
        if isinstance(tgt, ast.Starred):
            return _TaintScan._name_targets(tgt.value)
        return []

    def function_taint(self, fn: FunctionInfo) -> Set[str]:
        """Names in ``fn`` carrying device values (fixed point over the
        function's assignments)."""
        local_types = self.graph._local_types(fn)
        tainted: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in own_nodes(fn.node):
                targets: List[ast.AST] = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = list(node.targets), node.value
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) \
                        and node.value is not None:
                    targets, value = [node.target], node.value
                if value is None:
                    continue
                if self._expr_tainted(fn, value, tainted, local_types):
                    for tgt in targets:
                        for name in self._name_targets(tgt):
                            if name not in tainted:
                                tainted.add(name)
                                changed = True
        return tainted

    def compute_return_taint(self, funcs: Iterable[FunctionInfo]) -> None:
        """Fixed point of "returns a device value" over ``funcs``."""
        funcs = list(funcs)
        for _ in range(3):  # call chains deeper than 3 are rare; bounded
            changed = False
            for fn in funcs:
                tainted = self.function_taint(fn)
                local_types = self.graph._local_types(fn)
                ret = False
                for node in own_nodes(fn.node):
                    if isinstance(node, ast.Return) and node.value is not None:
                        if self._expr_tainted(fn, node.value, tainted,
                                              local_types):
                            ret = True
                            break
                if ret != self.returns_device.get(fn.qualname, False):
                    self.returns_device[fn.qualname] = ret
                    changed = True
            if not changed:
                break


def check_hot_path_syncs(
    graph: CallGraph,
    roots: Iterable[str] = HOT_PATH_ROOTS,
    stops: Iterable[str] = HOT_PATH_STOPS,
) -> List:
    """STA010: device syncs reachable from the step/tick/dispatch roots."""
    em = _Emitter()
    root_fns: List[FunctionInfo] = []
    for spec in roots:
        root_fns.extend(graph.find(spec))
    if not root_fns:
        return []
    reach = [f for f in graph.reachable(root_fns, stops=stops)
             if not f.is_traced]
    taint = _TaintScan(graph)
    taint.compute_return_taint(reach)
    for fn in reach:
        tainted = taint.function_taint(fn)
        local_types = graph._local_types(fn)
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = graph.resolve_name(fn, node.func)
            if name in SYNC_PRIMITIVES:
                em.emit(
                    "STA010", fn.module, node,
                    f"{name} on the hot path (reachable from "
                    f"{_root_label(graph, root_fns, fn)}): drains device "
                    "work per step/tick — keep telemetry and bookkeeping "
                    "host-side (see tests/core/test_obs/test_step_path.py)",
                )
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                em.emit(
                    "STA010", fn.module, node,
                    ".item() on the hot path is a device->host sync "
                    "(reachable from "
                    f"{_root_label(graph, root_fns, fn)})",
                )
                continue
            if name in _HOST_CONVERSIONS and node.args and \
                    taint._expr_tainted(fn, node.args[0], tainted,
                                        local_types):
                em.emit(
                    "STA010", fn.module, node,
                    f"{name}() on a device value blocks on a device->host "
                    "transfer on the hot path (reachable from "
                    f"{_root_label(graph, root_fns, fn)})",
                )
                continue
            if name in _HOST_PULLS and node.args and \
                    taint._expr_tainted(fn, node.args[0], tainted,
                                        local_types):
                em.emit(
                    "STA010", fn.module, node,
                    f"{name.replace('numpy', 'np')}() on a device value "
                    "pulls it to host on the hot path (reachable from "
                    f"{_root_label(graph, root_fns, fn)})",
                )
    return em.findings


def _root_label(graph: CallGraph, roots: List[FunctionInfo],
                fn: FunctionInfo) -> str:
    for r in roots:
        if fn.qualname == r.qualname:
            return r.dotted
        if fn.qualname in graph.descendants([r.qualname]):
            return r.dotted
    return roots[0].dotted


# ================================================================ STA011
def _in_scope(rel: str, scope_dirs: Iterable[str]) -> bool:
    norm = rel.replace("\\", "/")
    return any(f"/{d}/" in f"/{norm}" for d in scope_dirs)


def _guard_seeds(graph: CallGraph) -> Tuple[Set[str], Dict[str, Set[int]]]:
    """Functions that establish an I/O guard context, plus per-function
    line ranges guarded lexically (lambda bodies passed to retry_io).

    A seed is a function that (a) fires a FaultPlan point
    (``<plan>.fire("point")``) or (b) is passed into ``retry_io`` as
    the retried callable (by name — module functions, methods, nested
    closures). Everything a seed transitively calls runs under the
    guard."""
    seeds: Set[str] = set()
    regions: Dict[str, Set[int]] = {}
    for fn in graph.functions.values():
        local_types = graph._local_types(fn)
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            # (a) fault-point fire: <anything>.fire("point"[, ...]) —
            # process-lifecycle points excluded (firing host.kill at the
            # loop top is not I/O coverage for the save tree below it)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "fire"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value not in PROCESS_FAULT_POINTS
            ):
                seeds.add(fn.qualname)
                continue
            # (b) retry_io(callable, ...)
            name = graph.resolve_name(fn, node.func)
            if not (name and name.rsplit(".", 1)[-1] == "retry_io"):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Lambda):
                # the lambda body is guarded lexically; functions it
                # calls are guarded transitively
                regions.setdefault(fn.qualname, set()).update(
                    range(arg.lineno, getattr(arg, "end_lineno",
                                              arg.lineno) + 1)
                )
                for sub in ast.walk(arg.body):
                    if isinstance(sub, ast.Call):
                        t = graph.resolve_callable(fn, sub.func, local_types)
                        if t is not None:
                            seeds.add(t.qualname)
            else:
                t = graph.resolve_callable(fn, arg, local_types)
                if t is not None:
                    seeds.add(t.qualname)
    return seeds, regions


def check_unguarded_io(
    graph: CallGraph, scope_dirs: Iterable[str] = IO_SCOPE_DIRS
) -> List:
    """STA011: raw I/O in the gated subsystems outside every
    retry/fault guard context."""
    em = _Emitter()
    seeds, regions = _guard_seeds(graph)
    guarded = graph.descendants(seeds)
    for qual in sorted(graph.functions):
        fn = graph.functions[qual]
        if not _in_scope(fn.module.rel, scope_dirs):
            continue
        if qual in guarded:
            continue
        guarded_lines = regions.get(qual, set())
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            if getattr(node, "lineno", 0) in guarded_lines:
                continue
            name = graph.resolve_name(fn, node.func)
            is_raw = name in _RAW_IO_NAMES
            if not is_raw and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _RAW_IO_ATTRS:
                is_raw = True
                name = node.func.attr
            if not is_raw:
                continue
            em.emit(
                "STA011", fn.module, node,
                f"raw {name}() in {fn.dotted} is not reachable under "
                "retry_io or a FaultPlan point — the resilience gate's "
                "contract is that new I/O paths in "
                f"{'/'.join(scope_dirs)} take a fault point + bounded "
                "retry (docs/RESILIENCE.md); wire it through, or "
                "suppress with a comment explaining why this path must "
                "stay raw",
            )
    return em.findings


# ---------------------------------------------------------------- driver
class _Loc:
    """Pseudo-node carrying a location for comment-anchored findings."""

    def __init__(self, lineno: int, col_offset: int = 0) -> None:
        self.lineno = lineno
        self.col_offset = col_offset


def check_stale_lock_annotations(
    graph: CallGraph,
    lock_usage: Set[Tuple[str, str]],
    em: Optional[_Emitter] = None,
) -> List:
    """STA015 (lock half): a ``# sta: lock(attr, ...)`` annotation is
    stale when NONE of its attrs suppressed a hazard this run — either
    the class no longer spawns threads onto its own code, or the
    racing access pattern is gone. Stale annotations are worse than
    noise: they pre-suppress the next real race on that field."""
    em = em or _Emitter()
    for class_dotted in sorted(graph.classes):
        cinfo = graph.classes[class_dotted]
        for lineno, text in _annotation_comments(cinfo.module, cinfo.node):
            m = _LOCKFREE_RE.search(text)
            if not m:
                continue
            attrs = [a.strip() for a in m.group(1).split(",") if a.strip()]
            if any((class_dotted, a) in lock_usage for a in attrs):
                continue
            em.emit(
                "STA015", cinfo.module, _Loc(lineno),
                f"stale '# sta: lock({m.group(1).strip()})' on "
                f"{cinfo.name}: no cross-thread hazard on "
                f"{'these fields' if len(attrs) > 1 else 'this field'} "
                "is being suppressed — the class no longer races here. "
                "Remove the annotation (keep the prose if it documents "
                "intent) so it cannot pre-suppress the next real race",
            )
    return em.findings


def check_program(paths: Iterable[Path | str],
                  root: Optional[Path | str] = None,
                  graph: Optional[CallGraph] = None) -> List:
    """Every whole-program rule (STA009-STA015) over ONE shared call
    graph — pass ``graph`` to reuse a prebuilt one (the CLI builds a
    single graph per run and shares it across commands)."""
    if graph is None:
        graph = CallGraph.build(paths, root=root)
    findings: List = []
    lock_usage: Set[Tuple[str, str]] = set()
    findings.extend(check_lock_discipline(graph, lock_usage=lock_usage))
    findings.extend(check_hot_path_syncs(graph))
    findings.extend(check_unguarded_io(graph))
    from .protocol import check_protocol  # lazy: protocol imports us

    findings.extend(check_protocol(graph))
    findings.extend(check_stale_lock_annotations(graph, lock_usage))
    return findings
