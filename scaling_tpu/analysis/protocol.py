"""Distributed-protocol static analysis (STA012-STA014).

The multi-host rung moves the process fleet's RPC contract and the
control-plane barriers across machine boundaries — exactly where this
repo's most expensive recurring bug class lives (PR 4's barrier
split-exit deadlocks burned ~six review rounds; one host entered a
``commit:step-N`` barrier on a path a peer exited early from). These
rules catch that class in the analyzer, where a finding costs seconds
instead of a wedged pod:

**STA012 — barrier-divergence.** For every named-barrier call site
(``cp.barrier("name"/f"name-{step}", timeout)``), enumerate the
owning function's exit paths (return / raise / ``sys.exit`` /
fall-through) and flag paths that skip the barrier AFTER performing a
shared side-effect another path rendezvouses on: one host takes the
barrier path, a peer takes the early exit, and the barrier never
fills. Sanctioned exits are modeled, not suppressed wholesale —

- a ``raise`` exit is loud (the supervisor's staleness/abort machinery
  owns crashed hosts);
- a path that registers arrival (``cp.arrive(name)``, directly or via
  a resolved helper like the trainer's ``_broadcast_preempt``) parks
  no peers;
- a branch whose condition checks the abort flag (``get_flag(ABORT_*)``
  or any abort-named flag/variable) is the sanctioned drain;
- ``# sta: barrier-exempt(<name>)`` anywhere in the function body
  exempts that barrier name (with a comment saying why).

A path only fires when the exit diverges from a rendezvous path AFTER
a shared side-effect (a fault-point fire, a retry/raw I/O, a
control-plane mutation) in their common prefix: a pure guard at the
top of the function (``if cp is None: return``) diverges before any
shared work and is clean. Barrier names are matched as *templates* —
``f"commit:step-{step}"`` becomes ``commit:step-{}``.

**STA013 — RPC-contract.** Per module, extract the client op set
(dict literals with an ``"op"`` key passed into a request call — the
``ReplicaProcClient``/``TcpControlPlane`` send idiom) and the server
dispatch table (functions branching an op variable over string
constants — ``_ReplicaWorker.handle``/``TcpControlPlaneServer._handle``),
then flag: a client op with no handler, a dead handler no client ever
sends, and a reply key a client reads that no handler path for that op
returns (``ok``/``error`` are the transport envelope, always allowed).

**STA014 — protocol-edge coverage.** The STA011 contract extended to
the protocol layer: every RPC send site, named-barrier wait, and
replica spawn/kill site in the gated subsystems must sit under a
``FaultPlan`` point or ``retry_io`` guard AND inside (or beneath) an
``obs.span``. "Under" is transitive both ways: the site's enclosing
function may run beneath a guard/span, or the call's resolved target
may establish one (``ProcReplicaHandle._rpc`` -> ``retry_io``;
``ControlPlane.barrier`` opens ``barrier.wait``). Unlike STA011,
process-lifecycle fault points count here: a kill drill IS the fault
coverage for a kill site.

All three ride the standard plumbing: per-line ``# sta: disable=``
suppression, findings in the same JSON schema, clean tree pinned at
zero unsuppressed findings by the CLI gate. Resolution uses the call
graph's *virtual* dispatch (``override_edges``): a call on the
abstract ``ControlPlane`` surface reaches both backends.

The module also builds the goldens-pinned ``protocol`` inventory
(barrier name templates + participating functions, per-module RPC op
tables) — ``python -m scaling_tpu.analysis protocol`` compares it to
``analysis/goldens/protocol.json`` so contract drift fails CI
structurally; ``--repin`` rewrites the golden (commit deliberately).

No jax import; pure stdlib ``ast`` over :mod:`callgraph`.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import CallGraph, FunctionInfo, own_nodes
from .concurrency import (
    IO_SCOPE_DIRS,
    _RAW_IO_ATTRS,
    _RAW_IO_NAMES,
    _Emitter,
    _guard_seeds,
    _in_scope,
)

# STA014's scope: the I/O-gated subsystems plus the trainer (whose
# control-plane check-in owns the step/commit barriers).
PROTOCOL_SCOPE_DIRS = IO_SCOPE_DIRS + ("trainer",)

# the fault injector itself executes kills/exits — requiring the
# injector to run under a fault point is circular
_EXCLUDED_MODULE_TAILS = ("resilience.faults",)

# reply-envelope keys every handler returns implicitly
_ENVELOPE_KEYS = {"ok", "error"}

# an op-dict handed to a collection mutator is data construction (cost
# tables, record lists), not a request crossing a process boundary
_COLLECTION_MUTATORS = {
    "append", "extend", "add", "insert", "update", "setdefault",
    "put", "put_nowait", "appendleft",
}

# control-plane mutations that count as shared side-effects (STA012)
_CP_EFFECT_ATTRS = {"set_flag", "heartbeat", "prune_barrier"}

# bounded path enumeration: beyond this the function is skipped for
# STA012 (under-approximate, never explode)
MAX_PATHS = 256

_BARRIER_EXEMPT_RE = re.compile(r"#\s*sta:\s*barrier-exempt\(([^)]*)\)")


def _name_template(node: ast.AST) -> Optional[str]:
    """A constant or f-string barrier name as a template:
    ``f"commit:step-{step}"`` -> ``commit:step-{}``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: List[str] = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("{}")
        return "".join(parts)
    return None


# ------------------------------------------------------------ model
@dataclasses.dataclass
class BarrierSite:
    fn: FunctionInfo
    node: ast.Call
    name: str  # template
    kind: str  # 'wait' | 'arrive'


@dataclasses.dataclass
class RpcSend:
    fn: FunctionInfo
    node: ast.Call
    op: Optional[str]  # None = dynamic op value
    reads: List[Tuple[str, ast.AST]] = dataclasses.field(default_factory=list)
    # the request dict carries a literal "trace" key (STA016: the
    # serving fleet's trace-propagation contract); dict_node is the
    # envelope literal itself, so the finding anchors on the dict's
    # line (where the missing key belongs), not the call's
    has_trace: bool = False
    dict_node: Optional[ast.AST] = None


@dataclasses.dataclass
class RpcHandler:
    fn: FunctionInfo
    node: ast.AST  # the `if op == "x":` statement
    op: str
    reply_keys: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class ProcSite:
    fn: FunctionInfo
    node: ast.Call
    kind: str  # 'spawn' | 'kill'


class ProtocolModel:
    """The package's protocol surface plus the reachability closures
    the three rules (and the golden inventory) share."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.barrier_sites: List[BarrierSite] = []
        self.rpc_sends: Dict[str, List[RpcSend]] = {}  # modname -> sends
        self.rpc_handlers: Dict[str, Dict[str, List[RpcHandler]]] = {}
        self.proc_sites: List[ProcSite] = []
        self._collect()
        self._closures()

    # ----------------------------------------------------- collection
    def _collect(self) -> None:
        for qual in sorted(self.graph.functions):
            fn = self.graph.functions[qual]
            if any(fn.module.modname.endswith(t)
                   for t in _EXCLUDED_MODULE_TAILS):
                continue
            sends = self._collect_sends(fn)
            if sends:
                self.rpc_sends.setdefault(fn.module.modname, []).extend(sends)
            for handler in self._collect_handlers(fn):
                self.rpc_handlers.setdefault(
                    fn.module.modname, {}
                ).setdefault(handler.op, []).append(handler)
            for node in own_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute):
                    if node.func.attr in ("barrier", "arrive") and node.args:
                        t = _name_template(node.args[0])
                        if t is not None:
                            self.barrier_sites.append(BarrierSite(
                                fn, node, t,
                                "wait" if node.func.attr == "barrier"
                                else "arrive",
                            ))
                            continue
                    if node.func.attr in ("kill", "terminate") \
                            and not node.args:
                        self.proc_sites.append(ProcSite(fn, node, "kill"))
                        continue
                name = self.graph.resolve_name(fn, node.func)
                if name == "subprocess.Popen":
                    self.proc_sites.append(ProcSite(fn, node, "spawn"))
                elif name == "os.kill":
                    self.proc_sites.append(ProcSite(fn, node, "kill"))

    @staticmethod
    def _op_of_dict(d: ast.AST) -> Tuple[bool, Optional[str]]:
        """(is_rpc_request_dict, constant op value or None)."""
        if not isinstance(d, ast.Dict):
            return False, None
        for k, v in zip(d.keys, d.values):
            if isinstance(k, ast.Constant) and k.value == "op":
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    return True, v.value
                return True, None
        return False, None

    @staticmethod
    def _has_trace_key(d: ast.AST) -> bool:
        """The request dict carries a literal ``"trace"`` key (a
        ``None`` key means ``**spread`` — opaque, give the benefit of
        the doubt: a spread may well inject the trace)."""
        if not isinstance(d, ast.Dict):
            return False
        return any(
            k is None or (isinstance(k, ast.Constant)
                          and k.value == "trace")
            for k in d.keys
        )

    def _collect_sends(self, fn: FunctionInfo) -> List[RpcSend]:
        """Dict literals carrying an ``"op"`` key passed into a call —
        the line-JSON RPC send idiom — plus the reply keys each send's
        result is read for (direct subscripts/.get on the call, or on
        the name the call is assigned to, function-scoped)."""
        sends: List[RpcSend] = []
        send_nodes: Dict[int, RpcSend] = {}
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _COLLECTION_MUTATORS):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                is_rpc, op = self._op_of_dict(arg)
                if is_rpc:
                    send = RpcSend(fn, node, op,
                                   has_trace=self._has_trace_key(arg),
                                   dict_node=arg)
                    sends.append(send)
                    send_nodes[id(node)] = send
                    break
        if not sends:
            return sends
        # reply variables: reply = <send call>(...)
        reply_vars: Dict[str, RpcSend] = {}
        for node in own_nodes(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and id(node.value) in send_nodes
            ):
                reply_vars[node.targets[0].id] = send_nodes[id(node.value)]
        for node in own_nodes(fn.node):
            # reply["key"] / <send call>["key"]
            if isinstance(node, ast.Subscript) and isinstance(
                node.slice, ast.Constant
            ) and isinstance(node.slice.value, str):
                send = None
                if id(node.value) in send_nodes:
                    send = send_nodes[id(node.value)]
                elif isinstance(node.value, ast.Name) \
                        and node.value.id in reply_vars:
                    send = reply_vars[node.value.id]
                if send is not None:
                    send.reads.append((node.slice.value, node))
            # reply.get("key") / <send call>.get("key")
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                base = node.func.value
                send = None
                if id(base) in send_nodes:
                    send = send_nodes[id(base)]
                elif isinstance(base, ast.Name) and base.id in reply_vars:
                    send = reply_vars[base.id]
                if send is not None:
                    send.reads.append((node.args[0].value, node))
        return sends

    @staticmethod
    def _op_var_of(fn: FunctionInfo) -> Optional[str]:
        """The local bound from ``<req>.get("op")`` / ``<req>["op"]`` —
        the dispatch variable of a server handler."""
        for node in own_nodes(fn.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            v = node.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "get"
                and v.args
                and isinstance(v.args[0], ast.Constant)
                and v.args[0].value == "op"
            ):
                return node.targets[0].id
            if (
                isinstance(v, ast.Subscript)
                and isinstance(v.slice, ast.Constant)
                and v.slice.value == "op"
            ):
                return node.targets[0].id
        return None

    def _collect_handlers(self, fn: FunctionInfo) -> List[RpcHandler]:
        op_var = self._op_var_of(fn)
        if op_var is None:
            return []
        handlers: List[RpcHandler] = []
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.If):
                continue
            test = node.test
            if not (
                isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == op_var
                and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Eq)
                and isinstance(test.comparators[0], ast.Constant)
                and isinstance(test.comparators[0].value, str)
            ):
                continue
            handler = RpcHandler(fn, node, test.comparators[0].value)
            for sub in node.body:
                for n in ast.walk(sub):
                    if isinstance(n, ast.Return) and isinstance(
                        n.value, ast.Dict
                    ):
                        for k in n.value.keys:
                            if isinstance(k, ast.Constant) and isinstance(
                                k.value, str
                            ):
                                handler.reply_keys.add(k.value)
            handlers.append(handler)
        return handlers

    # ------------------------------------------------------- closures
    def _reverse_edges(self) -> Dict[str, Set[str]]:
        rev: Dict[str, Set[str]] = {}
        for caller, callees in self.graph.edges.items():
            for c in callees:
                rev.setdefault(c, set()).add(caller)
        # virtual dispatch: whoever calls the abstract method reaches
        # the override — for upward propagation the override's effects
        # belong to the abstract surface too
        for abstract, overrides in self.graph.override_edges.items():
            for o in overrides:
                rev.setdefault(o, set()).add(abstract)
        return rev

    @staticmethod
    def _propagate_up(rev: Dict[str, Set[str]],
                      direct: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
        """Transitive closure toward CALLERS: every function inherits
        the union of its callees' sets."""
        out: Dict[str, Set[str]] = {k: set(v) for k, v in direct.items()}
        work = list(direct)
        while work:
            q = work.pop()
            vals = out.get(q, set())
            for caller in rev.get(q, ()):
                cur = out.setdefault(caller, set())
                add = vals - cur
                if add:
                    cur |= add
                    work.append(caller)
        return out

    def _closures(self) -> None:
        graph = self.graph
        rev = self._reverse_edges()

        # barrier templates each function (transitively) waits/arrives at
        direct_waits: Dict[str, Set[str]] = {}
        direct_arrives: Dict[str, Set[str]] = {}
        for site in self.barrier_sites:
            d = direct_waits if site.kind == "wait" else direct_arrives
            d.setdefault(site.fn.qualname, set()).add(site.name)
        self.trans_waits = self._propagate_up(rev, direct_waits)
        self.trans_arrives = self._propagate_up(rev, direct_arrives)

        # shared-side-effect closure (STA012) + guard-establisher
        # closure (STA014): both propagate from functions whose OWN
        # body performs the thing toward their callers
        effect_direct: Dict[str, Set[str]] = {}
        guard_direct: Dict[str, Set[str]] = {}
        span_direct: Dict[str, Set[str]] = {}
        self.span_regions: Dict[str, Set[int]] = {}
        span_seeds: Set[str] = set()
        for qual in graph.functions:
            fn = graph.functions[qual]
            local_types = graph._local_types(fn)
            regions = self._span_regions_of(fn)
            if regions:
                self.span_regions[qual] = regions
                span_direct[qual] = {"span"}
                for node in own_nodes(fn.node):
                    if isinstance(node, ast.Call) and \
                            getattr(node, "lineno", 0) in regions:
                        t = graph.resolve_callable(fn, node.func, local_types)
                        if t is not None:
                            span_seeds.add(t.qualname)
            for node in own_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                if self._direct_effect(fn, node, local_types):
                    effect_direct[qual] = {"effect"}
                if self._establishes_guard(fn, node):
                    guard_direct[qual] = {"guard"}
        self.effectful = set(self._propagate_up(rev, effect_direct))
        self.guard_establishers = set(guard_direct)
        self.guard_closure = set(self._propagate_up(rev, guard_direct))
        self.span_enterers = set(self._propagate_up(rev, span_direct))
        self.span_covered = graph.descendants(span_seeds, virtual=True)

        # STA011-style guard context (fault-firing callers, retry_io
        # callables) — virtual so abstract-surface calls flow through
        seeds, self.retry_regions = _guard_seeds(graph)
        self.guarded_ctx = graph.descendants(seeds, virtual=True)

    def _direct_effect(self, fn: FunctionInfo, node: ast.Call,
                       local_types) -> bool:
        """Does this call perform a shared side-effect in its own right
        (fault fire, retry/raw I/O, control-plane mutation, RPC-ish)?"""
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "fire" and node.args and isinstance(
                node.args[0], ast.Constant
            ):
                return True
            if f.attr in _CP_EFFECT_ATTRS:
                return True
            if f.attr in _RAW_IO_ATTRS:
                return True
        name = self.graph.resolve_name(fn, f)
        if name in _RAW_IO_NAMES or name == "subprocess.Popen":
            return True
        if name and name.rsplit(".", 1)[-1] == "retry_io":
            return True
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            is_rpc, _ = self._op_of_dict(arg)
            if is_rpc:
                return True
        return False

    def _establishes_guard(self, fn: FunctionInfo, node: ast.Call) -> bool:
        """retry_io or ANY FaultPlan fire (process points included —
        a kill drill covers a kill site for STA014)."""
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "fire" and node.args \
                and isinstance(node.args[0], ast.Constant):
            return True
        name = self.graph.resolve_name(fn, f)
        return bool(name and name.rsplit(".", 1)[-1] == "retry_io")

    def _span_regions_of(self, fn: FunctionInfo) -> Set[int]:
        """Line numbers lexically inside ``with span(...)`` /
        ``with obs.span(...)`` / ``with self._span(...)`` bodies."""
        regions: Set[int] = set()
        for node in own_nodes(fn.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                ctx = item.context_expr
                if not isinstance(ctx, ast.Call):
                    continue
                f = ctx.func
                is_span = (
                    isinstance(f, ast.Attribute)
                    and f.attr in ("span", "_span")
                ) or (isinstance(f, ast.Name) and f.id == "span")
                if not is_span:
                    name = self.graph.resolve_name(fn, f)
                    is_span = bool(
                        name and name.rsplit(".", 1)[-1] == "span"
                    )
                if is_span:
                    for stmt in node.body:
                        regions.update(range(
                            stmt.lineno,
                            getattr(stmt, "end_lineno", stmt.lineno) + 1,
                        ))
                    break
        return regions

    # ------------------------------------------------- coverage helpers
    def site_guarded(self, fn: FunctionInfo, node: ast.Call) -> bool:
        if fn.qualname in self.guarded_ctx:
            return True
        if getattr(node, "lineno", 0) in self.retry_regions.get(
            fn.qualname, ()
        ):
            return True
        if fn.qualname in self.guard_establishers:
            return True
        target = self.graph.resolve_callable(fn, node.func)
        return target is not None and target.qualname in self.guard_closure

    def site_spanned(self, fn: FunctionInfo, node: ast.Call) -> bool:
        if getattr(node, "lineno", 0) in self.span_regions.get(
            fn.qualname, ()
        ):
            return True
        if fn.qualname in self.span_covered:
            return True
        target = self.graph.resolve_callable(fn, node.func)
        return target is not None and target.qualname in self.span_enterers


# ======================================================== STA012
@dataclasses.dataclass
class _Path:
    steps: List[Tuple[int, Tuple, ast.AST]]  # (stmt id, events, node)
    exit_kind: Optional[str] = None  # return / raise / exit / fall
    exit_node: Optional[ast.AST] = None
    flag_sanctioned: bool = False
    # branch outcomes: id(If stmt) -> (took body?, stmt node). Used to
    # reject cross-host-infeasible path pairs: two hosts cannot take
    # different sides of a UNIFORM test (cp.num_hosts), whatever else
    # differs between their paths.
    choices: Dict[int, Tuple[bool, ast.AST]] = dataclasses.field(
        default_factory=dict
    )

    def extended(self, frag: "_Path") -> "_Path":
        return _Path(
            self.steps + frag.steps,
            frag.exit_kind,
            frag.exit_node,
            self.flag_sanctioned or frag.flag_sanctioned,
            {**self.choices, **frag.choices},
        )


def _mentions_abort(test: ast.AST) -> bool:
    """The sanctioned drain check: the branch condition consults the
    abort flag (``get_flag(ABORT_FLAG)``) or an abort-named value."""
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and "abort" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "abort" in n.attr.lower():
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and n.value.lower() == "abort":
            return True
    return False


class _PathEnumerator:
    """Bounded statement-level path enumeration of one function body,
    carrying barrier/effect events per statement."""

    def __init__(self, model: ProtocolModel, fn: FunctionInfo):
        self.model = model
        self.graph = model.graph
        self.fn = fn
        self.local_types = self.graph._local_types(fn)
        self.truncated = False

    # -------------------------------------------------------- events
    def _expr_events(self, expr: ast.AST) -> Tuple:
        events: List[Tuple] = []
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                events.extend(self._call_events(n))
        return tuple(events)

    def _call_events(self, call: ast.Call) -> List[Tuple]:
        f = call.func
        if isinstance(f, ast.Attribute) and call.args:
            if f.attr == "barrier":
                t = _name_template(call.args[0])
                if t is not None:
                    return [("wait", t)]
            if f.attr == "arrive":
                t = _name_template(call.args[0])
                if t is not None:
                    return [("arrive", t)]
        name = self.graph.resolve_name(self.fn, f)
        if name in ("sys.exit", "os._exit"):
            return [("exit",)]
        if self.model._direct_effect(self.fn, call, self.local_types):
            return [("effect",)]
        target = self.graph.resolve_callable(self.fn, f, self.local_types)
        if target is not None:
            events: List[Tuple] = []
            for w in sorted(self.model.trans_waits.get(target.qualname, ())):
                events.append(("wait", w))
            for a in sorted(
                self.model.trans_arrives.get(target.qualname, ())
            ):
                events.append(("arrive", a))
            if target.qualname in self.model.effectful:
                events.append(("effect",))
            return events
        return []

    # --------------------------------------------------------- paths
    def paths(self, stmts: List[ast.stmt]) -> List[_Path]:
        out = self._seq(stmts)
        for p in out:
            if p.exit_kind is None:
                p.exit_kind = "fall"
                p.exit_node = p.steps[-1][2] if p.steps else self.fn.node
        return out

    def _seq(self, stmts: List[ast.stmt]) -> List[_Path]:
        paths = [_Path(steps=[])]
        for stmt in stmts:
            live = [p for p in paths if p.exit_kind is None]
            done = [p for p in paths if p.exit_kind is not None]
            if not live:
                break
            frags = self._stmt(stmt)
            combined: List[_Path] = []
            for p in live:
                for frag in frags:
                    combined.append(p.extended(frag))
                    if len(combined) + len(done) > MAX_PATHS:
                        self.truncated = True
                        break
                if self.truncated:
                    break
            paths = done + combined
        return paths

    def _step(self, stmt: ast.stmt, events: Tuple) -> Tuple[int, Tuple,
                                                            ast.AST]:
        return (id(stmt), events, stmt)

    def _stmt(self, stmt: ast.stmt) -> List[_Path]:
        if isinstance(stmt, ast.Return):
            ev = self._expr_events(stmt.value) if stmt.value else ()
            return [_Path([self._step(stmt, ev)], "return", stmt)]
        if isinstance(stmt, ast.Raise):
            return [_Path([self._step(stmt, ())], "raise", stmt)]
        if isinstance(stmt, ast.If):
            head = self._expr_events(stmt.test)
            abort = _mentions_abort(stmt.test)
            out: List[_Path] = []
            for taken, body in ((True, stmt.body), (False, stmt.orelse)):
                branch = self._seq(body) if body else [_Path(steps=[])]
                for b in branch:
                    hp = _Path([self._step(stmt, head)], None, None,
                               abort and taken)
                    hp.choices[id(stmt)] = (taken, stmt)
                    out.append(hp.extended(b))
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head_expr = stmt.test if isinstance(stmt, ast.While) \
                else stmt.iter
            head = self._expr_events(head_expr)
            out = [_Path([self._step(stmt, head)])]  # loop not taken
            for b in self._seq(list(stmt.body)):
                out.append(_Path([self._step(stmt, head)]).extended(b))
            for b in self._seq(list(stmt.orelse)) if stmt.orelse else []:
                out.append(_Path([self._step(stmt, head)]).extended(b))
            return out
        if isinstance(stmt, ast.Try):
            out = list(self._seq(list(stmt.body)))
            for handler in stmt.handlers:
                out.extend(self._seq(list(handler.body)))
            if stmt.orelse:
                body_paths = out
                out = []
                for p in body_paths:
                    if p.exit_kind is None:
                        for o in self._seq(list(stmt.orelse)):
                            out.append(p.extended(o))
                    else:
                        out.append(p)
            if stmt.finalbody:
                final = self._seq(list(stmt.finalbody))
                merged: List[_Path] = []
                for p in out:
                    if p.exit_kind is None:
                        for fp in final:
                            merged.append(p.extended(fp))
                    else:
                        merged.append(p)
                out = merged
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head_events: List[Tuple] = []
            for item in stmt.items:
                head_events.extend(self._expr_events(item.context_expr))
            out = []
            for b in self._seq(list(stmt.body)):
                out.append(
                    _Path([self._step(stmt, tuple(head_events))]).extended(b)
                )
            return out
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return [_Path([self._step(stmt, ())])]
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return [_Path([self._step(stmt, ())])]
        # simple statement: events from every expression inside it;
        # a bare `sys.exit()` expression statement is an exit path
        ev = self._expr_events(stmt)
        if ("exit",) in ev:
            return [_Path([self._step(stmt, ev)], "exit", stmt)]
        return [_Path([self._step(stmt, ev)])]


def _barrier_exemptions(fn: FunctionInfo) -> Set[str]:
    from .concurrency import _annotation_comments

    out: Set[str] = set()
    for _, text in _annotation_comments(fn.module, fn.node):
        m = _BARRIER_EXEMPT_RE.search(text)
        if m:
            out.update(
                t.strip() for t in m.group(1).split(",") if t.strip()
            )
    return out


def _rendezvouses(p: _Path, name: str,
                  kinds: Tuple[str, ...] = ("wait", "arrive")) -> bool:
    for _, events, _ in p.steps:
        for ev in events:
            if ev[0] in kinds and ev[1] == name:
                return True
    return False


def _divergence(p: _Path, r: _Path) -> Tuple[bool, Optional[ast.AST]]:
    """(shared side-effect in the common prefix, statement where the
    pair diverged). Any event in the common prefix counts as an
    effect — a heartbeat, an I/O, an arrival at ANOTHER barrier are
    all state a peer observes. The divergence statement is the last
    common step (the branching If/loop header)."""
    k = 0
    effect = False
    n = min(len(p.steps), len(r.steps))
    while k < n and p.steps[k][0] == r.steps[k][0]:
        if p.steps[k][1]:
            effect = True
        k += 1
    div = p.steps[k - 1][2] if k > 0 else None
    return effect, div


def _uniform_divergence(div: Optional[ast.AST]) -> bool:
    """A branch on cluster topology (``cp.num_hosts > 1``) is uniform:
    every participant takes the SAME side, so the skipping branch
    cannot strand a peer — there are no peers when it is taken."""
    if not isinstance(div, (ast.If, ast.While)):
        return False
    for n in ast.walk(div.test):
        if isinstance(n, ast.Attribute) and "num_hosts" in n.attr:
            return True
        if isinstance(n, ast.Name) and "num_hosts" in n.id:
            return True
    return False


def _feasible_pair(p: _Path, r: _Path) -> bool:
    """Can two HOSTS take these two paths concurrently? Not if the
    paths disagree on any uniform (topology) test — num_hosts is the
    same number everywhere, so every host branches the same way,
    wherever else their state diverges."""
    for sid, (choice, node) in p.choices.items():
        other = r.choices.get(sid)
        if other is not None and other[0] != choice \
                and _uniform_divergence(node):
            return False
    return True


def check_barrier_divergence(model: ProtocolModel,
                             em: Optional[_Emitter] = None) -> List:
    """STA012 over every function owning a named-barrier wait site."""
    em = em or _Emitter()
    by_fn: Dict[str, List[BarrierSite]] = {}
    for site in model.barrier_sites:
        if site.kind == "wait":
            by_fn.setdefault(site.fn.qualname, []).append(site)
    for qual in sorted(by_fn):
        fn = model.graph.functions[qual]
        enum = _PathEnumerator(model, fn)
        paths = enum.paths(list(fn.node.body))
        if enum.truncated:
            continue  # bounded: skip rather than flag half-enumerated
        exempt = _barrier_exemptions(fn)
        names = sorted({s.name for s in by_fn[qual]})
        for name in names:
            if name in exempt or "*" in exempt:
                continue
            # the conflict is SKIP vs WAIT: a peer is only stranded on
            # a path that actually parks at the barrier. Arrive-only
            # paths (the preempt broadcast) park nobody — they release
            # peers — so they are not in the comparison set, though
            # having one DOES sanction the skipping path itself below.
            rendezvous = [p for p in paths
                          if _rendezvouses(p, name, kinds=("wait",))]
            if not rendezvous:
                continue
            seen_exits: Set[int] = set()
            for p in paths:
                if _rendezvouses(p, name):
                    continue
                if p.exit_kind in ("raise", "exit"):
                    continue  # loud exits: the supervisor owns crashes
                if p.flag_sanctioned:
                    continue  # abort-flag drain
                hazardous = False
                for r in rendezvous:
                    if not _feasible_pair(p, r):
                        continue  # disagree on a uniform topology test
                    effect, div = _divergence(p, r)
                    if effect and not _uniform_divergence(div):
                        hazardous = True
                        break
                if not hazardous:
                    continue  # diverged before any shared work, or on
                    # a uniform topology test (same side on every host)
                node = p.exit_node or fn.node
                line = getattr(node, "lineno", 0)
                if line in seen_exits:
                    continue
                seen_exits.add(line)
                em.emit(
                    "STA012", fn.module, node,
                    f"exit path in {fn.dotted} skips barrier {name!r} "
                    "after shared side-effects another path rendezvouses "
                    "on — a peer parked inside the barrier waits out the "
                    "full timeout (the PR 4 split-exit deadlock). "
                    "Register arrival on this path (cp.arrive), raise "
                    "instead of returning, or annotate "
                    f"'# sta: barrier-exempt({name})' with a comment "
                    "saying why this exit is safe",
                )
    return em.findings


# ======================================================== STA013
def check_rpc_contract(model: ProtocolModel,
                       em: Optional[_Emitter] = None) -> List:
    """STA013: per-module client-op set vs server dispatch table."""
    em = em or _Emitter()
    for modname in sorted(set(model.rpc_sends) | set(model.rpc_handlers)):
        sends = model.rpc_sends.get(modname, [])
        handlers = model.rpc_handlers.get(modname, {})
        if not handlers:
            continue  # client-only module: no co-located table to check
        sent_ops = {s.op for s in sends if s.op is not None}
        for send in sends:
            if send.op is None:
                continue
            if send.op not in handlers:
                em.emit(
                    "STA013", send.fn.module, send.node,
                    f"client op {send.op!r} ({send.fn.dotted}) has no "
                    f"handler in {modname}'s dispatch table — the reply "
                    "will be the unknown-op error envelope",
                )
                continue
            reply_keys = set(_ENVELOPE_KEYS)
            for h in handlers[send.op]:
                reply_keys |= h.reply_keys
            for key, node in send.reads:
                if key not in reply_keys:
                    em.emit(
                        "STA013", send.fn.module, node,
                        f"client reads reply key {key!r} for op "
                        f"{send.op!r} ({send.fn.dotted}) but no handler "
                        "path returns it — that read is always "
                        "None/KeyError territory",
                    )
        for op in sorted(handlers):
            if op not in sent_ops:
                for h in handlers[op]:
                    em.emit(
                        "STA013", h.fn.module, h.node,
                        f"handler for op {op!r} ({h.fn.dotted}) is never "
                        f"sent by any client in {modname} — dead dispatch "
                        "arm (or the client moved modules without its "
                        "table)",
                    )
    return em.findings


# ======================================================== STA014
def check_edge_coverage(model: ProtocolModel,
                        em: Optional[_Emitter] = None,
                        scope_dirs: Iterable[str] = PROTOCOL_SCOPE_DIRS
                        ) -> List:
    """STA014: RPC sends, barrier waits, and replica spawn/kill sites
    must be guarded (fault point / retry_io) AND spanned."""
    em = em or _Emitter()
    sites: List[Tuple[FunctionInfo, ast.Call, str]] = []
    for sends in model.rpc_sends.values():
        for s in sends:
            sites.append((s.fn, s.node, f"rpc send {s.op!r}"))
    for b in model.barrier_sites:
        if b.kind == "wait":
            sites.append((b.fn, b.node, f"barrier wait {b.name!r}"))
    for p in model.proc_sites:
        sites.append((p.fn, p.node, f"replica {p.kind}"))
    sites.sort(key=lambda t: (t[0].module.rel,
                              getattr(t[1], "lineno", 0)))
    for fn, node, label in sites:
        if not _in_scope(fn.module.rel, scope_dirs):
            continue
        guarded = model.site_guarded(fn, node)
        spanned = model.site_spanned(fn, node)
        if guarded and spanned:
            continue
        missing = []
        if not guarded:
            missing.append("a FaultPlan point / retry_io guard")
        if not spanned:
            missing.append("an obs.span")
        em.emit(
            "STA014", fn.module, node,
            f"{label} in {fn.dotted} lacks {' and '.join(missing)} — "
            "the protocol layer extends the STA011 contract: every "
            "rpc/barrier/spawn/kill edge takes fault-or-retry coverage "
            "AND a span (docs/ANALYSIS.md, Protocol rules); wire it "
            "through or suppress with a comment saying why",
        )
    return em.findings


# ======================================================== STA016
# trace-propagation scope: the serving fleet only. Control-plane
# envelopes (resilience/) are deliberately exempt — their cross-host
# identity is DERIVED at both ends (``obs.derive_trace_id`` over the
# lease / commit key), never carried in the envelope, so demanding a
# "trace" key there would add dead payload the consumer ignores.
TRACE_SCOPE_DIRS = ("serve",)


def check_trace_propagation(model: ProtocolModel,
                            em: Optional[_Emitter] = None,
                            scope_dirs: Iterable[str] = TRACE_SCOPE_DIRS
                            ) -> List:
    """STA016: every serve/ RPC request dict literal must carry a
    literal ``"trace"`` key (value may be ``None`` — key presence IS
    the contract; ``obs/trace.py`` reassembles cross-host timelines
    from what the envelopes carry, and one bare envelope severs the
    request's trace at a process boundary)."""
    em = em or _Emitter()
    flat = sorted(
        (s for sends in model.rpc_sends.values() for s in sends),
        key=lambda s: (s.fn.module.rel, getattr(s.node, "lineno", 0)),
    )
    for s in flat:
        if not _in_scope(s.fn.module.rel, scope_dirs):
            continue
        if s.has_trace:
            continue
        em.emit(
            "STA016", s.fn.module, s.dict_node or s.node,
            f"rpc send {s.op!r} in {s.fn.dotted} carries no "
            "literal 'trace' key — serve/ envelopes must propagate "
            "the ambient trace context (obs.current_trace(), even "
            "when None) or a failover re-dispatch severs the "
            "request's distributed trace (docs/OBSERVABILITY.md, "
            "Tracing); add the key or suppress with a comment "
            "saying why",
        )
    return em.findings


# ------------------------------------------------------------- driver
def check_protocol(graph: CallGraph) -> List:
    """All four protocol rules over one shared graph + model."""
    model = ProtocolModel(graph)
    findings: List = []
    findings.extend(check_barrier_divergence(model))
    findings.extend(check_rpc_contract(model))
    findings.extend(check_edge_coverage(model))
    findings.extend(check_trace_propagation(model))
    return findings


# ---------------------------------------------------------- inventory
def _fn_label(fn: FunctionInfo) -> str:
    return f"{fn.module.modname}.{fn.dotted}"


def build_inventory(graph: CallGraph,
                    model: Optional[ProtocolModel] = None) -> dict:
    """The goldens-pinned protocol surface: barrier name templates with
    their participating functions, and per-module RPC op tables
    (clients, handler, reply keys). Structural — any drift (a renamed
    barrier, a dropped handler, a new op) diffs loudly."""
    model = model or ProtocolModel(graph)
    barriers: Dict[str, Dict[str, List[str]]] = {}
    for site in model.barrier_sites:
        rec = barriers.setdefault(site.name, {"waits": [], "arrives": []})
        key = "waits" if site.kind == "wait" else "arrives"
        label = _fn_label(site.fn)
        if label not in rec[key]:
            rec[key].append(label)
    for rec in barriers.values():
        rec["waits"].sort()
        rec["arrives"].sort()
    rpc: Dict[str, dict] = {}
    for modname in sorted(set(model.rpc_sends) | set(model.rpc_handlers)):
        sends = model.rpc_sends.get(modname, [])
        handlers = model.rpc_handlers.get(modname, {})
        ops: Dict[str, dict] = {}
        for op in sorted(
            {s.op for s in sends if s.op is not None} | set(handlers)
        ):
            op_sends = [s for s in sends if s.op == op]
            hs = handlers.get(op, [])
            reply_keys: Set[str] = set()
            for h in hs:
                reply_keys |= h.reply_keys
            ops[op] = {
                "clients": sorted({_fn_label(s.fn) for s in op_sends}),
                "handler": sorted({_fn_label(h.fn) for h in hs}),
                "reply_keys": sorted(reply_keys),
                "reads": sorted(
                    {k for s in op_sends for k, _ in s.reads}
                ),
            }
        rpc[modname] = {"ops": ops}
    return {
        "schema_version": 1,
        "barriers": {
            name: barriers[name] for name in sorted(barriers)
        },
        "rpc": rpc,
    }


def golden_path(golden_dir: Optional[Path] = None) -> Path:
    base = golden_dir or Path(__file__).parent / "goldens"
    return Path(base) / "protocol.json"


def write_inventory(inv: dict, golden_dir: Optional[Path] = None) -> Path:
    path = golden_path(golden_dir)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(inv, indent=1, sort_keys=True) + "\n")
    return path


def _diff(prefix: str, golden, current, out: List[str]) -> None:
    if isinstance(golden, dict) and isinstance(current, dict):
        for k in sorted(set(golden) | set(current)):
            if k not in golden:
                out.append(f"{prefix}{k}: added (not in golden)")
            elif k not in current:
                out.append(f"{prefix}{k}: removed (golden has it)")
            else:
                _diff(f"{prefix}{k}.", golden[k], current[k], out)
        return
    if golden != current:
        out.append(f"{prefix.rstrip('.')}: golden {golden!r} != "
                   f"current {current!r}")


def compare_inventory(inv: dict,
                      golden_dir: Optional[Path] = None) -> List[str]:
    """Drift lines against the pinned golden; a missing golden is one
    drift line (repin to create it deliberately)."""
    path = golden_path(golden_dir)
    if not path.exists():
        return [f"protocol golden missing: {path} (run "
                "`python -m scaling_tpu.analysis protocol --repin`)"]
    golden = json.loads(path.read_text())
    out: List[str] = []
    _diff("protocol.", golden, inv, out)
    return out
