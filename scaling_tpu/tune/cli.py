"""CLI: ``python -m scaling_tpu.tune`` — rank layouts, emit a config.

Exit codes: 0 clean, 1 golden drift (``--check-golden``), 2 usage error.

Calibration resolution (printed with the report — the tuner NEVER uses
the legacy step-time/3.2 fudge):

1. ``--run-dir DIR``: mean MFU of that obs run dir's step records.
2. A fresh bench capture: ``benchmarks/artifacts/LAST_GOOD.json``'s MFU
   — but ONLY while ``STALE.json`` is absent.
3. While the bench capture is stale, the newest obs run dir under
   ``--obs-root`` (ROADMAP "bench capture health"); the source used is
   recorded INTO ``STALE.json`` under ``tuner_calibration`` so the
   fallback is auditable.
4. An explicit default (efficiency 0.5) that says it is uncalibrated.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path
from typing import Optional, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]
LAST_GOOD_PATH = REPO_ROOT / "benchmarks" / "artifacts" / "LAST_GOOD.json"
STALE_PATH = REPO_ROOT / "benchmarks" / "artifacts" / "STALE.json"
GOLDEN_DIR = Path(__file__).resolve().parent / "goldens"

# golden scores compare within this band (pure-python floats are
# deterministic; the band absorbs deliberate small constant tweaks
# without re-pinning the world)
GOLDEN_RTOL = 0.02


def _newest_run_dir(obs_root: Path) -> Optional[Path]:
    """The run dir under ``obs_root`` whose telemetry is newest: the
    directory holding the most recently modified ``*.jsonl``."""
    newest: Tuple[float, Optional[Path]] = (-1.0, None)
    try:
        for p in obs_root.rglob("*.jsonl"):
            try:
                mtime = p.stat().st_mtime
            except OSError:
                continue
            if mtime > newest[0]:
                newest = (mtime, p.parent)
    except OSError:
        return None
    return newest[1]


def _note_stale_calibration(source: str) -> None:
    """Record into STALE.json which calibration source replaced the stale
    bench capture — best effort, the marker is an audit trail."""
    try:
        rec = json.loads(STALE_PATH.read_text())
    except (OSError, ValueError):
        return
    rec["tuner_calibration"] = {
        "source": source,
        "written": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "note": "bench capture stale: the tuner calibrated its cost model "
                "from this source instead of LAST_GOOD (never the 3.2-fudge "
                "profile path)",
    }
    try:
        tmp = STALE_PATH.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(rec, indent=1) + "\n")
        os.replace(tmp, STALE_PATH)
    except OSError as e:
        print(f"# tune: STALE.json note failed ({e})", file=sys.stderr)


def resolve_calibration(run_dir: Optional[str], obs_root: Optional[str]):
    from .costmodel import Calibration

    if run_dir:
        cal = Calibration.from_run_dir(run_dir)
        if cal is None:
            print(
                f"# tune: {run_dir} has no MFU step records; falling back",
                file=sys.stderr,
            )
        else:
            return cal
    stale = STALE_PATH.is_file()
    if not stale and LAST_GOOD_PATH.is_file():
        try:
            rec = json.loads(LAST_GOOD_PATH.read_text())
            mfu = float(rec["result"]["mfu"])
            return Calibration.from_mfu(
                mfu, f"bench:LAST_GOOD@{rec.get('captured')}"
            )
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"# tune: LAST_GOOD unreadable ({e})", file=sys.stderr)
    if stale:
        root = Path(obs_root) if obs_root else None
        newest = _newest_run_dir(root) if root else None
        if newest is not None:
            cal = Calibration.from_run_dir(newest)
            if cal is not None:
                _note_stale_calibration(cal.source)
                return cal
        cal = Calibration.default()
        _note_stale_calibration(
            cal.source if newest is None else f"{cal.source}; newest run dir "
            f"{newest} had no MFU records"
        )
        print(
            "# tune: bench capture is STALE and no obs run dir offered MFU "
            "records; scoring with the uncalibrated default efficiency "
            "(pass --run-dir or --obs-root)",
            file=sys.stderr,
        )
        return cal
    return None  # plain default, no stale marker to annotate


def golden_path(devices: int, model_name: str) -> Path:
    return GOLDEN_DIR / f"tune_{devices}dev_{model_name}.json"


def check_golden(payload: dict, path: Path) -> list:
    if not path.is_file():
        return [f"no golden at {path} (run --repin-golden)"]
    golden = json.loads(path.read_text())
    drift = []
    g_rank = [(r["label"], r["predicted_step_s"]) for r in golden["ranked"]]
    c_rank = [
        (r["label"], r["predicted_step_s"]) for r in payload["ranked"]
    ]
    if [l for l, _ in g_rank] != [l for l, _ in c_rank]:
        drift.append(
            f"ranking order changed: golden {[l for l, _ in g_rank][:5]}... "
            f"!= current {[l for l, _ in c_rank][:5]}..."
        )
    for (gl, gs), (cl, cs) in zip(g_rank, c_rank):
        if gl == cl and gs and abs(cs - gs) > GOLDEN_RTOL * gs:
            drift.append(
                f"{gl}: predicted {gs:.6f}s -> {cs:.6f}s "
                f"(> {GOLDEN_RTOL:.0%} band)"
            )
    return drift


def _lowered_crosscheck(scores, top: int) -> list:
    """Lower the real train step (tiny audit shapes) for the top layouts
    and return their per-axis inventories next to the analytic estimate
    at the SAME tiny shape — a structural check that the analytic model
    puts traffic on the right axes. cp>1 layouts are skipped (the audit
    section builder has no context-parallel arm)."""
    import dataclasses

    from ..analysis.hlo_audit import layout_cost_summary
    from .costmodel import analytic_collectives
    from .layouts import ModelSpec

    out = []
    for s in scores[:top]:
        L = s.layout
        if L.cp > 1:
            out.append({"label": L.label, "skipped": "cp>1 not lowerable "
                        "via the audit section builder"})
            continue
        layers = 2 * L.pp * L.vpp  # audit convention: 2 layers per chunk
        tiny = ModelSpec(hidden_size=128, num_layers=layers,
                         num_attention_heads=2, num_kv_heads=2,
                         sequence_length=64, vocab_size=512,
                         mlp_factor=2.0, glu=True)
        summary = layout_cost_summary(
            pp=L.pp, dp=L.dp, mp=L.mp,
            gas=L.gradient_accumulation_steps, zero=True,
            vpp=L.vpp, slices=L.token_slices, layers=layers,
        )
        # the audit section builder only expresses ZeRO-1 (zero=True);
        # pin the analytic side to the same stage so the two inventories
        # describe the SAME program, whatever stage the ranked layout ran
        tiny_layout = dataclasses.replace(
            L, micro_batch_size=2, zero_stage=1
        )
        analytic_axis: dict = {}
        for r in analytic_collectives(tiny, tiny_layout):
            # sum same-axis records (zero-3 layouts emit several per axis)
            analytic_axis[r["axis"]] = (
                analytic_axis.get(r["axis"], 0) + r["bytes"]
            )
        out.append({
            "label": L.label,
            "lowered_per_axis": summary["per_axis"],
            "analytic_per_axis": analytic_axis,
            "flops": summary["flops"],
        })
    return out


def _parse_model(name: str):
    """Resolve --model to a ModelSpec (shared by the training and
    serving modes); returns (model, model_name) or (None, error)."""
    from .layouts import BENCH_MODELS, ModelSpec

    if name in BENCH_MODELS:
        return BENCH_MODELS[name], name
    try:
        parts = [float(x) for x in name.split(",")]
        model = ModelSpec(
            hidden_size=int(parts[0]), num_layers=int(parts[1]),
            num_attention_heads=int(parts[2]), num_kv_heads=int(parts[3]),
            sequence_length=int(parts[4]), vocab_size=int(parts[5]),
            mlp_factor=parts[6] if len(parts) > 6 else 2.75,
        )
        return model, "custom"
    except (ValueError, IndexError):
        return None, name


def serve_main(args) -> int:
    """``--serve``: rank (mp, replicas, block_size, token_budget) serving
    points by predicted fleet tokens/s; golden-pinned like the training
    ranking, ``--emit-config`` writes a dict ``serve bench --config``
    runs directly (docs/TUNING.md "Serving layouts")."""
    from .costmodel import Calibration, SliceTopology
    from .serving import (
        ServeCalibration,
        check_serve_golden,
        enumerate_serving_points,
        rank_serving_points,
        serve_golden_path,
    )

    model, model_name = _parse_model(args.model)
    if model is None:
        print(f"error: unknown --model {model_name!r}", file=sys.stderr)
        return 2
    try:
        block_sizes = [
            int(x) for x in args.serve_block_sizes.split(",") if x.strip()
        ]
        budgets = [
            int(x) for x in args.serve_token_budgets.split(",") if x.strip()
        ]
    except ValueError:
        block_sizes = budgets = []
    if (not block_sizes or not budgets
            or any(v < 1 for v in block_sizes + budgets)):
        print("error: bad --serve-block-sizes / --serve-token-budgets "
              "(want comma lists of ints >= 1)", file=sys.stderr)
        return 2
    topo = SliceTopology(
        chips=args.devices, ici_domain=args.ici_domain,
        generation=args.generation,
    )
    pinning = args.check_golden or args.repin_golden
    calibration = (
        Calibration.default() if pinning
        else resolve_calibration(args.run_dir, args.obs_root)
    )
    serve_cal = None
    if args.serve_calibrate_from and not pinning:
        serve_cal = ServeCalibration.from_run_dir(
            args.serve_calibrate_from, model, topo, calibration
        )
        if serve_cal is None:
            print(
                f"# tune: {args.serve_calibrate_from} has no serve spans "
                "or engine facts; predictions uncalibrated",
                file=sys.stderr,
            )
    points = enumerate_serving_points(
        args.devices, model, block_sizes=block_sizes,
        token_budgets=budgets, num_slots=args.serve_num_slots,
    )
    if not points:
        print("error: no valid serving point (does any mp divide both "
              "the chip count and the q/kv heads?)", file=sys.stderr)
        return 2
    ranked = rank_serving_points(model, points, topo, calibration,
                                 serve_cal)
    if not ranked:
        print(f"error: no serving point fits {args.generation} HBM for "
              "this model", file=sys.stderr)
        return 2
    cal = calibration or Calibration.default()
    best = ranked[0]
    payload = {
        "mode": "serve",
        "devices": args.devices,
        "model": model_name,
        "slice_topology": topo.to_dict(),
        "calibration": cal.to_dict(),
        "serve_calibration": serve_cal.to_dict() if serve_cal else None,
        "ranked": [s.to_dict() for s in ranked],
        "serving_config": best.point.to_config(model),
        "dropped_over_hbm": len(points) - len(ranked),
    }
    if args.serve_hostsfile:
        # the placement axis (docs/SERVING.md "Host mode"): WHERE the
        # best point's replicas may spawn — per-host slot and HBM
        # feasibility over the deployment's hostsfile, plus the
        # least-loaded initial assignment `serve bench --hostsfile`
        # would make. Golden-safe: the pin compares only "ranked".
        from ..runner.config import RunnerConfig
        from ..runner.runner import get_resource_pool
        from .serving import (
            HBM_GB,
            HostCapacity,
            PlacementPlan,
            serving_memory_gb,
        )

        pool = get_resource_pool(RunnerConfig(
            hostsfile=args.serve_hostsfile, default_gpu_count=1,
        ))
        per_gb = serving_memory_gb(model, best.point) * best.point.mp
        chip_gb = HBM_GB.get(topo.generation, float("inf"))
        plan = PlacementPlan(
            [
                HostCapacity(i, hn, max(int(s), 1),
                             chip_gb * max(int(s), 1))
                for i, (hn, s) in enumerate(pool.items())
            ],
            per_replica_gb=per_gb,
        )
        try:
            assignment = plan.initial_assignment(best.point.replicas)
        except ValueError as e:
            assignment = None
            print(f"# tune: placement infeasible for best point: {e}",
                  file=sys.stderr)
        payload["placement"] = {
            "hostsfile": str(args.serve_hostsfile),
            "per_replica_gb": round(per_gb, 3),
            "hosts": plan.to_payload(),
            "assignment": assignment,
        }
    print(f"tune --serve: {len(ranked)} feasible serving point(s) of "
          f"{model_name} on {args.devices} chip(s) [{topo.generation}, "
          f"ici_domain={topo.domain}; {payload['dropped_over_hbm']} "
          f"dropped over HBM]")
    print(f"calibration: efficiency={cal.compute_efficiency:.3f} "
          f"({cal.source})"
          + (f"; serve tick factor {serve_cal.factor:.3f} "
             f"({serve_cal.source})" if serve_cal else ""))
    header = (f"{'rank':>4} {'layout':<24} {'tokens/s':>10} {'tick_s':>9} "
              f"{'comm_s':>9} {'mem_GB':>7} link")
    print(header)
    for i, s in enumerate(ranked[: args.top]):
        print(
            f"{i + 1:>4} {s.point.label:<24} {s.tokens_per_s:>10.0f} "
            f"{s.tick_s:>9.5f} {s.comm_s:>9.5f} {s.memory_gb:>7.2f} "
            f"{s.link}"
        )
    print(f"best: {best.point.label} predicted {best.tokens_per_s:.0f} "
          f"fleet tokens/s (run: python -m scaling_tpu.serve bench "
          f"--config <emitted>)")
    if payload.get("placement"):
        pl = payload["placement"]
        print(f"placement: {len(pl['hosts'])} host(s), "
              f"{pl['per_replica_gb']:.2f} GB/replica, "
              f"assignment={pl['assignment']}")
        for row in pl["hosts"]:
            print(f"    host {row['host_id']} ({row['hostname']}): "
                  f"slots={row['slots']} "
                  f"max_replicas={row['max_replicas']}")
    if args.emit_config:
        Path(args.emit_config).write_text(
            json.dumps(payload["serving_config"], indent=1) + "\n"
        )
    if args.json:
        Path(args.json).write_text(json.dumps(payload, indent=1) + "\n")
    gpath = serve_golden_path(args.devices, model_name)
    if args.repin_golden:
        gpath.parent.mkdir(parents=True, exist_ok=True)
        gpath.write_text(json.dumps(
            {
                "calibration": "pinned-default",
                "ranked": [
                    {"label": s.to_dict()["label"],
                     "tokens_per_s": s.to_dict()["tokens_per_s"]}
                    for s in ranked
                ],
            },
            indent=1,
        ) + "\n")
        print(f"serving golden repinned -> {gpath}")
    elif args.check_golden:
        drift = check_serve_golden(payload, gpath)
        for line in drift:
            print(f"DRIFT: {line}")
        print(f"golden: {'OK' if not drift else 'DRIFT'}")
        return 1 if drift else 0
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scaling_tpu.tune",
        description="topology-aware auto-sharding tuner (docs/TUNING.md)",
    )
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--model", default="0.5b",
                        help="bench model name (0.5b|1b) or "
                        "hidden,layers,heads,kv,seq,vocab[,mlp_factor]")
    parser.add_argument("--global-batch", type=int, default=64,
                        help="global batch size in sequences")
    parser.add_argument("--mbs", type=int, default=8,
                        help="micro batch size (bench self-tunes this "
                        "per chip; the tuner searches layouts at a fixed "
                        "one unless --mbs-ladder widens the search)")
    parser.add_argument("--mbs-ladder", metavar="LIST",
                        help="comma list of additional micro-batch sizes "
                        "to enumerate and score alongside --mbs (global "
                        "batch fixed, so gas scales inversely: smaller "
                        "mbs buys thinner pipeline bubbles and less "
                        "activation memory). Ignored under golden "
                        "pinning so the pinned ranking stays single-mbs")
    parser.add_argument("--generation", default="tpu_v5e",
                        choices=["tpu_v4", "tpu_v5e", "tpu_v5p", "tpu_v6e"])
    parser.add_argument("--ici-domain", type=int, default=None,
                        help="chips per ICI domain (default: all chips on "
                        "one slice; smaller values model DCN crossings)")
    parser.add_argument("--top", type=int, default=10,
                        help="rows to print (the JSON always carries all)")
    parser.add_argument("--json", metavar="FILE",
                        help="write the machine-readable report")
    parser.add_argument("--run-dir", help="obs run dir to calibrate "
                        "compute efficiency from (mean MFU)")
    parser.add_argument("--obs-root",
                        help="root to search for the newest obs run dir "
                        "when the bench capture is stale")
    parser.add_argument("--correct-from-runs", metavar="ROOT",
                        help="accumulate tuner-prediction vs span-measured "
                        "pairs from every run dir under ROOT and apply the "
                        "per-axis multiplicative correction to the ranking "
                        "(docs/TUNING.md calibration loop)")
    parser.add_argument("--emit-config", metavar="FILE",
                        help="write the best layout's TopologyConfig dict")
    parser.add_argument("--record-events", metavar="FILE",
                        help="append a tuner-prediction event for the best "
                        "layout to this events JSONL (an obs run dir file)")
    parser.add_argument("--lower", type=int, metavar="K", default=0,
                        help="cross-check the top K layouts' analytic axis "
                        "attribution against the really-lowered step "
                        "(tiny shapes; needs the 8-device CPU mesh)")
    parser.add_argument("--check-golden", action="store_true",
                        help="compare against the pinned ranking (forces "
                        "the default calibration)")
    parser.add_argument("--repin-golden", action="store_true",
                        help="rewrite the pinned ranking from this run "
                        "(forces the default calibration)")
    # ---- serving layouts (docs/TUNING.md "Serving layouts") ----
    parser.add_argument("--serve", action="store_true",
                        help="rank SERVING layouts instead of training "
                        "ones: (mp, replicas=devices/mp, block_size, "
                        "token_budget) points scored by fleet tokens/s — "
                        "mp activation all-reduces priced ICI-vs-DCN like "
                        "training, KV pool memory per chip gated against "
                        "the generation's HBM")
    parser.add_argument("--serve-block-sizes", default="8,16,32",
                        metavar="LIST", help="KV block sizes to sweep")
    parser.add_argument("--serve-token-budgets", default="128,256,512",
                        metavar="LIST",
                        help="per-tick token budgets to sweep")
    parser.add_argument("--serve-num-slots", type=int, default=8,
                        help="decode slots per replica (fixed across the "
                        "sweep; the jitted batch size)")
    parser.add_argument("--serve-hostsfile", metavar="FILE",
                        help="with --serve: plan WHERE the best point's "
                        "replicas spawn — per-host slot/HBM feasibility "
                        "over this runner hostsfile, published as the "
                        "payload's 'placement' table (the same "
                        "least-loaded rule serve bench --hostsfile "
                        "applies at spawn time)")
    parser.add_argument("--serve-calibrate-from", metavar="RUN_DIR",
                        help="scale predicted tick time by the measured "
                        "serve.mixed/serve.decode spans of this serve "
                        "bench run dir (its serve-summary must carry the "
                        "engine shape facts)")
    args = parser.parse_args(argv)
    if args.serve:
        return serve_main(args)

    from .costmodel import (
        AxisCorrection,
        Calibration,
        SliceTopology,
        rank_layouts,
    )
    from .layouts import BENCH_MODELS, enumerate_layouts

    model, model_name = _parse_model(args.model)
    if model is None:
        print(f"error: unknown --model {model_name!r} "
              f"(names: {sorted(BENCH_MODELS)})", file=sys.stderr)
        return 2

    topo = SliceTopology(
        chips=args.devices, ici_domain=args.ici_domain,
        generation=args.generation,
    )
    pinning = args.check_golden or args.repin_golden
    calibration = (
        Calibration.default() if pinning
        else resolve_calibration(args.run_dir, args.obs_root)
    )
    ladder = None
    if args.mbs_ladder and not pinning:
        try:
            ladder = [int(x) for x in args.mbs_ladder.split(",") if x.strip()]
        except ValueError:
            ladder = None
        if not ladder or any(m < 1 for m in ladder):
            print(f"error: bad --mbs-ladder {args.mbs_ladder!r} "
                  "(want a comma list of ints >= 1)", file=sys.stderr)
            return 2
    layouts = enumerate_layouts(
        args.devices, model, global_batch_size=args.global_batch,
        micro_batch_size=args.mbs, mbs_ladder=ladder,
    )
    if not layouts:
        print("error: no valid layouts for this model/device count",
              file=sys.stderr)
        return 2
    correction = None
    if args.correct_from_runs and not pinning:
        correction = AxisCorrection.from_run_dirs(args.correct_from_runs)
        if correction is None:
            print(
                f"correction: no tuner prediction/measured pairs under "
                f"{args.correct_from_runs}; ranking uncorrected",
                file=sys.stderr,
            )
    ranked = rank_layouts(model, layouts, topo, calibration,
                          correction=correction)
    cal = calibration or Calibration.default()

    best = ranked[0]
    prediction = {
        "label": best.layout.label,
        "predicted_step_s": round(best.predicted_step_s, 6),
        "world_size": best.layout.world,
        "source": cal.source,
        "collectives_source": best.collectives_source,
    }
    payload = {
        "devices": args.devices,
        "model": model_name,
        "model_spec": {
            "hidden_size": model.hidden_size,
            "num_layers": model.num_layers,
            "num_attention_heads": model.num_attention_heads,
            "num_kv_heads": model.num_kv_heads,
            "sequence_length": model.sequence_length,
            "vocab_size": model.vocab_size,
            "mlp_factor": model.mlp_factor,
            "parameter_count": model.parameter_count,
        },
        "global_batch_size": args.global_batch,
        "micro_batch_size": args.mbs,
        "slice_topology": topo.to_dict(),
        "calibration": cal.to_dict(),
        "axis_correction": correction.to_dict() if correction else None,
        "ranked": [s.to_dict() for s in ranked],
        "topology_config": best.layout.topology_dict(),
        "prediction": prediction,
    }
    if args.lower:
        from ..analysis.cli import _ensure_virtual_mesh

        _ensure_virtual_mesh()  # lowering needs the 8-device CPU mesh
        payload["lowered_crosscheck"] = _lowered_crosscheck(ranked, args.lower)

    print(f"tune: {len(ranked)} valid layout(s) of {model_name} on "
          f"{args.devices} device(s) [{topo.generation}, ici_domain="
          f"{topo.domain}]")
    print(f"calibration: efficiency={cal.compute_efficiency:.3f} "
          f"({cal.source})")
    if correction is not None:
        facs = " ".join(
            f"{a}={f:.3f}" for a, f in sorted(correction.factors.items())
        )
        print(f"axis correction: {facs or '(none)'} "
              f"[{correction.pairs} pair(s), {correction.source}]")
    header = (f"{'rank':>4} {'layout':<28} {'step_s':>9} {'tok/s':>10} "
              f"{'bubble':>7} {'comm_s':>8} {'mem_GB':>7} links")
    print(header)
    for i, s in enumerate(ranked[: args.top]):
        links = ",".join(
            f"{ax}:{rec['link']}" for ax, rec in sorted(s.comm_by_axis.items())
        )
        print(
            f"{i + 1:>4} {s.layout.label:<28} {s.predicted_step_s:>9.4f} "
            f"{s.tokens_per_s:>10.0f} {s.bubble_fraction:>6.1%} "
            f"{s.comm_s:>8.4f} {s.memory_gb:>7.2f} {links}"
        )
    print(f"best: {best.layout.label} predicted {best.predicted_step_s:.4f}"
          f"s/step ({best.tokens_per_s:.0f} tokens/s)")
    print("export " + "SCALING_TPU_TUNER_PREDICTION='"
          + json.dumps(prediction) + "'")

    if args.emit_config:
        Path(args.emit_config).write_text(
            json.dumps(payload["topology_config"], indent=1) + "\n"
        )
    if args.record_events:
        from ..logging.logger import append_jsonl_line

        append_jsonl_line(
            args.record_events,
            json.dumps(
                {"event": "tuner-prediction", "ts": time.time(), **prediction},
                sort_keys=True,
            ),
        )
    if args.json:
        Path(args.json).write_text(json.dumps(payload, indent=1) + "\n")

    gpath = golden_path(args.devices, model_name)
    if args.repin_golden:
        gpath.parent.mkdir(parents=True, exist_ok=True)
        gpath.write_text(json.dumps(
            {
                "calibration": "pinned-default",
                "ranked": [
                    {"label": s.to_dict()["label"],
                     "predicted_step_s": s.to_dict()["predicted_step_s"]}
                    for s in ranked
                ],
            },
            indent=1,
        ) + "\n")
        print(f"golden repinned -> {gpath}")
    elif args.check_golden:
        drift = check_golden(payload, gpath)
        for line in drift:
            print(f"DRIFT: {line}")
        print(f"golden: {'OK' if not drift else 'DRIFT'}")
        return 1 if drift else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
