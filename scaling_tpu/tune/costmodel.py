"""Topology-aware comm/compute cost model for layout search.

The TASP/ATP result (arxiv 2509.26541, 2301.08658) this module encodes:
layout choice is dominated by WHERE each mesh axis's collectives run —
an axis folded inside an ICI domain moves bytes two orders of magnitude
faster than one that crosses DCN — so a useful placement engine needs
(a) per-axis traffic volumes and (b) a link-class map, not a single
"communication" scalar.

Three ingredient sources, in decreasing fidelity:

- **lowered artifacts**: a per-(op, axis) inventory from the real jitted
  step (``analysis.hlo_audit.layout_cost_summary`` or a committed audit
  golden via ``cost_summary_from_report``) — exact counts/bytes for the
  lowered shape;
- **analytic volumes** (the default for searching spaces no one lowered):
  closed-form per-axis estimates — data-axis gradient all-reduce,
  model-axis activation reductions, pipe-edge collective-permutes,
  ring/ulysses context traffic, ZeRO-3 parameter all-gathers — the same
  textbook forms Megatron-LM/ATP use;
- **calibration**: a compute-efficiency scalar taken from a real
  measurement (obs run-dir MFU, bench LAST_GOOD MFU) so predicted step
  times live in measured units, and the obs report's tuner section can
  score the prediction against span-measured step time per run
  (docs/TUNING.md "calibration loop").

Pipeline layouts are priced through the PR 7 schedule simulator
(``parallel.pipeline_schedule.simulate_layout``) — bubble fractions come
from replaying the actual schedule (fill-drain / interleaved /
token-slice), not a closed-form guess.
"""

from __future__ import annotations

import dataclasses
import math
import re
from pathlib import Path
from typing import Dict, List, Optional

from .layouts import Layout, ModelSpec

BF16 = 2  # activation / parameter bytes
F32 = 4   # gradient / master bytes

# Token slicing forces attention through the segment-aware KV-cache path
# (nn/attention.py 3-tuple kv_cache) — the Pallas flash kernel does not
# run there. Two factors price that:
#
# - CACHE_VS_DENSE(S): compiled-FLOPs ratio of the S-sliced cache path
#   against one full-sequence DENSE (unfused) attention: the sliced path
#   computes sum_k (s/S * k*s/S) scores = (S+1)/(2S) of the dense s^2 —
#   pinned empirically by tests/core/test_tune/test_attention_penalty.py
#   against jitted cost_analysis FLOPs of the real unfused attention.
# - FLASH_CAUSAL_SKIP: the flash kernel's causal block skip does ~s^2/2
#   effective work, so relative to the FLASH baseline the sliced path
#   pays 2 * CACHE_VS_DENSE(S) = (S+1)/S.
# - CACHE_PATH_OVERHEAD: non-FLOPs cost of the cache path (per-slice
#   cache concatenation/bookkeeping, no fused softmax) — modest constant.
FLASH_CAUSAL_SKIP = 2.0
CACHE_PATH_OVERHEAD = 1.1


def cache_vs_dense_flops_ratio(token_slices: int) -> float:
    s = token_slices
    return (s + 1) / (2.0 * s)


def token_slice_attention_factor(token_slices: int) -> float:
    """Multiplier on the attention FLOPs share when the sequence is split
    into ``token_slices`` causal cache-path chunks, relative to the
    flash-kernel baseline every other layout runs."""
    if token_slices <= 1:
        return 1.0
    return (
        FLASH_CAUSAL_SKIP
        * cache_vs_dense_flops_ratio(token_slices)
        * CACHE_PATH_OVERHEAD
    )


# ------------------------------------------------------------ link classes
@dataclasses.dataclass(frozen=True)
class LinkClass:
    name: str          # "ici" | "dcn"
    gbytes_per_s: float
    latency_s: float


# Public per-chip interconnect figures (cloud.google.com TPU pages):
# ICI bidirectional bandwidth per chip — v4 2400 Gbps, v5e 1600 Gbps,
# v5p 4800 Gbps, v6e 3584 Gbps; DCN rides the hosts' NICs (~200 Gbps
# shared per host, ~25 GB/s). Absolute numbers matter less than the
# ICI:DCN ratio for ranking; the calibration loop owns absolute scale.
_GENERATIONS = {
    "tpu_v4": (300.0, 275.0),
    "tpu_v5e": (200.0, 197.0),
    "tpu_v5p": (600.0, 459.0),
    "tpu_v6e": (448.0, 918.0),
}


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """What the tuner knows about the physical slice: how many chips, how
    many of them share an ICI domain (contiguous in mesh order — the
    standard TPU runtime enumeration), and the generation's link rates.
    ``ici_domain == chips`` is a single slice (everything on ICI);
    smaller domains model multi-slice / multi-host DCN crossings."""

    chips: int
    ici_domain: Optional[int] = None  # None: one slice, all-ICI
    generation: str = "tpu_v5e"
    dcn_gbytes_per_s: float = 25.0
    ici_latency_s: float = 1e-6
    dcn_latency_s: float = 25e-6

    @property
    def domain(self) -> int:
        return self.ici_domain or self.chips

    @property
    def peak_tflops(self) -> float:
        return _GENERATIONS[self.generation][1]

    @property
    def ici(self) -> LinkClass:
        return LinkClass(
            "ici", _GENERATIONS[self.generation][0], self.ici_latency_s
        )

    @property
    def dcn(self) -> LinkClass:
        return LinkClass("dcn", self.dcn_gbytes_per_s, self.dcn_latency_s)

    def to_dict(self) -> dict:
        return {
            "chips": self.chips, "ici_domain": self.domain,
            "generation": self.generation,
            "ici_gbytes_per_s": self.ici.gbytes_per_s,
            "dcn_gbytes_per_s": self.dcn.gbytes_per_s,
        }


# mesh order (topology/topology.py MESH_AXES): flat rank =
# (((pipe*dp + data)*cp + context)*mp + model)
_AXES = ("pipe", "data", "context", "model")


def axis_sizes(layout: Layout) -> Dict[str, int]:
    return {
        "pipe": layout.pp, "data": layout.dp,
        "context": layout.cp, "model": layout.mp,
    }


def axis_stride(layout: Layout, axis: str) -> int:
    strides = {
        "model": 1,
        "context": layout.mp,
        "data": layout.cp * layout.mp,
        "pipe": layout.dp * layout.cp * layout.mp,
    }
    return strides[axis]


def link_for_axis(layout: Layout, topo: SliceTopology, axis: str) -> LinkClass:
    """ICI when every communicating group of this axis fits inside one
    ICI domain of contiguous device ids, DCN as soon as any neighbour
    pair crosses a domain boundary. Fused axes ("data+model") take the
    worst member — one DCN hop prices the whole group."""
    if "+" in axis:
        links = [link_for_axis(layout, topo, a) for a in axis.split("+")]
        return min(links, key=lambda l: l.gbytes_per_s)
    if axis not in _AXES:
        return topo.ici  # "world"/"unattributed": assume on-slice
    stride = axis_stride(layout, axis)
    size = axis_sizes(layout)[axis]
    # groups are arithmetic sequences {base + k*stride} spanning an
    # aligned block of stride*size contiguous ids; every group stays
    # inside one domain iff that block size DIVIDES the domain — a
    # merely-smaller block can straddle a boundary (stride=1, size=2,
    # domain=3: group {2,3} crosses), so non-dividing shapes price DCN
    # (conservative, and exact for the power-of-two meshes TPUs ship)
    block = stride * size
    return (
        topo.ici if block <= topo.domain and topo.domain % block == 0
        else topo.dcn
    )


# --------------------------------------------------------- collective math
_RING_FACTOR = {
    # effective wire bytes per payload byte on a size-n ring
    "all-reduce": lambda n: 2.0 * (n - 1) / n,
    "all-gather": lambda n: (n - 1) / n,
    "reduce-scatter": lambda n: (n - 1) / n,
    "all-to-all": lambda n: (n - 1) / n,
    "collective-permute": lambda n: 1.0,
}


def collective_seconds(op: str, payload_bytes: float, count: int,
                       axis_size: int, link: LinkClass) -> float:
    if axis_size <= 1 or payload_bytes <= 0:
        return count * link.latency_s if count else 0.0
    factor = _RING_FACTOR.get(op, lambda n: 1.0)(axis_size)
    return payload_bytes * factor / (link.gbytes_per_s * 1e9) + (
        count * link.latency_s
    )


def analytic_collectives(model: ModelSpec, layout: Layout) -> List[dict]:
    """Per-(op, axis) payload estimate for one optimizer step, in the
    SAME record shape as ``analysis.hlo_audit.collective_inventory``
    ({op, axis, count, bytes}) — bytes are per-device payload per step,
    so an artifact-fed summary can drop in for this list unchanged."""
    L = layout
    recs: List[dict] = []
    act = L.micro_batch_size * (model.sequence_length // L.cp) * (
        model.hidden_size
    ) * BF16  # one micro-batch's boundary activations per device
    params_shard = model.parameter_count // (L.pp * L.mp)
    gas = L.gradient_accumulation_steps
    layers_local = max(1, model.num_layers // L.pp)

    if L.dp > 1:
        if L.zero_stage >= 3:
            # FSDP: reduce-scatter grads once; re-gather bf16 params for
            # forward and backward
            recs.append({"op": "reduce-scatter", "axis": "data", "count": 1,
                         "bytes": params_shard * F32})
            recs.append({"op": "all-gather", "axis": "data", "count": 2,
                         "bytes": 2 * params_shard * BF16})
        else:
            recs.append({"op": "all-reduce", "axis": "data", "count": 1,
                         "bytes": params_shard * F32})
    if L.mp > 1:
        # Megatron TP: 2 activation reductions per layer forward + 2
        # backward, per micro-batch (SP recasts them as RS+AG at equal
        # volume, so sp does not change the estimate)
        count = 4 * layers_local * gas
        recs.append({"op": "all-reduce", "axis": "model", "count": count,
                     "bytes": count * act})
    if L.pp > 1:
        # stage-boundary shift each tick, forward + backward; interleaved
        # circulates v rounds (v x the crossings at full payload), token
        # slices cross S x at payload/S (equal volume)
        crossings = 2 * gas * L.vpp
        recs.append({
            "op": "collective-permute", "axis": "pipe",
            "count": crossings * max(1, L.token_slices),
            "bytes": crossings * act,
        })
    if L.cp > 1:
        head_dim = model.hidden_size // model.num_attention_heads
        if L.cp_variant == "ulysses":
            count = 4 * model.num_layers * gas  # 2 fwd + 2 bwd per layer
            recs.append({"op": "all-to-all", "axis": "context",
                         "count": count, "bytes": count * act})
        else:
            # ring attention: rotate unrepeated K/V blocks cp-1 times per
            # layer, forward and backward
            kv_block = L.micro_batch_size * (
                model.sequence_length // L.cp
            ) * model.num_kv_heads * head_dim * BF16 * 2  # K and V
            count = 2 * (L.cp - 1) * model.num_layers * gas
            recs.append({"op": "collective-permute", "axis": "context",
                         "count": count, "bytes": count * kv_block})
    return recs


# ------------------------------------------------------------- calibration
@dataclasses.dataclass(frozen=True)
class Calibration:
    """Compute efficiency = fraction of the peak FLOP rate the chip
    sustains on compute-bound work (exactly what a measured MFU is on a
    single-chip run). The tuner NEVER falls back to the legacy
    step-time/3.2 fudge — sources are a real MFU or an explicit default
    that says so."""

    compute_efficiency: float
    source: str

    @classmethod
    def default(cls) -> "Calibration":
        return cls(0.5, "default (uncalibrated: no bench capture or obs "
                        "run dir offered)")

    @classmethod
    def from_mfu(cls, mfu: float, source: str) -> "Calibration":
        eff = min(max(float(mfu), 0.01), 1.0)
        return cls(eff, source)

    @classmethod
    def from_run_dir(cls, run_dir) -> Optional["Calibration"]:
        """Mean MFU of the step records in an obs run dir (the trainer's
        own PaLM-MFU gauge), or None when the run recorded none."""
        from ..obs.report import load_run_dir, mfu_section  # stdlib-only

        data = load_run_dir(run_dir)
        _, stats = mfu_section(data)
        mean = stats.get("mfu_mean")
        if mean is None or mean <= 0:
            return None
        return cls.from_mfu(mean, f"obs:{run_dir}")

    def to_dict(self) -> dict:
        return {
            "compute_efficiency": round(self.compute_efficiency, 4),
            "source": self.source,
        }


# --------------------------------------------------- per-axis correction
_LABEL_AXES = (
    ("pipe", re.compile(r"(?:^|·)pp(\d+)")),
    ("data", re.compile(r"(?:^|·)dp(\d+)")),
    ("context", re.compile(r"(?:^|·)cp(\d+)")),
    ("model", re.compile(r"(?:^|·)mp(\d+)")),
)


def _axes_of_label(label: str) -> List[str]:
    """The parallel axes a layout label says are active (size > 1);
    ``["compute"]`` for a single-device / pure-replication layout."""
    active = [
        axis for axis, rx in _LABEL_AXES
        if (m := rx.search(label)) and int(m.group(1)) > 1
    ]
    return active or ["compute"]


def _axes_of_layout(layout: Layout) -> List[str]:
    active = [a for a, n in axis_sizes(layout).items() if n > 1]
    return active or ["compute"]


@dataclasses.dataclass(frozen=True)
class AxisCorrection:
    """Per-axis multiplicative correction learned from the calibration
    loop's accumulated (tuner-prediction, span-measured) pairs.

    Every run that exported a prediction leaves a ``tuner-prediction``
    event + measured step time in its run dir (docs/TUNING.md); each
    such pair contributes its measured/predicted ratio to the bucket of
    every parallel axis its layout label says is active (``compute``
    when none). A layout's correction is the geometric mean of its
    active axes' factors — so if every dp-dominant run measured 1.5x
    the prediction, dp-heavy candidates are re-priced up before the
    next placement decision (the supervisor's downsize replan reads
    this, so every prior epoch's telemetry sharpens the next layout)."""

    factors: Dict[str, float]
    pairs: int = 0
    source: str = "identity"

    @classmethod
    def identity(cls) -> "AxisCorrection":
        return cls(factors={}, pairs=0, source="identity")

    @classmethod
    def from_pairs(cls, pairs: List[dict], source: str = "pairs"
                   ) -> "AxisCorrection":
        """``pairs``: dicts with ``label``, ``predicted_step_s``,
        ``measured_step_s``. Non-finite / non-positive entries are
        dropped, never fatal (telemetry quality varies per run dir)."""
        logs: Dict[str, List[float]] = {}
        kept = 0
        for p in pairs:
            try:
                predicted = float(p["predicted_step_s"])
                measured = float(p["measured_step_s"])
                label = str(p["label"])
            except (KeyError, TypeError, ValueError):
                continue
            if not (
                math.isfinite(predicted) and math.isfinite(measured)
                and predicted > 0 and measured > 0
            ):
                continue
            kept += 1
            ratio = math.log(measured / predicted)
            for axis in _axes_of_label(label):
                logs.setdefault(axis, []).append(ratio)
        factors = {
            axis: round(math.exp(sum(v) / len(v)), 6)
            for axis, v in logs.items()
        }
        return cls(factors=factors, pairs=kept, source=source)

    @classmethod
    def from_run_dirs(cls, root: Path | str) -> Optional["AxisCorrection"]:
        """Accumulate pairs from the run dirs under ``root``: each
        immediate subdirectory is one run dir (scanned recursively),
        plus ``root``'s own direct files as one more — a flat telemetry
        dir with an incidental subdirectory (checkpoints, plots, a
        control dir) must not lose its own events. Root is read
        NON-recursively so subdirectory telemetry is never counted
        twice. None when no run recorded a usable pair."""
        from ..obs.report import load_run_dir, tuner_section  # stdlib-only

        root = Path(root)
        if not root.is_dir():
            return None
        subdirs = sorted(p for p in root.iterdir() if p.is_dir())
        pairs: List[dict] = []
        for d in subdirs + [root]:
            data = load_run_dir(d, recursive=d is not root)
            _, stats = tuner_section(data)
            predicted = stats.get("tuner_predicted_step_s")
            measured = stats.get("tuner_measured_step_s")
            if predicted is None or measured is None:
                continue
            preds = [
                e for e in data.lifecycle
                if e.get("event") == "tuner-prediction"
            ]
            label = preds[-1].get("label", "") if preds else ""
            pairs.append({
                "label": label, "predicted_step_s": predicted,
                "measured_step_s": measured,
            })
        if not pairs:
            return None
        return cls.from_pairs(pairs, source=f"run-dirs:{root}")

    def factor_for(self, layout: Layout) -> float:
        """Geometric mean of the layout's active axes' factors (axes
        with no accumulated telemetry contribute 1.0)."""
        logs = [
            math.log(self.factors[a])
            for a in _axes_of_layout(layout) if a in self.factors
        ]
        if not logs:
            return 1.0
        return math.exp(sum(logs) / len(logs))

    def to_dict(self) -> dict:
        return {
            "factors": dict(self.factors), "pairs": self.pairs,
            "source": self.source,
        }


# ------------------------------------------------------------------ scoring
@dataclasses.dataclass
class LayoutScore:
    layout: Layout
    predicted_step_s: float
    compute_s: float
    comm_s: float
    bubble_fraction: float
    comm_by_axis: Dict[str, dict]
    memory_gb: float
    collectives_source: str
    step_tokens: int = 0

    @property
    def tokens_per_s(self) -> float:
        if self.predicted_step_s <= 0:
            return 0.0
        return self.step_tokens / self.predicted_step_s

    def to_dict(self) -> dict:
        return {
            "label": self.layout.label,
            "layout": self.layout.topology_dict(),
            "predicted_step_s": round(self.predicted_step_s, 6),
            "compute_s": round(self.compute_s, 6),
            "comm_s": round(self.comm_s, 6),
            "bubble_fraction": round(self.bubble_fraction, 4),
            "comm_by_axis": self.comm_by_axis,
            "memory_gb_per_device": round(self.memory_gb, 3),
            "collectives_source": self.collectives_source,
            "tokens_per_s": round(self.tokens_per_s, 1),
        }


def memory_gb_per_device(model: ModelSpec, layout: Layout) -> float:
    """Rough HBM footprint: bf16 params + f32 grads + AdamW fp32 master
    and moments (ZeRO shards optimizer state over dp; stage 3 shards the
    stored params too) + boundary activations. A planning estimate, not
    an allocator — the dryrun remains the fit oracle."""
    shard = model.parameter_count / (layout.pp * layout.mp)
    zero_div = layout.dp if layout.zero_stage >= 1 else 1
    params = shard * BF16 / (layout.dp if layout.zero_stage >= 3 else 1)
    grads = shard * F32
    opt = shard * 3 * F32 / zero_div
    act = (
        layout.micro_batch_size
        * (model.sequence_length / layout.cp)
        * model.hidden_size
        * (model.num_layers / layout.pp)
        * 16  # residual + attention + mlp working set, bf16
        / (layout.mp if layout.sp else 1)
    )
    return (params + grads + opt + act) / 1e9


def score_layout(
    model: ModelSpec,
    layout: Layout,
    slice_topology: SliceTopology,
    calibration: Optional[Calibration] = None,
    collectives: Optional[List[dict]] = None,
    collectives_source: str = "analytic",
    correction: Optional[AxisCorrection] = None,
) -> LayoutScore:
    """Predicted seconds per optimizer step for ``layout``.

    compute: model FLOPs / world, at the calibrated efficiency of the
    generation's peak, with the token-slice attention penalty applied;
    pipeline layouts replay their actual schedule through the PR 7
    simulator (pipe-edge comm priced inside it). Non-pipe collectives
    (data/model/context axes) are priced per axis against the link class
    the slice topology assigns and added to the critical path — no
    overlap is assumed, which is conservative and, like every constant
    here, corrected by the calibration loop. ``correction`` applies the
    accumulated per-axis prediction-vs-measured factors on top.
    """
    cal = calibration or Calibration.default()
    L = layout
    tokens = L.global_batch_size * model.sequence_length

    attn_mult = token_slice_attention_factor(L.token_slices)
    flops_factor = 1.0 + model.attention_flops_fraction * (attn_mult - 1.0)
    device_flops = model.flops_per_token * tokens * flops_factor / L.world
    rate = slice_topology.peak_tflops * 1e12 * cal.compute_efficiency
    compute_s = device_flops / rate

    inventory = collectives if collectives is not None else (
        analytic_collectives(model, layout)
    )
    sizes = axis_sizes(layout)
    comm_by_axis: Dict[str, dict] = {}
    pipe_comm_s = 0.0
    comm_s = 0.0
    for rec in inventory:
        axis = rec["axis"]
        link = link_for_axis(layout, slice_topology, axis)
        n = 1
        for part in axis.split("+"):
            n *= sizes.get(part, 1)
        secs = collective_seconds(
            rec["op"], float(rec["bytes"]), int(rec["count"]), n, link
        )
        slot = comm_by_axis.setdefault(
            axis, {"seconds": 0.0, "bytes": 0, "link": link.name}
        )
        slot["seconds"] += secs
        slot["bytes"] += int(rec["bytes"])
        if axis == "pipe" and rec["op"] == "collective-permute":
            pipe_comm_s += secs  # priced inside the schedule simulator
        else:
            comm_s += secs
    for slot in comm_by_axis.values():
        slot["seconds"] = round(slot["seconds"], 6)

    bubble = 0.0
    if L.pp > 1:
        from ..parallel.pipeline_schedule import simulate_layout

        gas = L.gradient_accumulation_steps
        unit = compute_s / (3.0 * gas)
        # one boundary crossing's wire time at FULL micro-batch payload —
        # the schedule's own duration_scale thins token slices, so the
        # simulator prices the pipe-axis comm (the inventory's pipe
        # permutes), not this function
        link = link_for_axis(layout, slice_topology, "pipe")
        act_bytes = L.micro_batch_size * (
            model.sequence_length // L.cp
        ) * model.hidden_size * BF16
        hop = 0.5 * (
            act_bytes / (link.gbytes_per_s * 1e9) + link.latency_s
        )
        sim = simulate_layout(
            pipe_parallel_size=L.pp,
            gradient_accumulation_steps=gas,
            virtual_size=L.vpp,
            token_slices=L.token_slices,
            durations={
                "forward_pass": unit, "backward_pass": 2.0 * unit,
                "loss": 0.1 * unit, "optimizer_step": 0.1 * unit,
                "load_micro_batch": 0.05 * unit,
                "store_micro_batch": 0.05 * unit,
                "send_activation": hop, "recv_activation": hop,
                "send_grad": hop, "recv_grad": hop,
                "reduce_tied_grads": 0.0,
            },
        )
        step_core = sim["total_time"]
        bubble = sim["bubble_fraction"]
    else:
        step_core = compute_s

    predicted = step_core + comm_s
    if correction is not None:
        predicted *= correction.factor_for(layout)
    score = LayoutScore(
        layout=layout,
        predicted_step_s=predicted,
        compute_s=compute_s,
        comm_s=comm_s + pipe_comm_s,
        bubble_fraction=bubble,
        comm_by_axis=comm_by_axis,
        memory_gb=memory_gb_per_device(model, layout),
        collectives_source=collectives_source,
        step_tokens=tokens,
    )
    return score


def rank_layouts(
    model: ModelSpec,
    layouts: List[Layout],
    slice_topology: SliceTopology,
    calibration: Optional[Calibration] = None,
    correction: Optional[AxisCorrection] = None,
) -> List[LayoutScore]:
    scored = [
        score_layout(model, l, slice_topology, calibration,
                     correction=correction)
        for l in layouts
    ]
    scored.sort(key=lambda s: (s.predicted_step_s, s.layout.label))
    return scored
