"""scaling_tpu.tune — topology-aware auto-sharding tuner.

Turns the MULTICHIP dryrun grid into a placement engine (ROADMAP
"Topology-aware auto-sharding tuner"; TASP arxiv 2509.26541, ATP arxiv
2301.08658): enumerate every valid pp x dp x cp x mp (+zero / virtual
stages / token slices / ring-vs-ulysses) layout of a model on a chip
count, score each against a measured comm/compute cost model — per-axis
collective volumes priced by ICI-vs-DCN link class, pipeline bubbles
replayed through the PR 7 schedule simulator, compute calibrated from a
real MFU capture — and emit a ranked report plus a ready-to-run
``TopologyConfig``.

Library surface::

    from scaling_tpu import tune
    best, ranked = tune.best_layout(model_cfg, slice_topology)

CLI::

    python -m scaling_tpu.tune --devices 8 --model 0.5b --json report.json

The closed loop (docs/TUNING.md): the CLI's prediction for the chosen
layout is exported as ``SCALING_TPU_TUNER_PREDICTION``; the trainer logs
it as a ``tuner-prediction`` event into the run's events stream, and
``python -m scaling_tpu.obs report`` renders a tuner section comparing
the prediction against span-measured step time — calibration error is a
tracked, gateable number (``--assert-tuner-calibration``), so a drifted
cost model fails CI instead of silently mis-placing the next run.

Import stays light (stdlib only); the submodules pull pydantic/jax
lazily so ``prediction_from_env`` is safe anywhere the trainer runs.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

PREDICTION_ENV = "SCALING_TPU_TUNER_PREDICTION"

# re-exported lazily (PEP 562) so importing the package costs nothing
_LAZY = {
    "ModelSpec": "layouts", "Layout": "layouts", "BENCH_MODELS": "layouts",
    "enumerate_layouts": "layouts",
    "SliceTopology": "costmodel", "Calibration": "costmodel",
    "AxisCorrection": "costmodel",
    "LayoutScore": "costmodel", "score_layout": "costmodel",
    "rank_layouts": "costmodel", "analytic_collectives": "costmodel",
    "link_for_axis": "costmodel",
    "token_slice_attention_factor": "costmodel",
    # serving layouts (docs/TUNING.md "Serving layouts")
    "ServingPoint": "serving", "ServingScore": "serving",
    "ServeCalibration": "serving",
    "enumerate_serving_points": "serving",
    "score_serving_point": "serving", "rank_serving_points": "serving",
}

__all__ = sorted(_LAZY) + [
    "PREDICTION_ENV", "best_layout", "prediction_from_env", "rank_of_layout",
]


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def prediction_from_env() -> Optional[dict]:
    """The tuner prediction a launcher exported for this run, sanitized,
    or None. The trainer logs the result as a ``tuner-prediction``
    lifecycle event so the obs report can close the calibration loop;
    malformed payloads return None (a bad export must not kill a run)."""
    raw = os.environ.get(PREDICTION_ENV)
    if not raw:
        return None
    try:
        payload = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(payload, dict):
        return None
    out: dict = {}
    try:
        out["predicted_step_s"] = float(payload["predicted_step_s"])
    except (KeyError, TypeError, ValueError):
        return None  # a prediction without a number cannot calibrate
    for key in ("label", "source", "collectives_source"):
        if isinstance(payload.get(key), str):
            out[key] = payload[key][:200]
    for key in ("world_size",):
        try:
            out[key] = int(payload[key])
        except (KeyError, TypeError, ValueError):
            pass
    return out


def best_layout(
    model_cfg,
    slice_topology=None,
    *,
    global_batch_size: int = 64,
    micro_batch_size: int = 8,
    calibration=None,
    correction=None,
) -> Tuple["Layout", list]:
    """Search the layout space of ``model_cfg`` (a ``ModelSpec``, a
    transformer-architecture config object, or a bench model name like
    ``"0.5b"``) over ``slice_topology`` and return
    ``(best_layout, ranked_scores)``. ``correction`` (an
    ``AxisCorrection``) re-prices candidates by the accumulated
    prediction-vs-measured telemetry — the supervisor's downsize replan
    passes it so every prior epoch sharpens the next placement."""
    from .costmodel import SliceTopology, rank_layouts
    from .layouts import BENCH_MODELS, ModelSpec, enumerate_layouts

    if isinstance(model_cfg, str):
        model = BENCH_MODELS[model_cfg]
    elif isinstance(model_cfg, ModelSpec):
        model = model_cfg
    else:
        model = ModelSpec.from_arch(model_cfg)
    topo = slice_topology or SliceTopology(chips=8)
    layouts = enumerate_layouts(
        topo.chips, model, global_batch_size=global_batch_size,
        micro_batch_size=micro_batch_size,
    )
    if not layouts:
        raise ValueError(
            f"no valid layout of this model on {topo.chips} device(s) at "
            f"gbs={global_batch_size} mbs={micro_batch_size}"
        )
    ranked = rank_layouts(model, layouts, topo, calibration,
                          correction=correction)
    return ranked[0].layout, ranked


def rank_of_layout(
    model_cfg,
    layout,
    slice_topology=None,
    *,
    calibration=None,
) -> Tuple[int, int, "LayoutScore"]:
    """Where ``layout`` lands in the tuner's ranking of its own search
    space: ``(rank, space_size, score)``, 1-based. A layout outside the
    enumerated space (an MoE/LoRA dryrun arm) is scored directly and
    ranked by insertion. Used by the dryrun grid to annotate each arm
    with its tuner verdict."""
    from .costmodel import SliceTopology, rank_layouts, score_layout
    from .layouts import BENCH_MODELS, ModelSpec, enumerate_layouts

    if isinstance(model_cfg, str):
        model = BENCH_MODELS[model_cfg]
    elif isinstance(model_cfg, ModelSpec):
        model = model_cfg
    else:
        model = ModelSpec.from_arch(model_cfg)
    topo = slice_topology or SliceTopology(chips=layout.world)
    layouts = enumerate_layouts(
        topo.chips, model,
        global_batch_size=layout.global_batch_size,
        micro_batch_size=layout.micro_batch_size,
    )
    ranked = rank_layouts(model, layouts, topo, calibration)
    for i, s in enumerate(ranked):
        if s.layout.key() == layout.key():
            return i + 1, len(ranked), s
    score = score_layout(model, layout, topo, calibration)
    rank = 1 + sum(
        1 for s in ranked if s.predicted_step_s <= score.predicted_step_s
    )
    return rank, len(ranked) + 1, score
