"""Layout space: every valid (pp, dp, cp, mp, ...) placement of a model
on a chip count.

The dryrun grid (``__graft_entry__.dryrun_multichip``) hand-picks ~9
arms; the tuner instead enumerates EVERY factorization of the chip count
over the four mesh axes plus the schedule/optimizer knobs the grid
exercises (zero stage, interleaved virtual stages, TeraPipe token
slices, ring/ulysses context parallelism), and keeps exactly those that
pass the SAME validity rules the production config enforces — each
candidate is validated by constructing a real ``TopologyConfig``
(``topology/config.py``), so the tuner can never rank a layout the
trainer would reject, plus the model-shape divisibility rules the layer
stack imposes (heads per TP rank, layers per stage chunk, sequence per
token slice).

Pure host-side code; jax-bearing imports (the topology package pulls
jax) are deferred into the functions that need them.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The model shape the cost model prices. Mirrors the fields the
    FLOPs estimators read (models/transformer/utils/get_tflops.py — the
    parameter-count and PaLM appendix-B formulas are duplicated here so
    the tuner imports no jax-bearing package; equality with the
    originals is pinned by tests/core/test_tune/test_costmodel.py)."""

    hidden_size: int
    num_layers: int
    num_attention_heads: int
    num_kv_heads: int
    sequence_length: int
    vocab_size: int
    mlp_factor: float = 2.75
    glu: bool = True
    moe: bool = False

    @property
    def parameter_count(self) -> int:
        per_layer = 4 * self.hidden_size * self.hidden_size + (
            3 if self.glu else 2
        ) * int(self.hidden_size * self.hidden_size * self.mlp_factor)
        return self.num_layers * per_layer + self.vocab_size * self.hidden_size

    @property
    def flops_per_token(self) -> float:
        """PaLM appendix-B train FLOPs/token: 6N + 12 L H S."""
        return (
            6.0 * self.parameter_count
            + 12.0 * self.num_layers * self.hidden_size * self.sequence_length
        )

    @property
    def attention_flops_fraction(self) -> float:
        """Share of ``flops_per_token`` in the attention quadratic term —
        the part a token-sliced cache path re-prices."""
        return (
            12.0 * self.num_layers * self.hidden_size * self.sequence_length
            / self.flops_per_token
        )

    @classmethod
    def from_arch(cls, arch) -> "ModelSpec":
        """Build from anything with the transformer-architecture field
        names (a ``TransformerArchitectureConfig``, a plain dict, the
        audit's config objects)."""

        def get(name, default=None):
            if isinstance(arch, dict):
                return arch.get(name, default)
            return getattr(arch, name, default)

        mlp_type = get("mlp_type", "swiglu")
        mlp_type = getattr(mlp_type, "value", mlp_type)
        return cls(
            hidden_size=int(get("hidden_size")),
            num_layers=int(get("num_layers")),
            num_attention_heads=int(get("num_attention_heads")),
            num_kv_heads=int(
                get("attention_num_kv_heads", get("num_attention_heads"))
            ),
            sequence_length=int(get("sequence_length")),
            vocab_size=int(get("vocab_size")),
            mlp_factor=float(get("mlp_factor", 4.0)),
            glu=mlp_type == "swiglu",
            moe=mlp_type == "moe",
        )


# The bench arms (bench.py ``build``): heads = hidden // 128, kv heads =
# max(1, hidden // 512), seq 2048, swiglu 2.75 — kept in sync by the
# ModelSpec-vs-get_tflops pin test.
BENCH_MODELS = {
    "0.5b": ModelSpec(
        hidden_size=2048, num_layers=8, num_attention_heads=16,
        num_kv_heads=4, sequence_length=2048, vocab_size=32768,
        mlp_factor=2.75, glu=True,
    ),
    "1b": ModelSpec(
        hidden_size=2048, num_layers=20, num_attention_heads=16,
        num_kv_heads=4, sequence_length=2048, vocab_size=32768,
        mlp_factor=2.75, glu=True,
    ),
}


@dataclasses.dataclass(frozen=True)
class Layout:
    """One placement candidate: the mesh factorization plus the knobs the
    dryrun grid varies. ``sp`` follows the grid's own rule (Megatron SP
    whenever TP is on and context parallelism is off) rather than being
    a free axis — the repo never runs TP without it."""

    pp: int
    dp: int
    cp: int
    mp: int
    micro_batch_size: int
    gradient_accumulation_steps: int
    sp: bool = False
    cp_variant: str = "ring"
    zero_stage: int = 1
    vpp: int = 1
    token_slices: int = 1
    # set when this layout came from an mbs-ladder enumeration (several
    # candidates differ ONLY in micro_batch_size): the label then names
    # the mbs so ranked rows stay distinguishable. Off by default so
    # single-mbs labels — and the pinned tune golden — are unchanged.
    mbs_in_label: bool = False

    @property
    def world(self) -> int:
        return self.pp * self.dp * self.cp * self.mp

    @property
    def global_batch_size(self) -> int:
        return self.micro_batch_size * self.gradient_accumulation_steps * self.dp

    def key(self) -> Tuple:
        """Identity for matching a dryrun arm against the space."""
        return (
            self.pp, self.dp, self.cp, self.mp,
            self.cp_variant if self.cp > 1 else "-",
            self.zero_stage, self.vpp, self.token_slices,
        )

    @property
    def label(self) -> str:
        parts = [f"pp{self.pp}", f"dp{self.dp}"]
        if self.cp > 1:
            parts.append(f"cp{self.cp}:{self.cp_variant}")
        parts.append(f"mp{self.mp}")
        if self.sp:
            parts.append("sp")
        if self.mbs_in_label:
            parts.append(f"mbs{self.micro_batch_size}")
        parts.append(f"z{self.zero_stage}")
        if self.vpp > 1:
            parts.append(f"v{self.vpp}")
        if self.token_slices > 1:
            parts.append(f"ts{self.token_slices}")
        return "·".join(parts)

    def topology_dict(self) -> dict:
        """The exact dict ``TopologyConfig.from_dict`` (and the dryrun /
        trainer entrypoints) consume — the tuner's output IS a runnable
        config, not a description of one."""
        return {
            "world_size": self.world,
            "pipe_parallel_size": self.pp,
            "data_parallel_size": self.dp,
            "context_parallel_size": self.cp,
            "model_parallel_size": self.mp,
            "context_parallel_variant": self.cp_variant,
            "micro_batch_size": self.micro_batch_size,
            "gradient_accumulation_steps": self.gradient_accumulation_steps,
            "global_batch_size": self.global_batch_size,
            "pipe_virtual_size": self.vpp,
            "pipe_token_slices": self.token_slices,
            "sequence_parallel": self.sp,
        }

    def validate(self) -> Optional[str]:
        """None when a real ``TopologyConfig`` accepts this layout, else
        the rejection reason — the tuner reuses the production validity
        rules instead of reimplementing them."""
        from ..topology.config import TopologyConfig  # jax-bearing parent

        try:
            TopologyConfig.from_dict(self.topology_dict())
        except Exception as e:  # pydantic wraps the validator's asserts
            return str(e)
        return None


def _factorizations(n: int) -> Iterator[Tuple[int, int, int, int]]:
    """All ordered (pp, dp, cp, mp) with pp*dp*cp*mp == n."""
    divs = [d for d in range(1, n + 1) if n % d == 0]
    for pp in divs:
        for dp in [d for d in divs if (n // pp) % d == 0]:
            rem = n // (pp * dp)
            for cp in [d for d in divs if rem % d == 0]:
                yield pp, dp, cp, rem // cp


def _model_fits(model: ModelSpec, pp: int, dp: int, cp: int, mp: int,
                cp_variant: str, vpp: int, slices: int) -> bool:
    """Divisibility the layer stack imposes beyond TopologyConfig."""
    heads, kv = model.num_attention_heads, model.num_kv_heads
    if heads % mp or kv % mp:
        return False  # TP shards heads
    if model.num_layers % (pp * vpp):
        return False  # uniform stage (chunk) partition
    if cp > 1:
        if model.sequence_length % cp:
            return False
        if cp_variant == "ulysses" and (heads % cp or kv % cp):
            return False  # ulysses all-to-alls heads across cp
    if slices > 1 and model.sequence_length % slices:
        return False
    return True


def enumerate_layouts(
    n_devices: int,
    model: ModelSpec,
    global_batch_size: int,
    micro_batch_size: int,
    virtual_options: Sequence[int] = (2,),
    slice_options: Sequence[int] = (2,),
    mbs_ladder: Optional[Sequence[int]] = None,
) -> List[Layout]:
    """Every valid layout of ``model`` on ``n_devices`` at the given
    batch hierarchy. Candidates that any production rule rejects
    (TopologyConfig validation or layer-stack divisibility) are dropped;
    the result is deterministic and sorted by ``key()`` (then mbs).

    ``mbs_ladder`` additionally enumerates each listed micro-batch size
    alongside ``micro_batch_size`` (duplicates collapse): the global
    batch is fixed, so a smaller mbs means proportionally more
    accumulation steps — cheaper activation memory and a thinner
    pipeline bubble (more micro-batches fill the schedule), priced by
    the same cost model. Ladder candidates carry the mbs in their label
    so the ranked report stays readable; without a ladder labels (and
    the pinned golden) are byte-identical to before."""
    mbs_options = sorted({int(micro_batch_size), *(mbs_ladder or ())})
    ladder = len(mbs_options) > 1
    out: List[Layout] = []
    for mbs in mbs_options:
        if mbs < 1:
            raise ValueError(f"micro batch sizes must be >= 1, got {mbs}")
        for pp, dp, cp, mp in _factorizations(n_devices):
            if global_batch_size % (mbs * dp):
                continue
            gas = global_batch_size // (mbs * dp)
            sp = mp > 1 and cp == 1 and not model.moe
            cp_variants = ["ring", "ulysses"] if cp > 1 else ["ring"]
            zero_stages = [1] + ([3] if dp > 1 else [])
            schedules: List[Tuple[int, int]] = [(1, 1)]
            if pp > 1:
                schedules += [(v, 1) for v in virtual_options if v > 1]
                schedules += [(1, s) for s in slice_options if s > 1]
            for cpv in cp_variants:
                for zero in zero_stages:
                    for vpp, slices in schedules:
                        if not _model_fits(model, pp, dp, cp, mp, cpv,
                                           vpp, slices):
                            continue
                        layout = Layout(
                            pp=pp, dp=dp, cp=cp, mp=mp,
                            micro_batch_size=mbs,
                            gradient_accumulation_steps=gas, sp=sp,
                            cp_variant=cpv, zero_stage=zero, vpp=vpp,
                            token_slices=slices, mbs_in_label=ladder,
                        )
                        if layout.validate() is None:
                            out.append(layout)
    out.sort(key=lambda l: l.key() + (l.micro_batch_size,))
    return out
