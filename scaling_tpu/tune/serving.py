"""Serving-layout search: (mp, replicas, block_size, token_budget) points.

The training tuner (costmodel.py) answers "where do I place a TRAINING
step"; this module answers the serving twin: given ``D`` chips and a
model, how should the serving fleet slice them — how many model-parallel
shards per engine replica (mp), how many data-parallel replicas behind
the router (replicas = D / mp), what KV block size, and what per-tick
token budget? The scoring reuses the training tuner's machinery
wholesale (docs/TUNING.md):

- **compute**: a serving tick prices ``token_budget`` tokens at the
  inference FLOP rate (2 FLOPs per parameter per token — forward only,
  vs training's 6; plus the attention window term), divided over the
  replica's mp shards at the calibrated efficiency of the generation's
  peak. Small blocks pay a per-block streaming overhead in the paged
  kernel (one grid step per block: ``1 + PAGED_BLOCK_OVERHEAD /
  block_size``); large blocks pay internal fragmentation instead (a
  sequence wastes half a block on average), priced in memory.
- **comm**: mp > 1 costs the SAME Megatron activation all-reduces
  training's model axis pays — 2 per layer forward (no backward at
  serving) over the tick's activations — priced ICI-vs-DCN by the very
  ``link_for_axis`` rule the training tuner uses (the serving layout is
  a Layout with dp = replicas, so the mp axis's stride/domain math is
  identical).
- **memory**: bf16 params / mp + the sharded KV pool
  (``layers x 2 x pool_tokens x (kv/mp) x head x 2B``, fragmentation
  included) must fit the generation's HBM; infeasible points are
  dropped, not ranked.
- **calibration**: the analytic tick time is scaled by a measured
  factor from real serve run dirs (:class:`ServeCalibration` — mean
  ``serve.mixed``/``serve.decode`` span seconds vs the model's
  prediction for THAT run's engine shape, read from the serve-summary's
  ``engine`` facts), exactly like the training tuner's MFU calibration.

``python -m scaling_tpu.tune --serve`` ranks the space, pins a golden
(``tune/goldens/tune_serve_8dev_0.5b.json``), and ``--emit-config``
writes a dict ``serve bench --config`` runs directly.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .costmodel import (
    BF16,
    Calibration,
    LinkClass,
    SliceTopology,
    collective_seconds,
    link_for_axis,
)
from .layouts import Layout, ModelSpec

# paged-kernel streaming overhead: one grid step per KV block — fixed
# per-block cost (DMA issue, mask math) expressed in token-equivalents,
# so cost multiplies by (1 + OVERHEAD / block_size). Small blocks pack
# the pool tighter but pay more grid steps; the sweep prices the trade.
PAGED_BLOCK_OVERHEAD = 4.0

# steady-state KV residency per slot of token budget: the pool must hold
# the CONTEXTS of every in-flight sequence, not just the tick's new
# tokens. Derived from the engine defaults (num_slots * max context /
# token_budget at bench shapes); the emitted config scales num_blocks
# from it.
POOL_TOKENS_PER_BUDGET_TOKEN = 16.0

# generation -> usable HBM per chip (GiB); public cloud.google.com specs
HBM_GB = {
    "tpu_v4": 32.0,
    "tpu_v5e": 16.0,
    "tpu_v5p": 95.0,
    "tpu_v6e": 32.0,
}


@dataclasses.dataclass(frozen=True)
class ServingPoint:
    """One serving-layout candidate for ``mp * replicas`` chips."""

    mp: int
    replicas: int
    block_size: int
    token_budget: int
    num_slots: int = 8

    @property
    def world(self) -> int:
        return self.mp * self.replicas

    @property
    def label(self) -> str:
        return (f"mp{self.mp}·r{self.replicas}·bs{self.block_size}"
                f"·tb{self.token_budget}")

    def layout(self, mbs: int = 1) -> Layout:
        """The serving point as a training-tuner Layout (dp = replicas):
        what makes ``link_for_axis`` price the mp axis with the SAME
        stride/ICI-domain rules training placement uses."""
        return Layout(pp=1, dp=self.replicas, cp=1, mp=self.mp,
                      micro_batch_size=mbs, gradient_accumulation_steps=1)

    def to_config(self, model: Optional[ModelSpec] = None) -> dict:
        """A runnable serving config: the dict ``serve bench --config``
        consumes (and a deployment template for the real fleet)."""
        pool_tokens = int(self.token_budget * POOL_TOKENS_PER_BUDGET_TOKEN)
        num_blocks = max(2, pool_tokens // self.block_size + 1)
        cfg = {
            "mp": self.mp,
            "replicas": self.replicas,
            "block_size": self.block_size,
            "token_budget": self.token_budget,
            "num_slots": self.num_slots,
            "num_blocks": num_blocks,
        }
        if model is not None:
            cfg["model"] = {
                "hidden_size": model.hidden_size,
                "num_layers": model.num_layers,
                "num_kv_heads": model.num_kv_heads,
            }
        return cfg


def enumerate_serving_points(
    n_devices: int,
    model: ModelSpec,
    block_sizes: Sequence[int] = (8, 16, 32),
    token_budgets: Sequence[int] = (128, 256, 512),
    num_slots: int = 8,
) -> List[ServingPoint]:
    """Every (mp, replicas=D/mp, block_size, token_budget) the model
    shape admits: mp must divide the chip count AND the q/kv heads (the
    pool shards kv heads over the model axis — serve/kvcache.py raises
    on anything else, so the tuner never ranks an unbuildable point)."""
    points: List[ServingPoint] = []
    for mp in range(1, n_devices + 1):
        if n_devices % mp:
            continue
        if model.num_attention_heads % mp or model.num_kv_heads % mp:
            continue
        replicas = n_devices // mp
        for bs in block_sizes:
            for tb in token_budgets:
                points.append(ServingPoint(
                    mp=mp, replicas=replicas, block_size=bs,
                    token_budget=tb, num_slots=num_slots,
                ))
    points.sort(key=lambda p: (p.mp, p.block_size, p.token_budget))
    return points


def serve_flops_per_token(model: ModelSpec, avg_context: float) -> float:
    """Inference FLOPs per generated/prefilled token: 2 per parameter
    (one forward MAC each) plus the attention window reads —
    ``4 * layers * hidden * context`` (QK^T and PV over the cached
    context), the forward third of PaLM appendix-B's 12 L H S."""
    return (
        2.0 * model.parameter_count
        + 4.0 * model.num_layers * model.hidden_size * avg_context
    )


def predict_tick_seconds(
    model: ModelSpec,
    point: ServingPoint,
    topo: SliceTopology,
    calibration: Optional[Calibration] = None,
) -> Dict[str, float]:
    """Analytic seconds for ONE engine tick of ``token_budget`` tokens
    on one replica: compute over the mp shards + the mp activation
    all-reduces, the comm priced by the link class the slice topology
    assigns to the model axis (ICI inside a domain, DCN across)."""
    cal = calibration or Calibration.default()
    avg_context = point.token_budget * POOL_TOKENS_PER_BUDGET_TOKEN / (
        2.0 * point.num_slots
    )  # half the steady-state per-slot residency
    flops = point.token_budget * serve_flops_per_token(model, avg_context)
    rate = topo.peak_tflops * 1e12 * cal.compute_efficiency
    block_factor = 1.0 + PAGED_BLOCK_OVERHEAD / point.block_size
    compute_s = flops * block_factor / (rate * point.mp)
    comm_s = 0.0
    link: LinkClass = topo.ici
    if point.mp > 1:
        link = link_for_axis(point.layout(), topo, "model")
        # Megatron TP inference forward: 2 activation ARs per layer over
        # the tick's activations (no backward at serving)
        count = 2 * model.num_layers
        payload = count * point.token_budget * model.hidden_size * BF16
        comm_s = collective_seconds(
            "all-reduce", float(payload), count, point.mp, link
        )
    return {
        "compute_s": compute_s,
        "comm_s": comm_s,
        "tick_s": compute_s + comm_s,
        "link": link.name,
    }


def serving_memory_gb(model: ModelSpec, point: ServingPoint) -> float:
    """Per-chip HBM: bf16 params / mp + the kv-head-sharded pool.
    Fragmentation: each in-flight sequence wastes ~half a block."""
    params = model.parameter_count * BF16 / point.mp
    head = model.hidden_size // model.num_attention_heads
    pool_tokens = point.token_budget * POOL_TOKENS_PER_BUDGET_TOKEN
    pool_tokens += point.num_slots * point.block_size / 2.0  # fragmentation
    pool = (
        model.num_layers * 2.0 * pool_tokens
        * (model.num_kv_heads / point.mp) * head * BF16
    )
    return (params + pool) / 1e9


@dataclasses.dataclass
class ServingScore:
    point: ServingPoint
    tokens_per_s: float
    tick_s: float
    compute_s: float
    comm_s: float
    memory_gb: float
    link: str

    def to_dict(self) -> dict:
        return {
            "label": self.point.label,
            "mp": self.point.mp,
            "replicas": self.point.replicas,
            "block_size": self.point.block_size,
            "token_budget": self.point.token_budget,
            "tokens_per_s": round(self.tokens_per_s, 1),
            "tick_s": round(self.tick_s, 6),
            "compute_s": round(self.compute_s, 6),
            "comm_s": round(self.comm_s, 6),
            "memory_gb_per_chip": round(self.memory_gb, 3),
            "link": self.link,
        }


def score_serving_point(
    model: ModelSpec,
    point: ServingPoint,
    topo: SliceTopology,
    calibration: Optional[Calibration] = None,
    serve_calibration: Optional["ServeCalibration"] = None,
) -> Optional[ServingScore]:
    """Fleet tokens/s for one point, or None when it does not fit the
    generation's HBM (an unrankable point, not a slow one)."""
    memory = serving_memory_gb(model, point)
    if memory > HBM_GB.get(topo.generation, 16.0):
        return None
    pred = predict_tick_seconds(model, point, topo, calibration)
    tick_s = pred["tick_s"]
    if serve_calibration is not None:
        tick_s *= serve_calibration.factor
    tokens_per_s = point.replicas * point.token_budget / tick_s
    return ServingScore(
        point=point, tokens_per_s=tokens_per_s, tick_s=tick_s,
        compute_s=pred["compute_s"], comm_s=pred["comm_s"],
        memory_gb=memory, link=pred["link"],
    )


def rank_serving_points(
    model: ModelSpec,
    points: Sequence[ServingPoint],
    topo: SliceTopology,
    calibration: Optional[Calibration] = None,
    serve_calibration: Optional["ServeCalibration"] = None,
) -> List[ServingScore]:
    scored = [
        s for p in points
        if (s := score_serving_point(model, p, topo, calibration,
                                     serve_calibration)) is not None
    ]
    scored.sort(key=lambda s: (-s.tokens_per_s, s.point.label))
    return scored


# ---------------------------------------------------------- calibration
@dataclasses.dataclass(frozen=True)
class ServeCalibration:
    """Measured-vs-analytic tick-time factor from real serve run dirs.

    A serve bench run leaves ``serve.mixed`` / ``serve.decode`` spans
    (the device tick) and a serve-summary carrying the engine SHAPE it
    ran (``engine``: mp/num_slots/block_size/token_budget...). The
    factor is measured mean tick seconds over the analytic prediction
    for that exact shape — applied multiplicatively to every candidate,
    the serving twin of the training tuner's
    prediction-vs-span-measured loop (docs/TUNING.md)."""

    factor: float
    source: str
    ticks: int = 0

    @classmethod
    def identity(cls) -> "ServeCalibration":
        return cls(1.0, "identity")

    @classmethod
    def from_run_dir(cls, run_dir, model: ModelSpec,
                     topo: SliceTopology,
                     calibration: Optional[Calibration] = None,
                     ) -> Optional["ServeCalibration"]:
        """None when the run dir has no serve spans or no engine facts
        in its serve-summary (pre-fleet bench)."""
        from ..obs.report import load_run_dir  # stdlib-only

        data = load_run_dir(run_dir)
        spans = [
            sp for sp in data.spans
            if sp.get("span") in ("serve.mixed", "serve.decode")
            and sp.get("dur_s") is not None
        ]
        summaries = [
            e for e in data.lifecycle if e.get("event") == "serve-summary"
        ]
        if not spans or not summaries:
            return None
        eng = summaries[-1].get("engine")
        if not isinstance(eng, dict):
            return None
        try:
            point = ServingPoint(
                mp=int(eng.get("mp", 1)),
                replicas=int(eng.get("replicas", 1)),
                block_size=int(eng["block_size"]),
                token_budget=int(eng["token_budget"]),
                num_slots=int(eng["num_slots"]),
            )
        except (KeyError, TypeError, ValueError):
            return None
        measured = sum(float(sp["dur_s"]) for sp in spans) / len(spans)
        predicted = predict_tick_seconds(
            model, point, topo, calibration
        )["tick_s"]
        if predicted <= 0 or measured <= 0:
            return None
        return cls(
            factor=measured / predicted,
            source=f"serve-spans:{run_dir}",
            ticks=len(spans),
        )

    def to_dict(self) -> dict:
        return {
            "factor": round(self.factor, 6),
            "source": self.source,
            "ticks": self.ticks,
        }


# -------------------------------------------------------------- golden
GOLDEN_DIR = Path(__file__).resolve().parent / "goldens"
GOLDEN_RTOL = 0.02


def serve_golden_path(devices: int, model_name: str) -> Path:
    return GOLDEN_DIR / f"tune_serve_{devices}dev_{model_name}.json"


def check_serve_golden(payload: dict, path: Path) -> List[str]:
    """Ranking drift vs the pinned serving golden (labels exact,
    tokens/s within the band) — mirrors the training tuner's pin."""
    import json

    if not path.is_file():
        return [f"no serving golden at {path} (run --repin-golden)"]
    golden = json.loads(path.read_text())
    drift: List[str] = []
    g = [(r["label"], r["tokens_per_s"]) for r in golden["ranked"]]
    c = [(r["label"], r["tokens_per_s"]) for r in payload["ranked"]]
    if [l for l, _ in g] != [l for l, _ in c]:
        drift.append(
            f"serving ranking changed: golden {[l for l, _ in g][:4]}... "
            f"!= current {[l for l, _ in c][:4]}..."
        )
    for (gl, gs), (cl, cs) in zip(g, c):
        if gl == cl and gs and abs(cs - gs) > GOLDEN_RTOL * gs:
            drift.append(
                f"{gl}: tokens/s {gs:.1f} -> {cs:.1f} "
                f"(> {GOLDEN_RTOL:.0%} band)"
            )
    return drift


# ----------------------------------------------------------- placement
@dataclasses.dataclass(frozen=True)
class HostCapacity:
    """One machine of the serving fleet as the placement axis sees it:
    ``slots`` replica processes at most, ``hbm_gb`` usable accelerator
    memory for ALL of them together."""

    host_id: int
    hostname: str
    slots: int
    hbm_gb: float = float("inf")


class PlacementPlan:
    """WHERE the next replica may spawn: per-host slot + HBM feasibility
    over a hostsfile-shaped fleet. Pure policy, no I/O and no clocks —
    the serve bench consults it at spawn time (initial placement,
    relaunch pinning falls outside: a relaunch reuses its recorded
    host), and ``tune --serve --serve-hostsfile`` publishes the same
    math as the payload's ``placement`` table so the ranking and the
    bench agree on what fits."""

    def __init__(self, hosts: Sequence[HostCapacity],
                 per_replica_gb: float = 0.0):
        if not hosts:
            raise ValueError("a placement plan needs at least one host")
        ids = [h.host_id for h in hosts]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate host ids {ids}")
        self.hosts = list(hosts)
        self.per_replica_gb = float(per_replica_gb)

    @classmethod
    def from_pool(cls, pool: Dict[str, int],
                  per_replica_gb: float = 0.0,
                  hbm_gb: float = float("inf")) -> "PlacementPlan":
        """From a runner resource pool (``runner.get_resource_pool`` —
        ordered {hostname: slots}); host ids follow hostsfile order."""
        return cls(
            [
                HostCapacity(i, hostname, max(int(slots), 1), hbm_gb)
                for i, (hostname, slots) in enumerate(pool.items())
            ],
            per_replica_gb=per_replica_gb,
        )

    def host(self, host_id: int) -> HostCapacity:
        for h in self.hosts:
            if h.host_id == host_id:
                return h
        raise KeyError(f"no host {host_id} in the placement plan")

    def add_host(self, hostname: str, slots: int = 1,
                 hbm_gb: float = float("inf")) -> HostCapacity:
        """Admit a LEASED host into the plan mid-run (the elastic
        capacity arbiter borrowed it from training —
        ``resilience.capacity``): next free id, immediately eligible
        for ``next_host`` placement. A hostname already planned gains
        slots instead of a duplicate row (a second lease of the same
        machine's remaining chips)."""
        for i, h in enumerate(self.hosts):
            if h.hostname == hostname:
                grown = HostCapacity(
                    h.host_id, h.hostname, h.slots + max(int(slots), 1),
                    h.hbm_gb,
                )
                self.hosts[i] = grown
                return grown
        hid = max((h.host_id for h in self.hosts), default=-1) + 1
        cap = HostCapacity(hid, hostname, max(int(slots), 1), hbm_gb)
        self.hosts.append(cap)
        return cap

    def remove_host(self, hostname: str, slots: Optional[int] = None
                    ) -> None:
        """Give a leased host back (reclaim completed): drop its row, or
        shrink it by ``slots`` when only part of the machine was leased.
        Unknown hostnames are a no-op — release is idempotent."""
        for i, h in enumerate(self.hosts):
            if h.hostname != hostname:
                continue
            if slots is not None and h.slots > slots:
                self.hosts[i] = HostCapacity(
                    h.host_id, h.hostname, h.slots - slots, h.hbm_gb
                )
            else:
                del self.hosts[i]
            return

    def hostname(self, host_id: int) -> str:
        return self.host(host_id).hostname

    def feasible(self, host_id: int, count: int) -> bool:
        """Can host ``host_id``, already running ``count`` replicas,
        take one more? Slot-bound AND memory-bound: ``count + 1``
        replicas' HBM must fit the host's budget."""
        h = self.host(host_id)
        if count >= h.slots:
            return False
        return (count + 1) * self.per_replica_gb <= h.hbm_gb

    def next_host(self, counts: Dict[int, int]) -> Optional[int]:
        """The least-loaded feasible host (lowest id breaks ties), or
        None when no host can take another replica. ``counts`` maps
        host_id -> replicas currently placed there (missing = 0)."""
        best = None
        for h in self.hosts:
            count = int(counts.get(h.host_id, 0))
            if not self.feasible(h.host_id, count):
                continue
            if best is None or count < best[0]:
                best = (count, h.host_id)
        return None if best is None else best[1]

    def initial_assignment(self, n: int) -> List[int]:
        """Host ids for replicas ``0..n-1`` — least-loaded round-robin
        through ``next_host`` so the initial spread and the autoscale
        spread follow the SAME rule. Raises when the fleet cannot hold
        ``n`` replicas (better a loud launch error than a worker that
        OOMs or oversubscribes its host mid-run)."""
        counts: Dict[int, int] = {}
        out: List[int] = []
        for r in range(n):
            hid = self.next_host(counts)
            if hid is None:
                cap = sum(h.slots for h in self.hosts)
                raise ValueError(
                    f"placement infeasible: replica {r} of {n} has no "
                    f"host with a free slot that fits "
                    f"{self.per_replica_gb:.2f} GB/replica "
                    f"(fleet capacity {cap} slot(s) over "
                    f"{len(self.hosts)} host(s))"
                )
            counts[hid] = counts.get(hid, 0) + 1
            out.append(hid)
        return out

    def to_payload(self) -> List[dict]:
        """The tune payload's ``placement`` table: per-host capacity in
        replicas, both slot- and HBM-bound."""
        rows = []
        for h in self.hosts:
            if self.per_replica_gb > 0 and h.hbm_gb != float("inf"):
                mem_cap = int(h.hbm_gb // self.per_replica_gb)
            else:
                mem_cap = None
            rows.append({
                "host_id": h.host_id,
                "hostname": h.hostname,
                "slots": h.slots,
                "hbm_gb": (
                    None if h.hbm_gb == float("inf")
                    else round(h.hbm_gb, 2)
                ),
                "max_replicas_by_memory": mem_cap,
                "max_replicas": (
                    h.slots if mem_cap is None else min(h.slots, mem_cap)
                ),
            })
        return rows
