from .config import LaunchConfig, RunnerConfig, RunnerType
from .runner import get_resource_pool, initialize_distributed, runner_main
from .supervise import supervise_main

__all__ = [
    "LaunchConfig",
    "RunnerConfig",
    "RunnerType",
    "get_resource_pool",
    "initialize_distributed",
    "runner_main",
    "supervise_main",
]
