"""Multi-host launcher.

``runner_main`` mirrors the reference's entry
(reference: src/scaling/core/runner/runner.py:118-266): resolve a resource
pool from hostsfile/hosts, pick the coordinator, and start one worker per
host with the config riding along as a base64 payload. The per-host side
(``initialize_distributed``) is TPU-native: ``jax.distributed.initialize``
replaces the per-GPU process spawn — JAX owns all local devices in one
process (reference contrast: launch.py:73-161 spawns one proc per GPU).
"""

from __future__ import annotations

import base64
import json
import os
import subprocess
import sys
from typing import Any, Dict, List, Optional

from .config import LaunchConfig, RunnerConfig


def get_resource_pool(config: RunnerConfig) -> Dict[str, int]:
    """hostsfile/hosts -> ordered {hostname: device_slots}
    (reference: runner.py:118-196)."""
    pool: Dict[str, int] = {}
    if config.hostsfile is not None:
        for line in open(config.hostsfile).read().splitlines():
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = config.default_gpu_count
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            pool[host] = slots
    elif config.hosts:
        for host in config.hosts:
            pool[host] = config.default_gpu_count
    else:
        pool["localhost"] = config.default_gpu_count
    return pool


def encode_payload(payload: Any) -> str:
    return base64.urlsafe_b64encode(json.dumps(payload).encode()).decode()


def runner_main(config: RunnerConfig, payload: Any) -> int:
    """Launch ``config.script`` on every host in the pool. On a single host
    this just execs the script in-process-count 1; multi-host uses ssh."""
    pool = get_resource_pool(config)
    hosts = list(pool)
    master_addr = config.master_addr or hosts[0]
    num_processes = len(hosts)
    encoded = encode_payload(payload)

    procs: List[subprocess.Popen] = []
    for process_id, host in enumerate(hosts):
        env_exports = {
            "MASTER_ADDR": master_addr,
            "MASTER_PORT": str(config.master_port),
            "WORLD_SIZE": str(sum(pool.values())),
            "RANK": str(process_id),
            "LOCAL_SLOT": "0",
            "JAX_NUM_PROCESSES": str(num_processes),
            "JAX_PROCESS_ID": str(process_id),
        }
        script = config.script or "scaling_tpu.models.transformer.train"
        cmd = [sys.executable, "-u", "-m", script, f"--payload={encoded}"]
        if host in ("localhost", "127.0.0.1") and num_processes == 1:
            procs.append(subprocess.Popen(cmd, env={**os.environ, **env_exports}))
        else:
            exports = " ".join(f"{k}={v}" for k, v in env_exports.items())
            ssh_cmd = ["ssh", host, f"cd {os.getcwd()} && {exports} {' '.join(cmd)}"]
            procs.append(subprocess.Popen(ssh_cmd))

    # babysit: if any worker dies non-zero, kill the rest
    # (reference: launch.py:125-161)
    exit_code = 0
    try:
        while procs:
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                if ret != 0:
                    exit_code = ret
                    for other in procs:
                        other.terminate()
            import time

            time.sleep(0.2)
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        exit_code = 130
    return exit_code


def initialize_distributed(launch_config: Optional[LaunchConfig] = None) -> None:
    """Per-host bootstrap: joins the jax.distributed rendezvous when a
    multi-process launch is detected; no-op single host."""
    num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    import jax

    lc = launch_config or LaunchConfig.from_launcher_args()
    jax.distributed.initialize(
        coordinator_address=f"{lc.master_addr}:{lc.master_port}",
        num_processes=num_processes,
        process_id=int(os.environ.get("JAX_PROCESS_ID", str(lc.global_rank))),
    )
