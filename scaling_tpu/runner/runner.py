"""Multi-host launcher.

``runner_main`` mirrors the reference's entry
(reference: src/scaling/core/runner/runner.py:118-266): resolve a resource
pool from hostsfile/hosts, pick the coordinator, and start one worker per
host with the config riding along as a base64 payload. The per-host side
(``initialize_distributed``) is TPU-native: ``jax.distributed.initialize``
replaces the per-GPU process spawn — JAX owns all local devices in one
process (reference contrast: launch.py:73-161 spawns one proc per GPU).
"""

from __future__ import annotations

import base64
import json
import os
import shlex
import subprocess
import sys
from typing import Any, Dict, List, Optional

from .config import LaunchConfig, RunnerConfig, RunnerType


def get_resource_pool(config: RunnerConfig) -> Dict[str, int]:
    """hostsfile/hosts -> ordered {hostname: device_slots}
    (reference: runner.py:118-196).

    Hostsfile hygiene: blank lines and ``#`` comments (whole-line or
    trailing) are ignored; a duplicate hostname is a hard error — the
    silent last-entry-wins alternative launches the wrong world size
    and strands the rendezvous."""
    pool: Dict[str, int] = {}
    if config.hostsfile is not None:
        from pathlib import Path

        from ..resilience.guards import retry_io

        hosts_text = retry_io(
            Path(config.hostsfile).read_text,
            what=f"hostsfile read {config.hostsfile!r}",
        )
        for lineno, raw in enumerate(hosts_text.splitlines(), start=1):
            line = raw.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            if host in pool:
                raise ValueError(
                    f"duplicate hostname {host!r} at line {lineno} of "
                    f"hostsfile {config.hostsfile}: each host must appear "
                    "once (merge its slots= onto the first entry)"
                )
            slots = config.default_gpu_count
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            pool[host] = slots
    elif config.hosts:
        for host in config.hosts:
            if host in pool:
                raise ValueError(
                    f"duplicate hostname {host!r} in hosts list: each "
                    "host must appear once"
                )
            pool[host] = config.default_gpu_count
    else:
        pool["localhost"] = config.default_gpu_count
    return pool


def encode_payload(payload: Any) -> str:
    return base64.urlsafe_b64encode(json.dumps(payload).encode()).decode()


def build_worker_command(
    config: RunnerConfig, env_exports: Dict[str, str], encoded_payload: str
) -> List[str]:
    """The argv one worker runs (before any ssh wrapping) — factored out so
    the docker assembly is testable without a daemon (reference command
    assembly: runner.py:41-115).

    Docker mode mirrors the reference's: env rides in ``--env`` flags
    (PYTHON* keys skipped — the container has its own interpreter paths),
    bind mounts carry code/data, ``--privileged --network=host --ipc=host``
    give the container the TPU devices and the rendezvous network."""
    script = config.script or "scaling_tpu.models.transformer.train"
    if config.runner_type == RunnerType.PDSH_DOCKER:
        dc = config.docker_config
        if dc is None or not dc.docker_container:
            raise ValueError(
                "runner_type=pdsh_docker needs docker_config.docker_container"
            )
        cmd = ["sudo"] if dc.docker_sudo else []
        cmd += ["docker", "run", "--rm", "--privileged",
                "--network=host", "--ipc=host"]
        for key, val in env_exports.items():
            if key.lower().startswith("python"):
                continue
            cmd += ["--env", f"{key}={val}"]
        for host_dir, container_dir in dc.docker_mounts or []:
            cmd += ["-v", f"{host_dir}:{container_dir}"]
        cmd += list(dc.docker_args)
        cmd += [dc.docker_container, "python", "-u", "-m", script,
                f"--payload={encoded_payload}"]
        return cmd
    return [sys.executable, "-u", "-m", script, f"--payload={encoded_payload}"]


# the one definition of "local" — spawn/teardown/downsize all consult it
LOCAL_HOSTS = ("localhost", "127.0.0.1")


def is_local_pool(pool) -> bool:
    """True when every host of the pool (any hostname iterable) is this
    machine — the mode where slots expand into local worker processes."""
    return all(h in LOCAL_HOSTS for h in pool)


def plan_workers(pool: Dict[str, int]) -> List[tuple]:
    """``(host, slot)`` per worker process. All-localhost pools expand
    slots into local worker processes (each claiming its own device slot
    via LOCAL_SLOT/local_device_ids); remote hosts get one process each,
    owning all local devices."""
    if is_local_pool(pool):
        # the reference's pdsh-on-localhost mode (tests/core/test_runner
        # exercises a real multi-process rendezvous this way)
        return [
            (host, slot)
            for host, slots in pool.items()
            for slot in range(max(slots, 1))
        ]
    return [(host, 0) for host in pool]


def worker_env(
    pool: Dict[str, int],
    workers: List[tuple],
    process_id: int,
    master_addr: str,
    master_port: int,
) -> Dict[str, str]:
    """The launch-contract env one worker receives (LaunchConfig reads
    these back on the other side)."""
    host, slot = workers[process_id]
    local_workers = sum(1 for hh, _ in workers if hh == host)
    return {
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(master_port),
        # total device slots, NOT process count (LaunchConfig contract)
        "WORLD_SIZE": str(sum(max(s, 1) for s in pool.values())),
        "RANK": str(process_id),
        "LOCAL_SLOT": str(slot),
        "LOCAL_WORLD_SIZE": str(local_workers),
        "JAX_NUM_PROCESSES": str(len(workers)),
        "JAX_PROCESS_ID": str(process_id),
    }


def ssh_wrap(
    host: str,
    cmd: List[str],
    env_exports: Dict[str, str],
    cwd: Optional[str] = None,
) -> List[str]:
    """The ssh argv that runs ``cmd`` on ``host``: cd into ``cwd``
    (default: this process's, assumed shared-FS-visible like the rest of
    the launch contract), export the env inline, exec the quoted argv.
    One definition shared by the training launcher and the serving
    fleet's remote replica spawn — the wrapping is where quoting bugs
    live, so it exists exactly once."""
    quoted = " ".join(shlex.quote(a) for a in cmd)
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in env_exports.items()
    )
    wd = shlex.quote(str(cwd or os.getcwd()))
    remote = f"cd {wd} && {exports} {quoted}" if exports \
        else f"cd {wd} && {quoted}"
    return ["ssh", host, remote]


def spawn_worker(
    config: RunnerConfig,
    host: str,
    env_exports: Dict[str, str],
    encoded_payload: str,
) -> subprocess.Popen:
    """Start one worker process (local exec or ssh-wrapped)."""
    from ..resilience.faults import get_fault_plan

    get_fault_plan().fire("runner.worker.spawn")
    cmd = build_worker_command(config, env_exports, encoded_payload)
    docker = config.runner_type == RunnerType.PDSH_DOCKER
    if host in LOCAL_HOSTS:
        return subprocess.Popen(cmd, env={**os.environ, **env_exports})
    if docker:
        # env already rides inside the docker argv; no cd — the
        # container's workdir/mounts define the code location
        quoted = " ".join(shlex.quote(a) for a in cmd)
        return subprocess.Popen(["ssh", host, quoted])
    return subprocess.Popen(ssh_wrap(host, cmd, env_exports))


def runner_main(config: RunnerConfig, payload: Any) -> int:
    """Launch ``config.script`` across the resource pool.

    With ``config.supervise`` the workers run under the heartbeat
    supervisor (:mod:`.supervise`): dead/hung-host detection, clean
    teardown of survivors, bounded relaunch with a fresh coordinator
    epoch. Without it, the classic babysit loop below: if any worker
    dies non-zero, kill the rest."""
    if config.supervise:
        from .supervise import supervise_main

        return supervise_main(config, payload)
    pool = get_resource_pool(config)
    workers = plan_workers(pool)
    master_addr = config.master_addr or list(pool)[0]
    encoded = encode_payload(payload)

    procs: List[subprocess.Popen] = []
    for process_id, (host, _slot) in enumerate(workers):
        env_exports = worker_env(
            pool, workers, process_id, master_addr, config.master_port
        )
        procs.append(spawn_worker(config, host, env_exports, encoded))

    # babysit: if any worker dies non-zero, kill the rest
    # (reference: launch.py:125-161)
    from ..obs import span
    from ..resilience.faults import get_fault_plan

    exit_code = 0
    try:
        while procs:
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                if ret != 0:
                    exit_code = ret
                    get_fault_plan().fire("runner.worker.kill")
                    with span("runner.teardown", rc=ret):
                        for other in procs:
                            other.terminate()
            import time

            time.sleep(0.2)
    except KeyboardInterrupt:
        get_fault_plan().fire("runner.worker.kill")
        with span("runner.teardown", rc=130):
            for p in procs:
                p.terminate()
        exit_code = 130
    return exit_code


def initialize_distributed(launch_config: Optional[LaunchConfig] = None) -> None:
    """Per-host bootstrap: joins the jax.distributed rendezvous when a
    multi-process launch is detected; no-op single process.

    When several workers share one host (slot expansion), each claims only
    its own slot's device via ``local_device_ids`` — without this every
    process would try to own all local chips and libtpu would abort."""
    num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    import jax

    lc = launch_config or LaunchConfig.from_launcher_args()
    kwargs = {}
    platforms = (jax.config.jax_platforms or "") + os.environ.get("JAX_PLATFORMS", "")
    if int(os.environ.get("LOCAL_WORLD_SIZE", "1")) > 1 and "cpu" not in platforms:
        # accelerator hosts: each co-located worker claims only its slot's
        # chip; virtual CPU devices are per-process and never collide
        kwargs["local_device_ids"] = [lc.local_slot]
    jax.distributed.initialize(
        coordinator_address=f"{lc.master_addr}:{lc.master_port}",
        num_processes=num_processes,
        process_id=int(os.environ.get("JAX_PROCESS_ID", str(lc.global_rank))),
        **kwargs,
    )
