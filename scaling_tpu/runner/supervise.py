"""Multi-host supervisor: heartbeat monitoring, teardown, bounded relaunch.

Mesh-style SPMD makes every host a single point of failure: one
reclaimed machine leaves the other N-1 blocked inside a collective that
will never complete, burning pod-hours until a human notices. The
supervisor is the per-pod half of the resilience gate (the per-process
half is :mod:`scaling_tpu.resilience`): it launches the workers of one
*coordinator epoch*, watches their exit codes and control-plane
heartbeats, and on a dead or hung host

1. raises the ``abort`` broadcast flag so survivors waiting at any
   barrier exit within seconds instead of the full barrier timeout,
2. SIGTERMs the survivors, escalating to SIGKILL after a grace period
   (a host truly wedged inside an XLA collective ignores SIGTERM),
3. relaunches the whole rendezvous as a fresh epoch — new control-plane
   directory (no stale arrivals), new coordinator port (the dead
   coordinator's socket may linger in TIME_WAIT) — under a bounded
   exponential-backoff restart budget.

Relaunched workers resume exactly like ``run_with_resume`` does: the
training script points ``load_dir`` at its ``save_dir`` and restores the
newest checkpoint that passes integrity verification, so the resumed
loss trajectory is the uninterrupted one (the cross-host commit barrier
guarantees no mixed-step ``latest`` exists to restore from).

SIGTERM to the supervisor is relayed as SIGTERM to every worker (not a
direct flag write — see :func:`_relay_sigterm`): the workers' handlers
run the coordinated-preemption protocol, every host saves at the same
step boundary, exits 0, and the epoch counts as clean — no relaunch.

**Elastic downsizing** (``runner.downsize_after``, docs/RESILIENCE.md
"Elastic resharding"): when the SAME capacity keeps dying — a reclaimed
slice that is not coming back — retrying at full size burns the whole
restart budget on a recoverable failure. After ``downsize_after``
consecutive failed epochs the supervisor instead drops the lost hosts
from the worker plan, replans the layout for the surviving slots
(``tune.best_layout`` when ``runner.downsize_model`` names a model —
the new layout is picked by comm cost, ATP arxiv 2301.08658 /
Megatron-LM arxiv 2104.04473 — else a plain world shrink), rewrites the
payload topology when one rides along, emits a ``downsize`` event on
the obs rails, and relaunches: the workers resume through
reshard-on-restore (``resilience.reshard``). The restart budget resets
per world size.

**Elastic upsizing** (``runner.upsize_after``, docs/RESILIENCE.md
"Elastic capacity"): restored or standby capacity announces itself on
the control root's capacity channel (:mod:`..resilience.capacity`);
after ``upsize_after`` consecutive healthy observations the supervisor
drains the pod at a step boundary through the coordinated-preemption
save, replans the layout over the LARGER host list, and relaunches —
reshard-on-restore grows the mesh, consumed-samples carry over
skip/repeat-free, and the restart budget re-baselines just as downsize
does. With ``runner.arbitrate`` the same channel carries train<->serve
leases: sustained serving-fleet pressure borrows a host from training
(drain, downsize, journaled lease grant), sustained idle returns it
(the fleet drains its replicas, training upsizes).

Every transition lands as a structured event (``logger.log_event``):
``epoch-start``, ``host-dead``, ``teardown-complete``, ``relaunch``,
``preempt-relay``, ``epoch-clean-exit``, ``epoch-stalled``,
``downsize``, ``upsize``, ``capacity-drain``, ``capacity-lease``,
``capacity-reclaim``, ``give-up``.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from ..logging import logger
from ..obs import derive_trace_id, span, trace_context
from ..resilience.capacity import (
    ArbitrationPolicy,
    CapacityChannel,
    CapacityManager,
    SupervisorCapacity,
)
from ..resilience.controlplane import (
    ABORT_FLAG,
    ENV_CONTROL_DIR,
    ENV_COORD_EPOCH,
    ENV_HOST_ID,
    ENV_NUM_HOSTS,
    PREEMPT_FLAG,
    STALL_FLAG,
    FileControlPlane,
    straggler_table,
)
from .config import RunnerConfig
from .runner import (
    LOCAL_HOSTS,
    encode_payload,
    get_resource_pool,
    is_local_pool,
    plan_workers,
    spawn_worker,
    worker_env,
)


def classify_workers(
    exit_codes: List,
    heartbeats: Dict,
    *,
    heartbeat_timeout_s: float,
    startup_grace_s: float,
    epoch_elapsed_s: float,
    now: float,
) -> Dict[str, List[int]]:
    """Split one epoch's workers into dead / hung / alive.

    *dead*: exited non-zero (a SIGKILL shows as a negative code).
    *hung*: still running but the newest heartbeat is stale (or absent,
    or still ``starting``) AND the startup grace has passed. The grace
    suppresses ALL staleness verdicts, not just missing first
    heartbeats: a host can legitimately go silent for minutes inside
    the cold jit compile of its first step — after it already published
    ``starting`` and a ``barrier:step-0`` refresh — and that window is
    exactly what ``startup_grace_s`` budgets for. A worker whose last
    heartbeat says ``done`` or ``preempted`` is winding down, never
    hung. Pure function so the detection policy is unit-testable
    without spawning anything."""
    dead: List[int] = []
    hung: List[int] = []
    alive: List[int] = []
    for host, rc in enumerate(exit_codes):
        if rc is not None:
            if rc != 0:
                dead.append(host)
            continue  # exited 0: finished/preempted, not alive, not dead
        hb = heartbeats.get(host)
        # no special case for 'starting': a FRESH 'starting' heartbeat
        # past the grace is a host demonstrably alive (e.g. a restore
        # that outlasts the grace, still checking in) — only age makes
        # it stale, same as any other non-terminal status
        stale = (
            hb is None
            or (
                hb.status not in ("done", "preempted")
                and hb.age(now) > heartbeat_timeout_s
            )
        )
        if stale:
            (hung if epoch_elapsed_s > startup_grace_s else alive).append(host)
        else:
            alive.append(host)
    return {"dead": dead, "hung": hung, "alive": alive}


def restart_backoff(attempt: int, base_s: float,
                    cap_s: float = 60.0) -> float:
    """Relaunch delay for restart ``attempt`` (1-based): exponential
    from ``base_s``, capped — the ONE backoff curve every supervisor in
    the tree uses (trainer relaunches here, serving replica relaunches
    in ``serve.replica_proc``), so a chaos drill's restart timeline
    reads the same in both."""
    return min(base_s * (2 ** (max(attempt, 1) - 1)), cap_s)


def _signal_local(p: subprocess.Popen, sig: str) -> None:
    """SIGTERM/SIGKILL a local worker Popen, logging instead of raising
    (signal delivery races process exit benignly)."""
    try:
        (p.terminate if sig == "TERM" else p.kill)()
    except OSError as e:
        logger.warning(f"SIG{sig} to worker pid {p.pid} failed: {e!r}")


def remote_pkill(host: str, marker: str, sig: str) -> None:
    """Signal a remote host's processes matching ``marker`` via ssh pkill.

    The local Popen for an ssh-launched worker is only the ssh client —
    signalling it does not reach the remote process. ``marker`` must be
    a pattern unique to the processes being signalled (the training
    supervisor uses its launch's payload prefix; the serving fleet uses
    a replica's per-spawn config path)."""
    try:
        r = subprocess.run(
            ["ssh", host, f"pkill -{sig} -f -- {marker}"],
            timeout=30, capture_output=True,
        )
        # pkill 1 = pattern matched nothing (workers already gone) —
        # fine; anything else (pkill 2/3, ssh 255 transport failure)
        # means the remote workers may still be alive
        if r.returncode not in (0, 1):
            logger.warning(
                f"remote SIG{sig} on {host} failed rc={r.returncode}: "
                f"{getattr(r, 'stderr', b'')!r}"
            )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning(f"remote SIG{sig} on {host} failed: {e!r}")


def _remote_pkill(host: str, encoded: str, sig: str) -> None:
    """The training launch's marker: its unique base64 payload prefix —
    shell- and regex-safe by construction, and 48 chars keeps clear of
    base64 padding while staying unique per job."""
    remote_pkill(host, f"--payload={encoded[:48]}", sig)


def _relay_sigterm(
    procs: List[subprocess.Popen], workers: List[tuple], encoded: str
) -> None:
    """Supervisor-initiated drain: SIGTERM every worker instead of
    setting the preempt flag directly. A flag with no barrier arrival
    attached can be observed by two lockstep hosts on opposite sides
    of a barrier release, splitting their exit boundaries (mismatched
    commit barriers, failed drain). The workers' own SIGTERM handlers
    enter the broadcast protocol at one of its decision points, which
    IS race-free — flag-before-arrival plus the in-barrier deferral."""
    for (host, _slot), p in zip(workers, procs):
        if p.poll() is not None:
            continue
        if host in LOCAL_HOSTS:
            _signal_local(p, "TERM")
        else:
            # never terminate the ssh client here: the session dying
            # would reach the remote worker as a HUP (if at all), not
            # the SIGTERM its preemption handler is installed for
            _remote_pkill(host, encoded, "TERM")


def _teardown(
    cp: FileControlPlane,
    procs: List[subprocess.Popen],
    workers: List[tuple],
    encoded: str,
    config: RunnerConfig,
) -> None:
    """Stop the survivors of a failed epoch without an indefinite hang:
    abort flag (barrier waits raise within one poll), SIGTERM, then
    SIGKILL for anything that rode out the grace period.

    For ssh-launched workers the local Popen is only the ssh client —
    killing it does NOT kill the remote worker, and a host wedged
    inside a collective keeps holding its TPU devices into the next
    epoch. A best-effort remote ``pkill`` against the unique payload
    marker cleans those up; the base64 payload is shell- and
    regex-safe by construction."""
    with span("supervisor.teardown", level="info"):
        _teardown_inner(cp, procs, workers, encoded, config)


def _teardown_inner(
    cp: FileControlPlane,
    procs: List[subprocess.Popen],
    workers: List[tuple],
    encoded: str,
    config: RunnerConfig,
) -> None:
    try:
        cp.set_flag(ABORT_FLAG, "host-dead")
    except (OSError, RuntimeError, ValueError) as e:
        # best-effort: if the control-plane storage is what failed, the
        # signal escalation below is still the real teardown — dying
        # here would leave every survivor wedged in its collective
        logger.warning(f"abort flag write failed (continuing): {e!r}")
    remote_hosts = sorted(
        {h for h, _ in workers if h not in LOCAL_HOSTS}
    )
    for p in procs:
        if p.poll() is None:
            _signal_local(p, "TERM")
    for host in remote_hosts:
        # the local Popen is only the ssh client: it exits immediately on
        # TERM, which would otherwise collapse the grace window to ~0 and
        # send the still-running remote workers straight to pkill -KILL
        _remote_pkill(host, encoded, "TERM")
    deadline = time.monotonic() + config.worker_grace_seconds
    # remote liveness is not observable through the ssh-client procs, so
    # with remote hosts the grace is a plain wall-clock wait
    while time.monotonic() < deadline and (
        remote_hosts or any(p.poll() is None for p in procs)
    ):
        time.sleep(0.05)
    killed = []
    for p in procs:
        if p.poll() is None:
            killed.append(p.pid)
            _signal_local(p, "KILL")
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            logger.error(f"worker pid {p.pid} unreaped after SIGKILL")
    if killed:
        logger.warning(
            f"worker pid(s) {killed} survived the {config.worker_grace_seconds}s "
            "SIGTERM grace (wedged collective?); SIGKILLed"
        )
    for host in remote_hosts:
        _remote_pkill(host, encoded, "KILL")
    logger.log_event(
        "teardown-complete", killed_pids=killed, remote_hosts=remote_hosts
    )


def _run_epoch(
    config: RunnerConfig,
    pool: Dict[str, int],
    workers: List[tuple],
    encoded: str,
    master_addr: str,
    control_root: Path,
    epoch: int,
    state: Dict[str, Any],
    capacity: Optional[SupervisorCapacity] = None,
) -> int:
    """One coordinator epoch: spawn, monitor, and (on failure) tear down.

    Returns 0 on a clean epoch (training finished or coordinated
    preemption), non-zero when a host died/hung and the epoch was torn
    down. ``state["gone"]`` is left holding the worker indices this
    epoch lost (empty on a clean epoch) — the downsize planner's input.
    When ``capacity`` decides a resize/lease is due, the epoch is
    drained exactly like a coordinated preemption (every host saves at
    the same step boundary, exits 0) and the decision is left in
    ``state["capacity"]`` for :func:`supervise_main` to execute."""
    epoch_dir = control_root / f"epoch-{epoch}"
    if epoch_dir.exists():
        # ephemeral coordination state from a PREVIOUS supervisor run
        # over the same control root (never checkpoint data): a stale
        # abort flag or barrier arrival here would instantly poison the
        # new epoch's workers
        shutil.rmtree(epoch_dir)
    epoch_dir.mkdir(parents=True)
    num_hosts = len(workers)
    # monitor view of the epoch's control plane: heartbeat reads + flag
    # writes only (the supervisor never enters barriers)
    cp = FileControlPlane(epoch_dir, host_id=0, num_hosts=num_hosts)
    # a fresh port per epoch: the dead epoch's coordinator socket may
    # linger in TIME_WAIT and refuse the new rendezvous
    master_port = config.master_port + epoch
    procs: List[subprocess.Popen] = []
    for process_id, (host, _slot) in enumerate(workers):
        env = worker_env(
            pool, workers, process_id, master_addr, master_port
        )
        env.update({
            ENV_CONTROL_DIR: str(epoch_dir),
            ENV_HOST_ID: str(process_id),
            ENV_NUM_HOSTS: str(num_hosts),
            ENV_COORD_EPOCH: str(epoch),
        })
        procs.append(spawn_worker(config, host, env, encoded))
    logger.log_event(
        "epoch-start", epoch=epoch, num_hosts=num_hosts,
        master_port=master_port, pids=[p.pid for p in procs],
    )
    started = time.monotonic()
    preempt_broadcast = False
    state["gone"] = []
    state["capacity"] = None
    while True:
        time.sleep(config.supervisor_poll_seconds)
        if state["preempted"] and not preempt_broadcast:
            _relay_sigterm(procs, workers, encoded)
            preempt_broadcast = True
            logger.log_event("preempt-relay", host="supervisor",
                             epoch=epoch)
        if (capacity is not None and not preempt_broadcast
                and state["capacity"] is None
                # a worker that has not heartbeated THIS epoch may not
                # even have its SIGTERM handler installed yet (still
                # importing / restoring): draining now would kill it
                # outright, fail the epoch, and lose the decision — the
                # channel re-surfaces matured actions on every poll, so
                # waiting for full coverage costs nothing
                and len(cp.peer_heartbeats()) >= num_hosts):
            try:
                act = capacity.poll(
                    time.time(),
                    member_hosts=(
                        set() if is_local_pool(pool) else set(pool)
                    ),
                    train_world=len(workers),
                )
            except Exception as e:
                # the capacity channel must never take down a healthy
                # epoch — a sick announcement dir or an injected fault
                # skips this poll, training continues
                logger.warning(f"capacity poll failed: {e!r}")
                act = None
            if act is not None:
                # drain like a coordinated preemption: every host saves
                # at the same step boundary and exits 0; the resize is
                # executed between epochs
                state["capacity"] = act
                _relay_sigterm(procs, workers, encoded)
                logger.log_event(
                    "capacity-drain", epoch=epoch, action=act[0],
                )
        rcs = [p.poll() for p in procs]
        if all(rc is not None for rc in rcs):
            if all(rc == 0 for rc in rcs):
                stall = cp.get_flag(STALL_FLAG)
                if stall is not None:
                    # a step-stall watchdog drained the pod: every host
                    # saved and exited 0, but training is NOT done —
                    # count it as a failed epoch so the budgeted
                    # relaunch resumes it instead of reporting success
                    # mid-run
                    logger.log_event(
                        "epoch-stalled", epoch=epoch, stall_step=stall
                    )
                    logger.error(
                        f"epoch {epoch}: clean exit but the stall flag is "
                        f"set (step {stall}); relaunching to resume"
                    )
                    return 1
                logger.log_event(
                    "epoch-clean-exit", epoch=epoch,
                    preempted=preempt_broadcast or bool(
                        cp.get_flag(PREEMPT_FLAG)
                    ),
                )
                return 0
            bad = {h: rcs[h] for h in range(num_hosts) if rcs[h] != 0}
            state["gone"] = sorted(bad)
            logger.log_event(
                "host-dead", epoch=epoch, hosts=sorted(bad), reason="exit",
                exit_codes=bad,
            )
            # every LOCAL proc has exited, but for ssh-launched workers
            # those are only the ssh clients — a network blip can kill
            # all of them at once while the remote workers keep running,
            # and skipping teardown here would leave the orphans fighting
            # the relaunched epoch for devices and checkpoint dirs
            _teardown(cp, procs, workers, encoded, config)
            return 1
        now = time.time()
        heartbeats = cp.peer_heartbeats()
        verdict = classify_workers(
            rcs, heartbeats,
            heartbeat_timeout_s=config.heartbeat_timeout_seconds,
            startup_grace_s=config.startup_grace_seconds,
            epoch_elapsed_s=time.monotonic() - started,
            now=now,
        )
        if not verdict["dead"] and not verdict["hung"]:
            continue
        gone = verdict["dead"] or verdict["hung"]
        state["gone"] = sorted(gone)
        reason = "exit" if verdict["dead"] else "heartbeat-stale"
        # the SAME snapshot that produced the verdict: a host whose
        # heartbeat refreshes between two reads would otherwise render a
        # "heartbeat-stale" teardown next to an all-fresh straggler table
        report = straggler_table(
            heartbeats, num_hosts,
            config.heartbeat_timeout_seconds, now=now,
        )
        logger.error(
            f"epoch {epoch}: host(s) {gone} {reason}; tearing down "
            f"survivors\n{report.render()}"
        )
        logger.log_event(
            "host-dead", epoch=epoch, hosts=gone, reason=reason,
            exit_codes={h: rcs[h] for h in verdict["dead"]},
        )
        _teardown(cp, procs, workers, encoded, config)
        return 1


def replan_layout(
    config: RunnerConfig, new_slots: int, payload: Any
) -> Optional[dict]:
    """Tuner-picked layout for the downsized world, or None.

    When ``runner.downsize_model`` names a model, the surviving slot
    count goes through ``tune.best_layout`` so the new placement is
    chosen by comm cost (the ATP adaptive-re-parallelization move), not
    by naively shrinking dp; accumulated run-dir telemetry corrects the
    cost model per axis when the events path points at prior epochs'
    run dirs. Annotation-not-fatal: any tuner failure downgrades to a
    plain world shrink — a replan must never block the relaunch."""
    if config.downsize_model is None:
        return None
    try:
        from ..tune import best_layout
        from ..tune.costmodel import AxisCorrection, SliceTopology

        kwargs: Dict[str, Any] = {}
        topo = payload.get("topology") if isinstance(payload, dict) else None
        if isinstance(topo, dict):
            if topo.get("global_batch_size"):
                kwargs["global_batch_size"] = int(topo["global_batch_size"])
            if topo.get("micro_batch_size"):
                kwargs["micro_batch_size"] = int(topo["micro_batch_size"])
        events_path = os.environ.get("SCALING_TPU_EVENTS_PATH")
        if events_path:
            correction = AxisCorrection.from_run_dirs(Path(events_path).parent)
            if correction is not None:
                kwargs["correction"] = correction
        best, ranked = best_layout(
            config.downsize_model, SliceTopology(chips=new_slots), **kwargs
        )
        return {
            "label": best.label,
            "predicted_step_s": round(ranked[0].predicted_step_s, 6),
            "topology": best.topology_dict(),
        }
    except Exception as e:
        logger.warning(
            f"downsize replan via tune.best_layout failed ({e!r}); "
            "falling back to a plain world shrink"
        )
        return None


def _shrink_topology(topo: Dict[str, Any], new_slots: int
                     ) -> Optional[Dict[str, Any]]:
    """Plain refit of a payload-carried topology to ``new_slots``: keep
    the model axes (pp/cp/mp — changing those needs the tuner's
    validity rules) and fold the capacity delta into the data axis,
    shrink and GROW alike. Preserves the saving run's global_batch_size
    when the new grid divides it (gas adjusts — the data stream then
    continues skip/repeat-free at the same per-step sample blocks);
    otherwise keeps gas and re-derives gbs. None when the new slots
    cannot host the fixed axes."""
    try:
        pp = int(topo.get("pipe_parallel_size") or 1)
        cp = int(topo.get("context_parallel_size") or 1)
        mp = int(topo.get("model_parallel_size") or 1)
    except (TypeError, ValueError):
        return None
    fixed = pp * cp * mp
    if fixed <= 0 or new_slots % fixed:
        return None
    dp = new_slots // fixed
    if dp < 1:
        return None
    out = {**topo, "world_size": new_slots, "data_parallel_size": dp}
    mbs = topo.get("micro_batch_size")
    gbs = topo.get("global_batch_size")
    if mbs and gbs and int(gbs) % (int(mbs) * dp) == 0:
        out["gradient_accumulation_steps"] = int(gbs) // (int(mbs) * dp)
    elif mbs and topo.get("gradient_accumulation_steps"):
        out["global_batch_size"] = (
            int(mbs) * int(topo["gradient_accumulation_steps"]) * dp
        )
    return out


def plan_downsize(
    config: RunnerConfig,
    pool: Dict[str, int],
    workers: List[tuple],
    gone: List[int],
    payload: Any,
) -> Optional[tuple]:
    """The downsized plan after repeated failures: drop the lost worker
    indices, rebuild the pool from the survivors, replan the layout.

    Returns ``(pool, workers, replan, payload)`` — ``replan`` is the
    tuner's pick or None — or None when downsizing is impossible
    (nothing identifiably dead, or the floor ``runner.min_hosts`` would
    be crossed: better to give up loudly than thrash below a size the
    model cannot fit)."""
    dead = {h for h in gone if 0 <= h < len(workers)}
    if not dead:
        return None
    survivors = [w for i, w in enumerate(workers) if i not in dead]
    if len(survivors) < max(config.min_hosts, 1):
        return None
    new_pool: Dict[str, int] = {}
    for host, _slot in survivors:
        new_pool[host] = new_pool.get(host, 0) + 1
    # remote pools plan one worker per host owning all its slots — keep
    # the surviving hosts' full slot counts in that mode
    if not is_local_pool(new_pool):
        new_pool = {h: pool[h] for h, _ in survivors}
    new_slots = sum(new_pool.values())
    replan, new_payload = _replan_payload(
        config, new_slots, payload, direction="downsize"
    )
    return new_pool, plan_workers(new_pool), replan, new_payload


def _replan_payload(
    config: RunnerConfig, new_slots: int, payload: Any, *, direction: str
) -> tuple:
    """The resize tail shared by downsize and upsize: tuner replan over
    the new slot count, then the payload-carried topology rewrite.

    A payload-carried topology MUST be rewritten to the new world size
    — relaunching 4 survivors into an 8-way mesh (or 8 hosts into a
    4-way one) fails every epoch at startup and burns the fresh budget.
    Tuner pick when available, else the plain dp refit."""
    replan = replan_layout(config, new_slots, payload)
    new_payload = payload
    if isinstance(payload, dict) and isinstance(payload.get("topology"), dict):
        new_topo = (
            replan["topology"] if replan is not None
            else _shrink_topology(payload["topology"], new_slots)
        )
        if new_topo is not None:
            new_payload = {**payload, "topology": new_topo}
        else:
            logger.warning(
                f"{direction}: the payload topology's pp*cp*mp does not "
                f"fit {new_slots} slot(s) and no tuner replan is "
                "available; relaunching with the topology UNCHANGED — "
                "set runner.downsize_model so the layout is replanned"
            )
    return replan, new_payload


def plan_upsize(
    config: RunnerConfig,
    pool: Dict[str, int],
    additions: List[tuple],
    payload: Any,
) -> Optional[tuple]:
    """The grown plan after capacity returned: merge ``additions``
    (``(host, slots)`` pairs — matured announcements or a released
    lease) into the pool, replan the layout over the larger slot count.

    Local slot-expansion pools grow by adding slots to the local entry
    (the fake-pod / single-machine mode); a remote hostname already in
    the pool is skipped — it is running workers right now, there is
    nothing to add. Returns ``(pool, workers, replan, payload)`` like
    :func:`plan_downsize`, or None when nothing new would be added."""
    new_pool = dict(pool)
    added: List[str] = []
    for host, slots in additions:
        if host in new_pool:
            if is_local_pool({host}):
                new_pool[host] = new_pool[host] + max(int(slots), 1)
                added.append(host)
            continue  # remote member already planned: nothing to add
        new_pool[host] = max(int(slots), 1)
        added.append(host)
    if not added:
        return None
    new_slots = sum(new_pool.values())
    replan, new_payload = _replan_payload(
        config, new_slots, payload, direction="upsize"
    )
    return new_pool, plan_workers(new_pool), replan, new_payload


def choose_lease_victim(
    pool: Dict[str, int], workers: List[tuple], master_addr: str
) -> tuple:
    """``(worker_index, host, slots)`` training hands to the fleet on a
    lease: the LAST worker, skipping the coordinator's host when any
    other host exists (demoting the coordinator would force a
    re-election for a voluntary lend). Local slot-expansion pools lend
    one slot; remote pools lend the whole host with all its slots."""
    local = is_local_pool(pool)
    for idx in range(len(workers) - 1, -1, -1):
        host = workers[idx][0]
        if local or host != master_addr:
            return idx, host, (1 if local else pool[host])
    idx = len(workers) - 1
    host = workers[idx][0]
    return idx, host, (1 if local else pool[host])


def resolve_master_addr(
    pinned: Optional[str], pool: Dict[str, int], previous: str
) -> str:
    """Coordinator election across elastic resizes (downsize AND
    upsize). The pinned ``runner.master_addr`` wins whenever it names a
    CURRENT pool member — including a host that left and came back,
    which is safe exactly because every epoch rendezvouses on a fresh
    ``master_port`` (base + epoch): the returned host's stale
    coordinator socket from its pre-downsize incarnation can never
    capture the new epoch's rendezvous. When the pinned host is absent,
    keep the PREVIOUS coordinator if it survived (election stability —
    no pointless re-rendezvous churn), else elect the first pool
    host."""
    if pinned and pinned in pool:
        return pinned
    if previous in pool:
        return previous
    return next(iter(pool))


def _build_capacity(
    config: RunnerConfig, control_root: Path
) -> Optional[SupervisorCapacity]:
    """The supervisor's capacity rails, when elasticity is on. The
    channel lives BESIDE the per-epoch control dirs (which are wiped on
    every relaunch): announcements and leases must survive coordinator
    epochs. The arbitration manager only exists under ``arbitrate`` —
    upsize-only runs poll announcements but never lend a host."""
    if config.upsize_after is None and not config.arbitrate:
        return None
    manager = None
    if config.arbitrate:
        manager = CapacityManager(ArbitrationPolicy(
            pressure_high=config.capacity_pressure_high,
            sustain_s=config.capacity_sustain_seconds,
            idle_sustain_s=config.capacity_idle_seconds,
            cooldown_s=config.capacity_cooldown_seconds,
            lease_timeout_s=config.lease_timeout_seconds,
            min_train_hosts=config.min_train_hosts,
            min_replicas=config.min_replicas,
        ))
    return SupervisorCapacity(
        CapacityChannel(control_root / "capacity"),
        upsize_after=config.upsize_after,
        manager=manager,
        stale_s=config.capacity_stale_seconds,
        poll_interval_s=config.capacity_poll_seconds,
    )


def _execute_capacity_action(
    config: RunnerConfig,
    capacity: SupervisorCapacity,
    act: tuple,
    epoch: int,
    ctx: Dict[str, Any],
) -> bool:
    """Apply a drained capacity decision between epochs. ``ctx`` holds
    the mutable plan (``pool``/``workers``/``payload``/``master_addr``)
    and is updated in place; returns True when the world actually
    resized (the caller re-baselines the restart budget)."""
    pool, workers = ctx["pool"], ctx["workers"]
    payload, master_addr = ctx["payload"], ctx["master_addr"]
    if act[0] == "lease":
        idx, lease_host, lease_slots = choose_lease_victim(
            pool, workers, master_addr
        )
        plan = plan_downsize(config, pool, workers, [idx], payload)
        if plan is None:
            logger.warning(
                "lease requested by the capacity arbiter but no viable "
                f"smaller plan exists (min_hosts={config.min_hosts}); "
                "relaunching at the current size"
            )
            capacity.absorb(act)  # start the cooldown — do not thrash
            return False
        try:
            capacity.grant(lease_host, lease_slots, epoch=epoch)
        except Exception as e:
            # grant-before-shrink ordering is the no-orphan guarantee:
            # a failed/killed grant write means NO lease exists, so
            # training keeps the host and relaunches at full size —
            # nothing is stranded between the two owners
            logger.warning(
                f"lease grant for {lease_host} failed ({e!r}); keeping "
                "the host and relaunching at the current size"
            )
            capacity.absorb(act)
            return False
        old_world = len(workers)
        pool, workers, replan, payload = plan
        master_addr = resolve_master_addr(
            config.master_addr, pool, master_addr
        )
        logger.log_event(
            "downsize", epoch=epoch, old_world=old_world,
            new_world=len(workers), removed_hosts=[lease_host],
            layout=replan["label"] if replan else None,
            predicted_step_s=(
                replan["predicted_step_s"] if replan else None
            ),
            source="lease",
        )
        logger.warning(
            f"leased {lease_host} ({lease_slots} slot(s)) to the serving "
            f"fleet; pod {old_world} -> {len(workers)} host(s)"
        )
        capacity.on_downsize()
    else:  # "upsize" (matured announcements) / "upsize-release" (lease)
        additions = (
            [(o.host, o.slots) for o in act[1]] if act[0] == "upsize"
            else [(act[1].host, act[1].slots)]
        )
        plan = plan_upsize(config, pool, additions, payload)
        if plan is None:
            logger.warning(
                f"upsize matured for {additions} but added no capacity; "
                "relaunching unchanged"
            )
            capacity.absorb(act)
            return False
        old_world = len(workers)
        pool, workers, replan, payload = plan
        master_addr = resolve_master_addr(
            config.master_addr, pool, master_addr
        )
        source = "announce" if act[0] == "upsize" else "lease-return"
        logger.log_event(
            "upsize", epoch=epoch, old_world=old_world,
            new_world=len(workers),
            added_hosts=sorted({h for h, _ in additions}),
            layout=replan["label"] if replan else None,
            predicted_step_s=(
                replan["predicted_step_s"] if replan else None
            ),
            source=source,
        )
        logger.warning(
            f"upsizing pod {old_world} -> {len(workers)} host(s) "
            f"({source}); workers relaunch via reshard-on-restore"
            + (f" into tuner layout {replan['label']}" if replan else "")
        )
        capacity.absorb(act)
    ctx.update(pool=pool, workers=workers, payload=payload,
               master_addr=master_addr)
    return True


def supervise_main(config: RunnerConfig, payload: Any) -> int:
    """Run the pool under supervision until training completes, a
    coordinated preemption drains it, or the restart budget runs out."""
    if config.control_dir is None:
        raise ValueError(
            "runner.supervise=true needs runner.control_dir (a directory "
            "every host can reach, for the heartbeat control plane)"
        )
    pool = get_resource_pool(config)
    workers = plan_workers(pool)
    master_addr = config.master_addr or list(pool)[0]
    encoded = encode_payload(payload)
    control_root = Path(config.control_dir)
    control_root.mkdir(parents=True, exist_ok=True)

    # the capacity channel lives BESIDE the per-epoch control dirs (which
    # are wiped on every relaunch): announcements and leases must survive
    # coordinator epochs
    capacity = _build_capacity(config, control_root)

    # SIGTERM to the supervisor = coordinated preemption of the pod
    # (chained to any previously installed handler, like the trainer's)
    state = {"preempted": False}
    prev = signal.getsignal(signal.SIGTERM)

    def on_sigterm(signum, frame):
        state["preempted"] = True
        if callable(prev):  # SIG_DFL/SIG_IGN are enum ints, skipped
            prev(signum, frame)

    signal.signal(signal.SIGTERM, on_sigterm)

    restarts = 0
    epoch = 0
    # downsize bookkeeping: consecutive failed epochs that each LOST
    # capacity (stall drains lose none and do not count) at the current
    # world size — runner.downsize_after epochs of that means the
    # capacity is not coming back and the survivors should carry on
    consecutive_losses = 0
    while True:
        # one trace per supervision epoch, derived from (control root,
        # epoch) so a relaunched supervisor over the same run re-derives
        # the same incident ids: every span/event in the epoch —
        # teardown, backoff, relaunch — reads as one timeline in
        # obs trace
        with trace_context(derive_trace_id(
                "supervisor-epoch", str(control_root), epoch)):
            with span("supervisor.epoch", level="info", epoch=epoch) as ep:
                rc = _run_epoch(
                    config, pool, workers, encoded, master_addr,
                    control_root, epoch, state, capacity,
                )
                ep.annotate(rc=rc)
        if rc == 0:
            act = state.get("capacity")
            if act is None or state["preempted"] or capacity is None:
                return 0
            ctx = {"pool": pool, "workers": workers, "payload": payload,
                   "master_addr": master_addr}
            resized = _execute_capacity_action(
                config, capacity, act, epoch, ctx
            )
            if resized:
                pool, workers, payload = (
                    ctx["pool"], ctx["workers"], ctx["payload"]
                )
                master_addr = ctx["master_addr"]
                encoded = encode_payload(payload)
                consecutive_losses = 0
                # a fresh budget for the new world size, exactly like
                # downsize: the budget is PER world size
                restarts = 0
            epoch += 1
            continue
        if state["preempted"]:
            # an operator-initiated shutdown that still lost a host is
            # not a reason to spin the pod back up
            logger.error("epoch failed during preemption drain; not relaunching")
            return rc
        gone = list(state.get("gone") or [])
        consecutive_losses = consecutive_losses + 1 if gone else 0
        if (
            config.downsize_after is not None
            and consecutive_losses >= config.downsize_after
        ):
            plan = plan_downsize(config, pool, workers, gone, payload)
            if plan is None:
                logger.warning(
                    f"downsize requested after {consecutive_losses} "
                    f"consecutive capacity losses but no viable smaller "
                    f"plan exists (min_hosts={config.min_hosts}); "
                    "continuing relaunches at the current size"
                )
            else:
                old_world = len(workers)
                pool, workers, replan, payload = plan
                encoded = encode_payload(payload)
                # a pinned master_addr naming a host the downsize just
                # removed would make every downsized epoch rendezvous
                # against the dead coordinator and burn the fresh
                # budget on guaranteed failures — re-elect a survivor
                # (resolve_master_addr re-adopts the pin if the host
                # later returns through an upsize)
                elected = resolve_master_addr(
                    config.master_addr, pool, master_addr
                )
                if elected != master_addr:
                    logger.warning(
                        f"downsize removed coordinator {master_addr}; "
                        f"re-electing {elected}"
                    )
                master_addr = elected
                if capacity is not None:
                    # the capacity that shrank the job must re-prove
                    # itself: every upsize streak starts over
                    capacity.on_downsize()
                logger.log_event(
                    "downsize", epoch=epoch, old_world=old_world,
                    new_world=len(workers), removed_hosts=sorted(gone),
                    layout=replan["label"] if replan else None,
                    predicted_step_s=(
                        replan["predicted_step_s"] if replan else None
                    ),
                    source="tuner" if replan else "shrink",
                )
                logger.warning(
                    f"downsizing pod {old_world} -> {len(workers)} host(s) "
                    f"after {consecutive_losses} consecutive capacity "
                    "losses; survivors relaunch via reshard-on-restore"
                    + (f" into tuner layout {replan['label']}" if replan
                       else "")
                )
                consecutive_losses = 0
                # a fresh budget for the new world size: the old one was
                # spent discovering the lost capacity is not coming back
                restarts = 0
        restarts += 1
        if restarts > config.restart_budget:
            logger.log_event(
                "give-up", epoch=epoch, restarts=restarts - 1,
                budget=config.restart_budget,
            )
            logger.error(
                f"supervisor restart budget exhausted "
                f"({config.restart_budget}); giving up"
            )
            return rc
        delay = restart_backoff(restarts, config.restart_backoff_seconds,
                                cap_s=float("inf"))
        epoch += 1
        logger.log_event(
            "relaunch", epoch=epoch, restarts=restarts,
            budget=config.restart_budget, backoff_s=delay,
        )
        logger.warning(
            f"relaunching as coordinator epoch {epoch} in {delay:.1f}s "
            f"(restart {restarts}/{config.restart_budget}); workers will "
            "resume from the newest valid checkpoint"
        )
        # traced so the analyzer's restart timeline shows backoff cost
        # (time the pod sat idle between epochs) next to the epochs
        with span("supervisor.backoff", level="info", epoch=epoch):
            time.sleep(delay)
