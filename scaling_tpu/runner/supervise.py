"""Multi-host supervisor: heartbeat monitoring, teardown, bounded relaunch.

Mesh-style SPMD makes every host a single point of failure: one
reclaimed machine leaves the other N-1 blocked inside a collective that
will never complete, burning pod-hours until a human notices. The
supervisor is the per-pod half of the resilience gate (the per-process
half is :mod:`scaling_tpu.resilience`): it launches the workers of one
*coordinator epoch*, watches their exit codes and control-plane
heartbeats, and on a dead or hung host

1. raises the ``abort`` broadcast flag so survivors waiting at any
   barrier exit within seconds instead of the full barrier timeout,
2. SIGTERMs the survivors, escalating to SIGKILL after a grace period
   (a host truly wedged inside an XLA collective ignores SIGTERM),
3. relaunches the whole rendezvous as a fresh epoch — new control-plane
   directory (no stale arrivals), new coordinator port (the dead
   coordinator's socket may linger in TIME_WAIT) — under a bounded
   exponential-backoff restart budget.

Relaunched workers resume exactly like ``run_with_resume`` does: the
training script points ``load_dir`` at its ``save_dir`` and restores the
newest checkpoint that passes integrity verification, so the resumed
loss trajectory is the uninterrupted one (the cross-host commit barrier
guarantees no mixed-step ``latest`` exists to restore from).

SIGTERM to the supervisor is relayed as SIGTERM to every worker (not a
direct flag write — see :func:`_relay_sigterm`): the workers' handlers
run the coordinated-preemption protocol, every host saves at the same
step boundary, exits 0, and the epoch counts as clean — no relaunch.

**Elastic downsizing** (``runner.downsize_after``, docs/RESILIENCE.md
"Elastic resharding"): when the SAME capacity keeps dying — a reclaimed
slice that is not coming back — retrying at full size burns the whole
restart budget on a recoverable failure. After ``downsize_after``
consecutive failed epochs the supervisor instead drops the lost hosts
from the worker plan, replans the layout for the surviving slots
(``tune.best_layout`` when ``runner.downsize_model`` names a model —
the new layout is picked by comm cost, ATP arxiv 2301.08658 /
Megatron-LM arxiv 2104.04473 — else a plain world shrink), rewrites the
payload topology when one rides along, emits a ``downsize`` event on
the obs rails, and relaunches: the workers resume through
reshard-on-restore (``resilience.reshard``). The restart budget resets
per world size. Restored capacity sizes back up through the same
mechanism: relaunching the supervisor over the full host list restores
the downsized checkpoint onto the bigger mesh.

Every transition lands as a structured event (``logger.log_event``):
``epoch-start``, ``host-dead``, ``teardown-complete``, ``relaunch``,
``preempt-relay``, ``epoch-clean-exit``, ``epoch-stalled``,
``downsize``, ``give-up``.
"""

from __future__ import annotations

import os
import shutil
import signal
import subprocess
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from ..logging import logger
from ..obs import span
from ..resilience.controlplane import (
    ABORT_FLAG,
    ENV_CONTROL_DIR,
    ENV_COORD_EPOCH,
    ENV_HOST_ID,
    ENV_NUM_HOSTS,
    PREEMPT_FLAG,
    STALL_FLAG,
    FileControlPlane,
    straggler_table,
)
from .config import RunnerConfig
from .runner import (
    LOCAL_HOSTS,
    encode_payload,
    get_resource_pool,
    is_local_pool,
    plan_workers,
    spawn_worker,
    worker_env,
)


def classify_workers(
    exit_codes: List,
    heartbeats: Dict,
    *,
    heartbeat_timeout_s: float,
    startup_grace_s: float,
    epoch_elapsed_s: float,
    now: float,
) -> Dict[str, List[int]]:
    """Split one epoch's workers into dead / hung / alive.

    *dead*: exited non-zero (a SIGKILL shows as a negative code).
    *hung*: still running but the newest heartbeat is stale (or absent,
    or still ``starting``) AND the startup grace has passed. The grace
    suppresses ALL staleness verdicts, not just missing first
    heartbeats: a host can legitimately go silent for minutes inside
    the cold jit compile of its first step — after it already published
    ``starting`` and a ``barrier:step-0`` refresh — and that window is
    exactly what ``startup_grace_s`` budgets for. A worker whose last
    heartbeat says ``done`` or ``preempted`` is winding down, never
    hung. Pure function so the detection policy is unit-testable
    without spawning anything."""
    dead: List[int] = []
    hung: List[int] = []
    alive: List[int] = []
    for host, rc in enumerate(exit_codes):
        if rc is not None:
            if rc != 0:
                dead.append(host)
            continue  # exited 0: finished/preempted, not alive, not dead
        hb = heartbeats.get(host)
        # no special case for 'starting': a FRESH 'starting' heartbeat
        # past the grace is a host demonstrably alive (e.g. a restore
        # that outlasts the grace, still checking in) — only age makes
        # it stale, same as any other non-terminal status
        stale = (
            hb is None
            or (
                hb.status not in ("done", "preempted")
                and hb.age(now) > heartbeat_timeout_s
            )
        )
        if stale:
            (hung if epoch_elapsed_s > startup_grace_s else alive).append(host)
        else:
            alive.append(host)
    return {"dead": dead, "hung": hung, "alive": alive}


def restart_backoff(attempt: int, base_s: float,
                    cap_s: float = 60.0) -> float:
    """Relaunch delay for restart ``attempt`` (1-based): exponential
    from ``base_s``, capped — the ONE backoff curve every supervisor in
    the tree uses (trainer relaunches here, serving replica relaunches
    in ``serve.replica_proc``), so a chaos drill's restart timeline
    reads the same in both."""
    return min(base_s * (2 ** (max(attempt, 1) - 1)), cap_s)


def _signal_local(p: subprocess.Popen, sig: str) -> None:
    """SIGTERM/SIGKILL a local worker Popen, logging instead of raising
    (signal delivery races process exit benignly)."""
    try:
        (p.terminate if sig == "TERM" else p.kill)()
    except OSError as e:
        logger.warning(f"SIG{sig} to worker pid {p.pid} failed: {e!r}")


def remote_pkill(host: str, marker: str, sig: str) -> None:
    """Signal a remote host's processes matching ``marker`` via ssh pkill.

    The local Popen for an ssh-launched worker is only the ssh client —
    signalling it does not reach the remote process. ``marker`` must be
    a pattern unique to the processes being signalled (the training
    supervisor uses its launch's payload prefix; the serving fleet uses
    a replica's per-spawn config path)."""
    try:
        r = subprocess.run(
            ["ssh", host, f"pkill -{sig} -f -- {marker}"],
            timeout=30, capture_output=True,
        )
        # pkill 1 = pattern matched nothing (workers already gone) —
        # fine; anything else (pkill 2/3, ssh 255 transport failure)
        # means the remote workers may still be alive
        if r.returncode not in (0, 1):
            logger.warning(
                f"remote SIG{sig} on {host} failed rc={r.returncode}: "
                f"{getattr(r, 'stderr', b'')!r}"
            )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.warning(f"remote SIG{sig} on {host} failed: {e!r}")


def _remote_pkill(host: str, encoded: str, sig: str) -> None:
    """The training launch's marker: its unique base64 payload prefix —
    shell- and regex-safe by construction, and 48 chars keeps clear of
    base64 padding while staying unique per job."""
    remote_pkill(host, f"--payload={encoded[:48]}", sig)


def _relay_sigterm(
    procs: List[subprocess.Popen], workers: List[tuple], encoded: str
) -> None:
    """Supervisor-initiated drain: SIGTERM every worker instead of
    setting the preempt flag directly. A flag with no barrier arrival
    attached can be observed by two lockstep hosts on opposite sides
    of a barrier release, splitting their exit boundaries (mismatched
    commit barriers, failed drain). The workers' own SIGTERM handlers
    enter the broadcast protocol at one of its decision points, which
    IS race-free — flag-before-arrival plus the in-barrier deferral."""
    for (host, _slot), p in zip(workers, procs):
        if p.poll() is not None:
            continue
        if host in LOCAL_HOSTS:
            _signal_local(p, "TERM")
        else:
            # never terminate the ssh client here: the session dying
            # would reach the remote worker as a HUP (if at all), not
            # the SIGTERM its preemption handler is installed for
            _remote_pkill(host, encoded, "TERM")


def _teardown(
    cp: FileControlPlane,
    procs: List[subprocess.Popen],
    workers: List[tuple],
    encoded: str,
    config: RunnerConfig,
) -> None:
    """Stop the survivors of a failed epoch without an indefinite hang:
    abort flag (barrier waits raise within one poll), SIGTERM, then
    SIGKILL for anything that rode out the grace period.

    For ssh-launched workers the local Popen is only the ssh client —
    killing it does NOT kill the remote worker, and a host wedged
    inside a collective keeps holding its TPU devices into the next
    epoch. A best-effort remote ``pkill`` against the unique payload
    marker cleans those up; the base64 payload is shell- and
    regex-safe by construction."""
    with span("supervisor.teardown", level="info"):
        _teardown_inner(cp, procs, workers, encoded, config)


def _teardown_inner(
    cp: FileControlPlane,
    procs: List[subprocess.Popen],
    workers: List[tuple],
    encoded: str,
    config: RunnerConfig,
) -> None:
    try:
        cp.set_flag(ABORT_FLAG, "host-dead")
    except (OSError, RuntimeError, ValueError) as e:
        # best-effort: if the control-plane storage is what failed, the
        # signal escalation below is still the real teardown — dying
        # here would leave every survivor wedged in its collective
        logger.warning(f"abort flag write failed (continuing): {e!r}")
    remote_hosts = sorted(
        {h for h, _ in workers if h not in LOCAL_HOSTS}
    )
    for p in procs:
        if p.poll() is None:
            _signal_local(p, "TERM")
    for host in remote_hosts:
        # the local Popen is only the ssh client: it exits immediately on
        # TERM, which would otherwise collapse the grace window to ~0 and
        # send the still-running remote workers straight to pkill -KILL
        _remote_pkill(host, encoded, "TERM")
    deadline = time.monotonic() + config.worker_grace_seconds
    # remote liveness is not observable through the ssh-client procs, so
    # with remote hosts the grace is a plain wall-clock wait
    while time.monotonic() < deadline and (
        remote_hosts or any(p.poll() is None for p in procs)
    ):
        time.sleep(0.05)
    killed = []
    for p in procs:
        if p.poll() is None:
            killed.append(p.pid)
            _signal_local(p, "KILL")
    for p in procs:
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            logger.error(f"worker pid {p.pid} unreaped after SIGKILL")
    if killed:
        logger.warning(
            f"worker pid(s) {killed} survived the {config.worker_grace_seconds}s "
            "SIGTERM grace (wedged collective?); SIGKILLed"
        )
    for host in remote_hosts:
        _remote_pkill(host, encoded, "KILL")
    logger.log_event(
        "teardown-complete", killed_pids=killed, remote_hosts=remote_hosts
    )


def _run_epoch(
    config: RunnerConfig,
    pool: Dict[str, int],
    workers: List[tuple],
    encoded: str,
    master_addr: str,
    control_root: Path,
    epoch: int,
    state: Dict[str, Any],
) -> int:
    """One coordinator epoch: spawn, monitor, and (on failure) tear down.

    Returns 0 on a clean epoch (training finished or coordinated
    preemption), non-zero when a host died/hung and the epoch was torn
    down. ``state["gone"]`` is left holding the worker indices this
    epoch lost (empty on a clean epoch) — the downsize planner's input."""
    epoch_dir = control_root / f"epoch-{epoch}"
    if epoch_dir.exists():
        # ephemeral coordination state from a PREVIOUS supervisor run
        # over the same control root (never checkpoint data): a stale
        # abort flag or barrier arrival here would instantly poison the
        # new epoch's workers
        shutil.rmtree(epoch_dir)
    epoch_dir.mkdir(parents=True)
    num_hosts = len(workers)
    # monitor view of the epoch's control plane: heartbeat reads + flag
    # writes only (the supervisor never enters barriers)
    cp = FileControlPlane(epoch_dir, host_id=0, num_hosts=num_hosts)
    # a fresh port per epoch: the dead epoch's coordinator socket may
    # linger in TIME_WAIT and refuse the new rendezvous
    master_port = config.master_port + epoch
    procs: List[subprocess.Popen] = []
    for process_id, (host, _slot) in enumerate(workers):
        env = worker_env(
            pool, workers, process_id, master_addr, master_port
        )
        env.update({
            ENV_CONTROL_DIR: str(epoch_dir),
            ENV_HOST_ID: str(process_id),
            ENV_NUM_HOSTS: str(num_hosts),
            ENV_COORD_EPOCH: str(epoch),
        })
        procs.append(spawn_worker(config, host, env, encoded))
    logger.log_event(
        "epoch-start", epoch=epoch, num_hosts=num_hosts,
        master_port=master_port, pids=[p.pid for p in procs],
    )
    started = time.monotonic()
    preempt_broadcast = False
    state["gone"] = []
    while True:
        time.sleep(config.supervisor_poll_seconds)
        if state["preempted"] and not preempt_broadcast:
            _relay_sigterm(procs, workers, encoded)
            preempt_broadcast = True
            logger.log_event("preempt-relay", host="supervisor",
                             epoch=epoch)
        rcs = [p.poll() for p in procs]
        if all(rc is not None for rc in rcs):
            if all(rc == 0 for rc in rcs):
                stall = cp.get_flag(STALL_FLAG)
                if stall is not None:
                    # a step-stall watchdog drained the pod: every host
                    # saved and exited 0, but training is NOT done —
                    # count it as a failed epoch so the budgeted
                    # relaunch resumes it instead of reporting success
                    # mid-run
                    logger.log_event(
                        "epoch-stalled", epoch=epoch, stall_step=stall
                    )
                    logger.error(
                        f"epoch {epoch}: clean exit but the stall flag is "
                        f"set (step {stall}); relaunching to resume"
                    )
                    return 1
                logger.log_event(
                    "epoch-clean-exit", epoch=epoch,
                    preempted=preempt_broadcast or bool(
                        cp.get_flag(PREEMPT_FLAG)
                    ),
                )
                return 0
            bad = {h: rcs[h] for h in range(num_hosts) if rcs[h] != 0}
            state["gone"] = sorted(bad)
            logger.log_event(
                "host-dead", epoch=epoch, hosts=sorted(bad), reason="exit",
                exit_codes=bad,
            )
            # every LOCAL proc has exited, but for ssh-launched workers
            # those are only the ssh clients — a network blip can kill
            # all of them at once while the remote workers keep running,
            # and skipping teardown here would leave the orphans fighting
            # the relaunched epoch for devices and checkpoint dirs
            _teardown(cp, procs, workers, encoded, config)
            return 1
        now = time.time()
        heartbeats = cp.peer_heartbeats()
        verdict = classify_workers(
            rcs, heartbeats,
            heartbeat_timeout_s=config.heartbeat_timeout_seconds,
            startup_grace_s=config.startup_grace_seconds,
            epoch_elapsed_s=time.monotonic() - started,
            now=now,
        )
        if not verdict["dead"] and not verdict["hung"]:
            continue
        gone = verdict["dead"] or verdict["hung"]
        state["gone"] = sorted(gone)
        reason = "exit" if verdict["dead"] else "heartbeat-stale"
        # the SAME snapshot that produced the verdict: a host whose
        # heartbeat refreshes between two reads would otherwise render a
        # "heartbeat-stale" teardown next to an all-fresh straggler table
        report = straggler_table(
            heartbeats, num_hosts,
            config.heartbeat_timeout_seconds, now=now,
        )
        logger.error(
            f"epoch {epoch}: host(s) {gone} {reason}; tearing down "
            f"survivors\n{report.render()}"
        )
        logger.log_event(
            "host-dead", epoch=epoch, hosts=gone, reason=reason,
            exit_codes={h: rcs[h] for h in verdict["dead"]},
        )
        _teardown(cp, procs, workers, encoded, config)
        return 1


def replan_layout(
    config: RunnerConfig, new_slots: int, payload: Any
) -> Optional[dict]:
    """Tuner-picked layout for the downsized world, or None.

    When ``runner.downsize_model`` names a model, the surviving slot
    count goes through ``tune.best_layout`` so the new placement is
    chosen by comm cost (the ATP adaptive-re-parallelization move), not
    by naively shrinking dp; accumulated run-dir telemetry corrects the
    cost model per axis when the events path points at prior epochs'
    run dirs. Annotation-not-fatal: any tuner failure downgrades to a
    plain world shrink — a replan must never block the relaunch."""
    if config.downsize_model is None:
        return None
    try:
        from ..tune import best_layout
        from ..tune.costmodel import AxisCorrection, SliceTopology

        kwargs: Dict[str, Any] = {}
        topo = payload.get("topology") if isinstance(payload, dict) else None
        if isinstance(topo, dict):
            if topo.get("global_batch_size"):
                kwargs["global_batch_size"] = int(topo["global_batch_size"])
            if topo.get("micro_batch_size"):
                kwargs["micro_batch_size"] = int(topo["micro_batch_size"])
        events_path = os.environ.get("SCALING_TPU_EVENTS_PATH")
        if events_path:
            correction = AxisCorrection.from_run_dirs(Path(events_path).parent)
            if correction is not None:
                kwargs["correction"] = correction
        best, ranked = best_layout(
            config.downsize_model, SliceTopology(chips=new_slots), **kwargs
        )
        return {
            "label": best.label,
            "predicted_step_s": round(ranked[0].predicted_step_s, 6),
            "topology": best.topology_dict(),
        }
    except Exception as e:
        logger.warning(
            f"downsize replan via tune.best_layout failed ({e!r}); "
            "falling back to a plain world shrink"
        )
        return None


def _shrink_topology(topo: Dict[str, Any], new_slots: int
                     ) -> Optional[Dict[str, Any]]:
    """Plain-shrink rewrite of a payload-carried topology: keep the
    model axes (pp/cp/mp — shrinking those needs the tuner's validity
    rules) and fold the lost capacity out of the data axis. Preserves
    the saving run's global_batch_size when the new grid divides it
    (gas grows — the data stream then continues skip/repeat-free at the
    same per-step sample blocks); otherwise keeps gas and re-derives
    gbs. None when the surviving slots cannot host the fixed axes."""
    try:
        pp = int(topo.get("pipe_parallel_size") or 1)
        cp = int(topo.get("context_parallel_size") or 1)
        mp = int(topo.get("model_parallel_size") or 1)
    except (TypeError, ValueError):
        return None
    fixed = pp * cp * mp
    if fixed <= 0 or new_slots % fixed:
        return None
    dp = new_slots // fixed
    if dp < 1:
        return None
    out = {**topo, "world_size": new_slots, "data_parallel_size": dp}
    mbs = topo.get("micro_batch_size")
    gbs = topo.get("global_batch_size")
    if mbs and gbs and int(gbs) % (int(mbs) * dp) == 0:
        out["gradient_accumulation_steps"] = int(gbs) // (int(mbs) * dp)
    elif mbs and topo.get("gradient_accumulation_steps"):
        out["global_batch_size"] = (
            int(mbs) * int(topo["gradient_accumulation_steps"]) * dp
        )
    return out


def plan_downsize(
    config: RunnerConfig,
    pool: Dict[str, int],
    workers: List[tuple],
    gone: List[int],
    payload: Any,
) -> Optional[tuple]:
    """The downsized plan after repeated failures: drop the lost worker
    indices, rebuild the pool from the survivors, replan the layout.

    Returns ``(pool, workers, replan, payload)`` — ``replan`` is the
    tuner's pick or None — or None when downsizing is impossible
    (nothing identifiably dead, or the floor ``runner.min_hosts`` would
    be crossed: better to give up loudly than thrash below a size the
    model cannot fit)."""
    dead = {h for h in gone if 0 <= h < len(workers)}
    if not dead:
        return None
    survivors = [w for i, w in enumerate(workers) if i not in dead]
    if len(survivors) < max(config.min_hosts, 1):
        return None
    new_pool: Dict[str, int] = {}
    for host, _slot in survivors:
        new_pool[host] = new_pool.get(host, 0) + 1
    # remote pools plan one worker per host owning all its slots — keep
    # the surviving hosts' full slot counts in that mode
    if not is_local_pool(new_pool):
        new_pool = {h: pool[h] for h, _ in survivors}
    new_slots = sum(new_pool.values())
    replan = replan_layout(config, new_slots, payload)
    new_payload = payload
    if isinstance(payload, dict) and isinstance(payload.get("topology"), dict):
        # a payload-carried topology MUST be rewritten to the new world
        # size — relaunching 4 survivors into an 8-way mesh fails every
        # downsized epoch at startup and burns the fresh budget. Tuner
        # pick when available, else the plain dp shrink.
        new_topo = (
            replan["topology"] if replan is not None
            else _shrink_topology(payload["topology"], new_slots)
        )
        if new_topo is not None:
            new_payload = {**payload, "topology": new_topo}
        else:
            logger.warning(
                "downsize: the payload topology's pp*cp*mp does not fit "
                f"{new_slots} surviving slot(s) and no tuner replan is "
                "available; relaunching with the topology UNCHANGED — "
                "set runner.downsize_model so the layout is replanned"
            )
    return new_pool, plan_workers(new_pool), replan, new_payload


def supervise_main(config: RunnerConfig, payload: Any) -> int:
    """Run the pool under supervision until training completes, a
    coordinated preemption drains it, or the restart budget runs out."""
    if config.control_dir is None:
        raise ValueError(
            "runner.supervise=true needs runner.control_dir (a directory "
            "every host can reach, for the heartbeat control plane)"
        )
    pool = get_resource_pool(config)
    workers = plan_workers(pool)
    master_addr = config.master_addr or list(pool)[0]
    encoded = encode_payload(payload)
    control_root = Path(config.control_dir)
    control_root.mkdir(parents=True, exist_ok=True)

    # SIGTERM to the supervisor = coordinated preemption of the pod
    # (chained to any previously installed handler, like the trainer's)
    state = {"preempted": False}
    prev = signal.getsignal(signal.SIGTERM)

    def on_sigterm(signum, frame):
        state["preempted"] = True
        if callable(prev):  # SIG_DFL/SIG_IGN are enum ints, skipped
            prev(signum, frame)

    signal.signal(signal.SIGTERM, on_sigterm)

    restarts = 0
    epoch = 0
    # downsize bookkeeping: consecutive failed epochs that each LOST
    # capacity (stall drains lose none and do not count) at the current
    # world size — runner.downsize_after epochs of that means the
    # capacity is not coming back and the survivors should carry on
    consecutive_losses = 0
    while True:
        with span("supervisor.epoch", level="info", epoch=epoch) as ep:
            rc = _run_epoch(
                config, pool, workers, encoded, master_addr, control_root,
                epoch, state,
            )
            ep.annotate(rc=rc)
        if rc == 0:
            return 0
        if state["preempted"]:
            # an operator-initiated shutdown that still lost a host is
            # not a reason to spin the pod back up
            logger.error("epoch failed during preemption drain; not relaunching")
            return rc
        gone = list(state.get("gone") or [])
        consecutive_losses = consecutive_losses + 1 if gone else 0
        if (
            config.downsize_after is not None
            and consecutive_losses >= config.downsize_after
        ):
            plan = plan_downsize(config, pool, workers, gone, payload)
            if plan is None:
                logger.warning(
                    f"downsize requested after {consecutive_losses} "
                    f"consecutive capacity losses but no viable smaller "
                    f"plan exists (min_hosts={config.min_hosts}); "
                    "continuing relaunches at the current size"
                )
            else:
                old_world = len(workers)
                removed_hostnames = set(pool) - set(plan[0])
                pool, workers, replan, payload = plan
                encoded = encode_payload(payload)
                master_addr = config.master_addr or list(pool)[0]
                if master_addr in removed_hostnames:
                    # a pinned master_addr naming a host the downsize
                    # just removed would make every downsized epoch
                    # rendezvous against the dead coordinator and burn
                    # the fresh budget on guaranteed failures —
                    # re-elect a survivor
                    master_addr = list(pool)[0]
                    logger.warning(
                        f"downsize removed the pinned master_addr "
                        f"({config.master_addr}); re-electing "
                        f"{master_addr} as coordinator"
                    )
                logger.log_event(
                    "downsize", epoch=epoch, old_world=old_world,
                    new_world=len(workers), removed_hosts=sorted(gone),
                    layout=replan["label"] if replan else None,
                    predicted_step_s=(
                        replan["predicted_step_s"] if replan else None
                    ),
                    source="tuner" if replan else "shrink",
                )
                logger.warning(
                    f"downsizing pod {old_world} -> {len(workers)} host(s) "
                    f"after {consecutive_losses} consecutive capacity "
                    "losses; survivors relaunch via reshard-on-restore"
                    + (f" into tuner layout {replan['label']}" if replan
                       else "")
                )
                consecutive_losses = 0
                # a fresh budget for the new world size: the old one was
                # spent discovering the lost capacity is not coming back
                restarts = 0
        restarts += 1
        if restarts > config.restart_budget:
            logger.log_event(
                "give-up", epoch=epoch, restarts=restarts - 1,
                budget=config.restart_budget,
            )
            logger.error(
                f"supervisor restart budget exhausted "
                f"({config.restart_budget}); giving up"
            )
            return rc
        delay = restart_backoff(restarts, config.restart_backoff_seconds,
                                cap_s=float("inf"))
        epoch += 1
        logger.log_event(
            "relaunch", epoch=epoch, restarts=restarts,
            budget=config.restart_budget, backoff_s=delay,
        )
        logger.warning(
            f"relaunching as coordinator epoch {epoch} in {delay:.1f}s "
            f"(restart {restarts}/{config.restart_budget}); workers will "
            "resume from the newest valid checkpoint"
        )
        # traced so the analyzer's restart timeline shows backoff cost
        # (time the pod sat idle between epochs) next to the epochs
        with span("supervisor.backoff", level="info", epoch=epoch):
            time.sleep(delay)
