"""Runner configuration.

The reference launches one Python per GPU over pdsh/ssh/docker
(reference: src/scaling/core/runner/runner_config.py, runner.py:41-115).
On TPU pods the runtime is one process per host and rendezvous goes through
``jax.distributed.initialize(coordinator, num_processes, process_id)``, so
the config keeps the same user surface (hosts, docker knobs retained for
parity) but resolves to coordinator-based bootstrap.
"""

from __future__ import annotations

from enum import Enum
from pathlib import Path
from typing import List, Optional

from pydantic import Field

from ..config import BaseConfig


class RunnerType(Enum):
    PDSH = "pdsh"
    PDSH_DOCKER = "pdsh_docker"


class DockerConfig(BaseConfig):
    """Containerized launch (reference: runner.py:54-82 docker mode).

    On TPU VMs the container needs ``--privileged`` (libtpu drives
    /dev/accel* and vfio) and host networking for the jax.distributed
    rendezvous; both are always set, like the reference's GPU mode."""

    docker_container: Optional[str] = Field(
        None, description="image to run the worker in"
    )
    docker_sudo: bool = Field(False, description="prefix docker with sudo")
    docker_mounts: Optional[List[List[str]]] = Field(
        None, description="[host_dir, container_dir] bind mounts (code, data)"
    )
    docker_args: List[str] = Field(
        [], description="extra args appended to docker run"
    )


class RunnerConfig(BaseConfig):
    runner_type: RunnerType = Field(RunnerType.PDSH, description="launch mechanism")
    hostsfile: Optional[Path] = Field(
        None, description="file with one hostname (+ optional slot count) per line"
    )
    hosts: Optional[List[str]] = Field(None, description="inline host list")
    master_port: int = Field(29500, description="coordinator port")
    master_addr: Optional[str] = Field(None, description="coordinator address")
    script: Optional[str] = Field(
        "scaling_tpu.models.transformer.train",
        description="module to run per host; null falls back to the default "
        "train entry (the reference allows null here, launch_config.py)"
    )
    default_gpu_count: int = Field(
        8, description="devices per host when the hostsfile gives no slot counts"
    )
    docker_config: Optional[DockerConfig] = Field(
        None, description="container settings for runner_type=pdsh_docker"
    )
    use_determined: bool = Field(False, description="kept for config parity")
    supervise: bool = Field(
        False,
        description="run the workers under the multi-host supervisor "
        "(scaling_tpu.runner.supervise): per-host heartbeats over a "
        "control plane, dead/hung-host detection, clean teardown of "
        "survivors, bounded relaunch with a fresh coordinator epoch",
    )
    control_dir: Optional[Path] = Field(
        None,
        description="root directory for the file-backed control plane "
        "(required when supervise=true; each coordinator epoch gets a "
        "fresh subdirectory). Must be on storage every host can reach — "
        "shared FS for real pods, any local dir for single-machine runs",
    )
    heartbeat_timeout_seconds: float = Field(
        60.0,
        description="a host whose newest heartbeat is older than this is "
        "declared hung and the epoch is torn down (heartbeats are "
        "published once per train-loop iteration and at the head of "
        "each checkpoint/eval window; set this several multiples of "
        "the LONGEST silent stretch — the slowest step, a full eval "
        "pass, or a checkpoint write, whichever is largest)",
        gt=0,
    )
    startup_grace_seconds: float = Field(
        600.0,
        description="grace before the FIRST heartbeat of an epoch is due "
        "(covers process start + imports + cold jit compile, which can "
        "run minutes on big models)",
        gt=0,
    )
    restart_budget: int = Field(
        3,
        description="maximum supervisor relaunches (new coordinator "
        "epochs) after host failures before giving up",
        ge=0,
    )
    restart_backoff_seconds: float = Field(
        1.0,
        description="base relaunch delay; doubles with each consecutive "
        "restart (bounded exponential backoff)",
        ge=0,
    )
    worker_grace_seconds: float = Field(
        15.0,
        description="teardown grace: after the abort flag + SIGTERM, "
        "surviving workers get this long to exit before SIGKILL",
        gt=0,
    )
    supervisor_poll_seconds: float = Field(
        0.2, description="supervisor monitoring loop period", gt=0
    )
    downsize_after: Optional[int] = Field(
        None,
        description="elastic downsizing (docs/RESILIENCE.md 'Elastic "
        "resharding'): after this many CONSECUTIVE failed epochs that "
        "each lost capacity, drop the most recently dead hosts from "
        "the worker plan and relaunch the survivors at the smaller "
        "world size instead of burning the rest of the restart budget "
        "waiting for capacity to return (workers resume via "
        "reshard-on-restore). The restart budget resets on each "
        "downsize — it budgets relaunches PER world size. None "
        "disables (legacy behavior: retry at full size until the "
        "budget runs out)",
        ge=1,
    )
    min_hosts: int = Field(
        1,
        description="never downsize below this many hosts (a pod that "
        "cannot fit the model on fewer hosts should give up, not "
        "thrash)",
        ge=1,
    )
    downsize_model: Optional[str] = Field(
        None,
        description="model spec for the downsize replan: a bench model "
        "name ('0.5b', '1b') the tuner prices so the NEW layout is "
        "picked by comm cost (tune.best_layout over the surviving "
        "slots) rather than by naively shrinking dp. None skips the "
        "tuner and only shrinks the world (the payload topology, when "
        "present, is still rewritten to the new world size). The same "
        "replan runs on elastic UPSIZES over the larger slot count",
    )
    upsize_after: Optional[int] = Field(
        None,
        description="elastic size-back-up (docs/RESILIENCE.md 'Elastic "
        "capacity'): restored/standby capacity announcing itself on the "
        "control plane's capacity channel must be observed healthy this "
        "many CONSECUTIVE supervisor polls — same incarnation "
        "throughout — before the supervisor drains at a step boundary "
        "and relaunches over the larger host list (hysteresis "
        "mirroring downsize_after; a flapping host can never churn the "
        "pod, and capacity that downsized the job re-proves itself "
        "from zero). The restart budget re-baselines per world size. "
        "None disables auto upsizing",
        ge=1,
    )
    capacity_stale_seconds: float = Field(
        15.0,
        description="a capacity announcement or fleet demand heartbeat "
        "older than this is treated as withdrawn",
        gt=0,
    )
    capacity_poll_seconds: float = Field(
        0.5,
        description="how often the supervisor reads the capacity "
        "channel (upsize hysteresis counts in units of this poll)",
        gt=0,
    )
    arbitrate: bool = Field(
        False,
        description="run the train<->serve CapacityManager: sustained "
        "serving-fleet pressure on the capacity channel borrows a host "
        "from training (lease), sustained fleet idle returns it "
        "(reclaim). Lease state rides the capacity journal; see "
        "docs/RESILIENCE.md 'Elastic capacity'",
    )
    min_train_hosts: int = Field(
        1,
        description="arbitration floor: training never lends a host "
        "below this world size",
        ge=1,
    )
    capacity_pressure_high: float = Field(
        0.5,
        description="fleet pool pressure at or above this, sustained "
        "for capacity_sustain_seconds, triggers a lease",
        ge=0,
    )
    capacity_sustain_seconds: float = Field(
        2.0, description="how long fleet pressure must hold before a "
        "host is leased", ge=0,
    )
    capacity_idle_seconds: float = Field(
        2.0, description="how long fleet idle must hold before a leased "
        "host is reclaimed", ge=0,
    )
    capacity_cooldown_seconds: float = Field(
        5.0, description="minimum gap between arbitration decisions "
        "(lease or reclaim)", ge=0,
    )
    lease_timeout_seconds: float = Field(
        30.0,
        description="a lease still 'granted' (never activated by the "
        "fleet) after this long is expired back to training — the "
        "no-orphaned-host guarantee when a client dies mid-handoff",
        gt=0,
    )
    min_replicas: int = Field(
        1,
        description="arbitration floor: never reclaim the serving "
        "fleet below this many replicas",
        ge=0,
    )


class LaunchConfig(BaseConfig):
    """Per-process launch parameters, read back from env/args
    (reference: src/scaling/core/runner/launch_config.py:40-83)."""

    master_addr: str = Field("127.0.0.1", description="")
    master_port: int = Field(29500, description="")
    world_size: int = Field(1, description="total number of devices")
    global_rank: int = Field(0, description="")
    local_slot: int = Field(0, description="")
    payload: Optional[dict] = Field(None, description="base64/json config payload")

    @classmethod
    def from_launcher_args(cls) -> "LaunchConfig":
        import argparse
        import base64
        import json
        import os

        parser = argparse.ArgumentParser()
        parser.add_argument("--payload", type=str, default=None)
        args, _ = parser.parse_known_args()
        payload = None
        if args.payload:
            payload = json.loads(base64.urlsafe_b64decode(args.payload).decode())
        return cls(
            master_addr=os.environ.get("MASTER_ADDR", "127.0.0.1"),
            master_port=int(os.environ.get("MASTER_PORT", "29500")),
            world_size=int(os.environ.get("WORLD_SIZE", "1")),
            global_rank=int(os.environ.get("RANK", "0")),
            local_slot=int(os.environ.get("LOCAL_SLOT", "0")),
            payload=payload,
        )
