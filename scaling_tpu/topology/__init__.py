from .config import ActivationCheckpointingType, PipePartitionMethod, TopologyConfig
from .rng import RngTracker
from .topology import DATA_AXIS, MESH_AXES, MODEL_AXIS, PIPE_AXIS, Topology

__all__ = [
    "ActivationCheckpointingType",
    "PipePartitionMethod",
    "TopologyConfig",
    "RngTracker",
    "Topology",
    "DATA_AXIS",
    "MESH_AXES",
    "MODEL_AXIS",
    "PIPE_AXIS",
]
