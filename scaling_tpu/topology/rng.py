"""Deterministic RNG key plumbing.

The reference needs a CUDA RNG state tracker so all model-parallel ranks draw
identical dropout masks (reference: src/scaling/core/topology/rng_tracker.py).
With stateless ``jax.random`` the whole apparatus collapses to key
derivation: one base key per training run, folded with (step, layer, name)
tags. Under jit+sharding every device computes its slice of the same global
mask, so model-parallel consistency is automatic.
"""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp


def _tag_to_int(tag: str) -> int:
    return int.from_bytes(hashlib.md5(tag.encode()).digest()[:4], "little")


class RngTracker:
    """Derives per-(step, purpose) keys from a single seed."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self._base = jax.random.PRNGKey(self.seed)

    def base_key(self) -> jax.Array:
        return self._base

    def key(self, *tags: str | int) -> jax.Array:
        k = self._base
        for tag in tags:
            data = _tag_to_int(tag) if isinstance(tag, str) else int(tag)
            k = jax.random.fold_in(k, data)
        return k

    def step_key(self, step: jax.Array | int, *tags: str | int) -> jax.Array:
        """Key usable inside jit: fold the (traced) step counter last."""
        k = self.key(*tags)
        return jax.random.fold_in(k, jnp.asarray(step, dtype=jnp.uint32))
