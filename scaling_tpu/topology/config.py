"""Topology configuration.

Validates and derives the 3D parallel layout (pipe x data x model) and the
batch hierarchy (global = micro x grad_accum x dp). Field surface matches the
reference so configs run unchanged
(reference: src/scaling/core/topology/topology_config.py:20-207).
"""

from __future__ import annotations

from enum import Enum
from typing import Any, List, Optional

from pydantic import Field, model_validator

from ..config import BaseConfig


class PipePartitionMethod(Enum):
    UNIFORM = "uniform"
    BALANCED = "balanced"


class ActivationCheckpointingType(Enum):
    EVERY_PIPE_STAGE = "every_pipe_stage"
    EVERY_LAYER = "every_layer"
    # every_layer granularity, but matmul outputs are SAVED instead of
    # recomputed (jax dots_with_no_batch_dims_saveable policy): ~one extra
    # elementwise forward instead of a full forward — the usual sweet spot
    # when HBM allows it
    EVERY_LAYER_SAVE_DOTS = "every_layer_save_dots"
    DISABLED = "disabled"


class ContextParallelVariant(Enum):
    RING = "ring"
    ULYSSES = "ulysses"


class TopologyConfig(BaseConfig):
    global_rank: Optional[int] = Field(None, description="", ge=0)

    world_size: int = Field(description="", gt=0)

    local_slot: Optional[int] = Field(None, description="", ge=0)

    model_parallel_size: int = Field(description="", gt=0)

    pipe_parallel_size: int = Field(description="", gt=0)

    data_parallel_size: int = Field(description="", gt=0)

    context_parallel_size: int = Field(
        1,
        description="context parallelism: activations shard along the "
        "sequence dim over a 'context' mesh axis. A capability beyond the "
        "reference (which caps context at per-device memory, SURVEY §5). "
        "Requires pipe_parallel_size == 1.",
        gt=0,
    )

    context_parallel_variant: ContextParallelVariant = Field(
        ContextParallelVariant.RING,
        description="how attention crosses the context axis: 'ring' rotates "
        "unrepeated K/V blocks over ICI collective-permute (O(s/cp) memory, "
        "best for very long sequences); 'ulysses' all-to-alls heads for "
        "sequence so each device attends its n/cp heads over the full "
        "sequence (two collectives per layer, needs heads divisible by cp)",
    )

    global_batch_size: int = Field(
        description="global train batch size including all gradient accumulation steps",
        gt=0,
    )

    micro_batch_size: int = Field(
        description="Batch size for one training micro step. This is used when the "
        "global_batch_size cannot fit in device memory to determine the number of "
        "gradient accumulation steps.",
        gt=0,
    )

    gradient_accumulation_steps: int = Field(
        description="Number of gradient accumulation steps. This is used when the "
        "global_batch_size cannot fit in device memory to determine the number of "
        "gradient accumulation steps.",
        gt=0,
    )

    pipe_virtual_size: int = Field(
        1,
        description="interleaved virtual pipeline stages per physical stage "
        "(Megatron-LM, arxiv 2104.04473): the layer stack is split into "
        "pipe_parallel_size * pipe_virtual_size chunks assigned round-robin "
        "over the stages, and micro-batches circulate v times through the "
        "stage ring. Fill/drain shrinks from (pp-1) full-stage ticks to "
        "(pp-1) thin virtual-stage ticks (~v x less bubble) at the cost of "
        "v x more stage-shift collective-permutes. Requires "
        "pipe_parallel_size > 1, num_layers divisible by pp * v, and "
        "gradient_accumulation_steps divisible by pp (micro-batches are "
        "injected in full groups of pp).",
        gt=0,
    )

    pipe_token_slices: int = Field(
        1,
        description="TeraPipe-style token slicing (arxiv 2102.07988): each "
        "micro-batch's sequence is split into this many causal chunks and "
        "the chunks are pipelined through the stages, for the "
        "long-sequence / low-gradient-accumulation regime where micro-batch "
        "parallelism alone cannot fill the pipeline. Exact math: attention "
        "runs against a per-stage KV cache of the earlier chunks "
        "(segment-aware, so packed-document masking is preserved). Requires "
        "pipe_parallel_size > 1 and sequence_length divisible by the slice "
        "count; mutually exclusive with pipe_virtual_size > 1.",
        gt=0,
    )

    pipe_partition_method: PipePartitionMethod = Field(
        PipePartitionMethod.UNIFORM,
        description="Method to assign layers to pipeline stages",
    )

    pipe_partition_overwrite: Optional[List[int]] = Field(
        None, description="manually set pipe partitions"
    )

    activation_checkpointing_type: ActivationCheckpointingType = Field(
        ActivationCheckpointingType.DISABLED,
        description="disabled | every_layer (full per-layer recompute) | "
        "every_layer_save_dots (per-layer remat that keeps matmul outputs "
        "— less recompute, more memory) | every_pipe_stage",
    )

    sequence_parallel: bool = Field(
        False,
        description="shard activations along the sequence dimension over the model "
        "axis between tensor-parallel regions (Megatron-style SP)",
    )

    @model_validator(mode="before")
    @classmethod
    def _derive(cls, values: dict[Any, Any]) -> dict[Any, Any]:
        if not isinstance(values, dict):
            return values

        mp = values.get("model_parallel_size")
        pp = values.get("pipe_parallel_size")
        dp = values.get("data_parallel_size")
        cp = values.get("context_parallel_size") or 1
        world = values.get("world_size")

        sizes = [mp, pp, dp, world]
        if sum(1 for s in sizes if s is not None) < 3:
            raise AssertionError(
                "At least 3 out of 4 parallelization parameters (model_parallel_size, "
                "pipe_parallel_size, data_parallel_size and world_size) need to be set."
            )
        if world is None:
            world = mp * pp * dp * cp
        if mp is None:
            mp = world // (pp * dp * cp)
        if pp is None:
            pp = world // (mp * dp * cp)
        if dp is None:
            dp = world // (mp * pp * cp)
        if mp * pp * dp * cp != world:
            raise AssertionError(
                f"world_size {world} does not equal model_parallel_size ({mp}) x "
                f"pipe_parallel_size ({pp}) x data_parallel_size ({dp}) x "
                f"context_parallel_size ({cp})."
            )
        if cp > 1 and pp > 1:
            raise AssertionError(
                "context_parallel_size > 1 requires pipe_parallel_size == 1 "
                "(ring attention replaces pipelining for long sequences)"
            )

        gbs = values.get("global_batch_size")
        mbs = values.get("micro_batch_size")
        gas = values.get("gradient_accumulation_steps")
        if sum(1 for s in (gbs, mbs, gas) if s is not None) < 2:
            raise AssertionError(
                "At least 2 out of 3 batch size parameters (global_batch_size, "
                "micro_batch_size, and gradient_accumulation_steps) need to be set."
            )
        if gas is None:
            gas = gbs // (mbs * dp)
        if mbs is None:
            mbs = gbs // (gas * dp)
        if gbs is None:
            gbs = mbs * gas * dp
        if gbs != mbs * gas * dp:
            raise AssertionError(
                f"global_batch_size {gbs} does not equal the product of "
                f"micro_batch_size ({mbs}) and gradient_accumulation_steps ({gas}) "
                f"and data_parallel_size ({dp})."
            )

        vpp = values.get("pipe_virtual_size") or 1
        slices = values.get("pipe_token_slices") or 1
        if vpp > 1 and pp < 2:
            raise AssertionError(
                "pipe_virtual_size > 1 requires pipe_parallel_size > 1 "
                "(virtual stages interleave over the physical stage ring)"
            )
        if slices > 1 and pp < 2:
            raise AssertionError(
                "pipe_token_slices > 1 requires pipe_parallel_size > 1 "
                "(token slices pipeline through the physical stages)"
            )
        if vpp > 1 and slices > 1:
            raise AssertionError(
                "pipe_virtual_size and pipe_token_slices are mutually "
                "exclusive (the executor interleaves micro-batches OR "
                "token slices, not both)"
            )
        if vpp > 1 and gas % pp != 0:
            raise AssertionError(
                f"interleaved virtual stages need gradient_accumulation_steps "
                f"({gas}) divisible by pipe_parallel_size ({pp}): micro-"
                f"batches are injected in full groups of pp"
            )

        values.update(
            world_size=world,
            model_parallel_size=mp,
            pipe_parallel_size=pp,
            data_parallel_size=dp,
            context_parallel_size=cp,
            global_batch_size=gbs,
            micro_batch_size=mbs,
            gradient_accumulation_steps=gas,
        )
        return values
