"""3D device topology as a view over a ``jax.sharding.Mesh``.

The reference maps ``world_size`` NCCL ranks onto a ``(pipe, data, model)``
grid and builds process groups for every sub-axis
(reference: src/scaling/core/topology/topology.py:20-441). On TPU the same
layout is a single ``Mesh`` with axes ``("pipe", "data", "model")``; XLA
emits the collectives, so the process-group machinery disappears. This class
keeps the reference's rank-accessor surface (flat-rank math, io-rank
predicates) because checkpoint naming, the pipeline schedule simulator and
the trainer's logging all speak in those terms.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import ActivationCheckpointingType, TopologyConfig

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
CONTEXT_AXIS = "context"
MODEL_AXIS = "model"
MESH_AXES = (PIPE_AXIS, DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS)


class Topology:
    """Device layout: ``world_size`` devices reshaped to (pipe, data, model)."""

    def __init__(
        self,
        config: TopologyConfig,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        self.config = config
        if devices is None:
            devices = jax.devices()
        if len(devices) < config.world_size:
            raise ValueError(
                f"topology needs {config.world_size} devices, found {len(devices)}"
            )
        grid = np.asarray(devices[: config.world_size]).reshape(
            config.pipe_parallel_size,
            config.data_parallel_size,
            config.context_parallel_size,
            config.model_parallel_size,
        )
        self.mesh = Mesh(grid, MESH_AXES)
        self._device_count = config.world_size

    # ------------------------------------------------------------- sizes
    @property
    def world_size(self) -> int:
        return self.config.world_size

    @property
    def model_parallel_size(self) -> int:
        return self.config.model_parallel_size

    @property
    def pipe_parallel_size(self) -> int:
        return self.config.pipe_parallel_size

    @property
    def data_parallel_size(self) -> int:
        return self.config.data_parallel_size

    @property
    def context_parallel_size(self) -> int:
        return self.config.context_parallel_size

    @property
    def pipe_virtual_size(self) -> int:
        return self.config.pipe_virtual_size

    @property
    def pipe_token_slices(self) -> int:
        return self.config.pipe_token_slices

    @property
    def context_parallel_variant(self) -> str:
        return self.config.context_parallel_variant.value

    @property
    def micro_batch_size(self) -> int:
        return self.config.micro_batch_size

    @property
    def global_batch_size(self) -> int:
        return self.config.global_batch_size

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    @property
    def sequence_parallel(self) -> bool:
        return self.config.sequence_parallel

    @property
    def activation_checkpointing_type(self) -> ActivationCheckpointingType:
        return self.config.activation_checkpointing_type

    @property
    def is_distributed_initialized(self) -> bool:
        return True

    # -------------------------------------------------------- rank math
    # Flat-rank layout: rank = (((pp_rank * dp + dp_rank) * cp + cp_rank)
    # * mp + mp_rank), i.e. arange(world).reshape(pp, dp, cp, mp) — with
    # cp == 1 this is the reference convention (topology.py:45-49) so
    # checkpoint artifact names line up.
    def get_global_rank(
        self,
        pipe_parallel_rank: int,
        data_parallel_rank: int,
        model_parallel_rank: int,
        context_parallel_rank: int = 0,
    ) -> int:
        cfg = self.config
        assert 0 <= pipe_parallel_rank < cfg.pipe_parallel_size
        assert 0 <= data_parallel_rank < cfg.data_parallel_size
        assert 0 <= context_parallel_rank < cfg.context_parallel_size
        assert 0 <= model_parallel_rank < cfg.model_parallel_size
        return (
            (pipe_parallel_rank * cfg.data_parallel_size + data_parallel_rank)
            * cfg.context_parallel_size
            + context_parallel_rank
        ) * cfg.model_parallel_size + model_parallel_rank

    def pipe_parallel_rank_of(self, global_rank: int) -> int:
        cfg = self.config
        return global_rank // (
            cfg.data_parallel_size * cfg.context_parallel_size * cfg.model_parallel_size
        )

    def data_parallel_rank_of(self, global_rank: int) -> int:
        cfg = self.config
        return (
            global_rank // (cfg.context_parallel_size * cfg.model_parallel_size)
        ) % cfg.data_parallel_size

    def context_parallel_rank_of(self, global_rank: int) -> int:
        return (global_rank // self.config.model_parallel_size) % self.config.context_parallel_size

    def model_parallel_rank_of(self, global_rank: int) -> int:
        return global_rank % self.config.model_parallel_size

    # The rank this process "is" — in single-controller SPMD there is one
    # python process driving all devices; for multi-host, process_index 0
    # plays the coordinator role. global_rank may be pinned by the launcher.
    @property
    def global_rank(self) -> int:
        if self.config.global_rank is not None:
            return self.config.global_rank
        return 0

    @property
    def pipe_parallel_rank(self) -> int:
        return self.pipe_parallel_rank_of(self.global_rank)

    @property
    def data_parallel_rank(self) -> int:
        return self.data_parallel_rank_of(self.global_rank)

    @property
    def model_parallel_rank(self) -> int:
        return self.model_parallel_rank_of(self.global_rank)

    def is_first_pipe_parallel_rank(self, global_rank: Optional[int] = None) -> bool:
        r = self.global_rank if global_rank is None else global_rank
        return self.pipe_parallel_rank_of(r) == 0

    def is_last_pipe_parallel_rank(self, global_rank: Optional[int] = None) -> bool:
        r = self.global_rank if global_rank is None else global_rank
        return self.pipe_parallel_rank_of(r) == self.config.pipe_parallel_size - 1

    def is_io_rank(self, global_rank: Optional[int] = None) -> bool:
        """Ranks that touch input data: first/last pipe stage at mp rank 0."""
        r = self.global_rank if global_rank is None else global_rank
        return self.model_parallel_rank_of(r) == 0 and (
            self.is_first_pipe_parallel_rank(r) or self.is_last_pipe_parallel_rank(r)
        )

    # --------------------------------------------------------- shardings
    def named_sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self) -> NamedSharding:
        """Batch-leading arrays: sharded over the data axis."""
        return NamedSharding(self.mesh, P(DATA_AXIS))

    @contextmanager
    def activate(self) -> Iterator[Mesh]:
        with self.mesh:
            yield self.mesh
