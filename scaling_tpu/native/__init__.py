"""Native (C++) hot paths with transparent Python fallbacks.

The reference framework's native surface is all imported (NCCL, flash-attn,
torch internals — reference SURVEY §2.3); here the compute hot path is
XLA/Pallas and the *runtime* hot paths (data indexing) are first-party C++,
compiled on demand with the system toolchain and loaded via ctypes. Missing
compiler → the callers fall back to their Python implementations.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

_SRC_DIR = Path(__file__).parent
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = _SRC_DIR / "pack_index.cpp"
    lib_path = _SRC_DIR / "libpack_index.so"
    try:
        if not lib_path.exists() or lib_path.stat().st_mtime < src.stat().st_mtime:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", str(lib_path), str(src)],
                check=True, capture_output=True, timeout=120,
            )
        lib = ctypes.CDLL(str(lib_path))
        lib.build_pack_index.restype = ctypes.c_int64
        lib.build_pack_index.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
        ]
        return lib
    except Exception:
        return None


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if not _TRIED:
        _LIB = _build_and_load()
        _TRIED = True
    return _LIB


def native_available() -> bool:
    return _lib() is not None


def build_pack_index(
    doc_sizes: np.ndarray, sequence_length: int, allow_incomplete_every_n: int
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """(starts, ends) spans for only_full_sequences packing, or None if the
    native library is unavailable (caller falls back to Python)."""
    lib = _lib()
    if lib is None:
        return None
    sizes = np.ascontiguousarray(doc_sizes, dtype=np.int64)
    total = int(sizes.sum())
    L = int(sequence_length)
    # upper bound: every doc boundary plus every mid-doc cut
    max_spans = len(sizes) + total // max(L, 1) + 2
    starts = np.empty(max_spans, dtype=np.int64)
    ends = np.empty(max_spans, dtype=np.int64)
    n = lib.build_pack_index(
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(sizes), L, int(allow_incomplete_every_n),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        max_spans,
    )
    return starts[:n].copy(), ends[:n].copy()
