// Document-pack index builder (C++ hot path).
//
// Mirrors TextDataset._build_pack_index (reference algorithm:
// src/scaling/transformer/data/text_dataset.py:130-300): greedy packing of
// whole documents into fixed windows, with over-long-document splitting and
// the every-n incomplete-sequence exception. Per-corpus cost is O(num_docs)
// — for billion-document corpora the Python loop is minutes, this is
// milliseconds. Exposed via ctypes (extern "C", raw pointers); the Python
// caller owns all memory.
//
// Build: g++ -O3 -shared -fPIC -o libpack_index.so pack_index.cpp

#include <cstdint>
#include <vector>

extern "C" {

// Returns the number of spans; writes up to max_spans (start, end) pairs.
// A span of L+1 tokens overlapping its neighbour by 1 marks a mid-document
// cut; other spans end at document boundaries.
int64_t build_pack_index(
    const int64_t* doc_sizes,
    int64_t num_docs,
    int64_t sequence_length,
    int64_t allow_incomplete_every_n,
    int64_t* out_starts,
    int64_t* out_ends,
    int64_t max_spans) {
  const int64_t L = sequence_length;
  int64_t total = 0;
  for (int64_t d = 0; d < num_docs; ++d) total += doc_sizes[d];

  int64_t n_spans = 0;
  auto emit = [&](int64_t s, int64_t e) {
    if (e - s >= 2 && s + 2 <= total && n_spans < max_spans) {
      out_starts[n_spans] = s;
      out_ends[n_spans] = e;
      ++n_spans;
    }
  };

  int64_t window_start = 0;
  int64_t since_cut = 0;
  int64_t doc_start = 0;
  const int64_t every_n = allow_incomplete_every_n;

  for (int64_t d = 0; d < num_docs; ++d) {
    const int64_t doc_end = doc_start + doc_sizes[d];
    if (doc_end - window_start <= L) {
      doc_start = doc_end;
      continue;  // document fits into the open window
    }
    if (every_n > 0 && since_cut + 1 >= every_n) {
      // the every-n exception: cut mid-document with 1-token overlap
      while (doc_end - window_start > L) {
        emit(window_start, window_start + L + 1);
        window_start += L;
      }
      since_cut = 0;
      doc_start = doc_end;
      continue;
    }
    // close the open window at this document's boundary
    if (doc_start > window_start) {
      emit(window_start, doc_start);
      ++since_cut;
    }
    window_start = doc_start;
    if (doc_end - window_start > L) {
      // over-long document: full L+1 windows, tail dropped to realign
      while (doc_end - window_start > L) {
        emit(window_start, window_start + L + 1);
        window_start += L;
        since_cut = 0;
      }
      window_start = doc_end;
    }
    doc_start = doc_end;
  }
  if (total - window_start >= 2) emit(window_start, total);
  return n_spans;
}

}  // extern "C"
