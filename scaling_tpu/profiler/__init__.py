from .profiler import Profiler, ProfilerConfig, SynchronizedTimer

__all__ = ["Profiler", "ProfilerConfig", "SynchronizedTimer"]
