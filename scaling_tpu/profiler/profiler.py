"""Profiling: synchronized timers, a step-windowed collector, XLA traces.

(reference: src/scaling/core/profiler/ — ``SynchronizedTimer`` brackets with
``torch.cuda.synchronize`` (timer.py:16-23); ``Profiler`` collects
per-instruction observations inside a configured step window and gathers
them to rank 0 as JSON (profiler.py:79-104)). The TPU equivalents:

- ``SynchronizedTimer`` brackets with ``jax.block_until_ready`` — the
  single-controller analogue of a device sync;
- the instruction loop is one fused XLA program, so per-instruction timers
  become per-step phase timers (data load / step / sync) plus an optional
  ``jax.profiler`` trace of the window, which exposes the true per-op
  schedule in TensorBoard / Perfetto — strictly more detail than the
  reference's hand-rolled instruction timers;
- observations are written as one JSON, feeding the pipeline schedule
  simulator (parallel/pipeline_schedule.py) exactly like the reference's
  profile JSON feeds its SimulationEngine (base.py:276-595).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
from pydantic import Field

from ..config import BaseConfig
from ..logging import logger


class ProfilerConfig(BaseConfig):
    profile_steps: int = Field(0, description="number of steps to profile; 0 disables")
    profile_start_at_step: int = Field(
        10, description="first profiled step (skips compile/warmup)"
    )
    profiler_output: Optional[Path] = Field(
        None, description="where the observations JSON (and XLA trace dir) go"
    )
    capture_xla_trace: bool = Field(
        False, description="also capture a jax.profiler trace of the window "
        "(TensorBoard/Perfetto-compatible)"
    )


class SynchronizedTimer:
    """Wall clock around device work; stop() drains outstanding computation
    so the measured span covers it (reference: timer.py:7-35)."""

    def __init__(self, name: str = ""):
        self.name = name
        self._start: Optional[float] = None
        self.durations: List[float] = []

    def start(self) -> None:
        self._start = time.perf_counter()

    def stop(self, wait_for: Any = None) -> float:
        if wait_for is not None:
            jax.block_until_ready(wait_for)
        assert self._start is not None, "timer not started"
        d = time.perf_counter() - self._start
        self.durations.append(d)
        self._start = None
        return d

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Profiler:
    """Collects per-step phase timings inside the configured window."""

    def __init__(self, config: Optional[ProfilerConfig] = None):
        self.config = config or ProfilerConfig()
        self.observations: List[Dict[str, Any]] = []
        self._tracing = False

    def enabled_at(self, step: int) -> bool:
        c = self.config
        return (
            c.profile_steps > 0
            and c.profile_start_at_step <= step < c.profile_start_at_step + c.profile_steps
        )

    def begin_step(self, step: int) -> None:
        c = self.config
        if (
            c.capture_xla_trace
            and c.profiler_output is not None
            and step == c.profile_start_at_step
            and not self._tracing
        ):
            trace_dir = Path(c.profiler_output).parent / "xla_trace"
            trace_dir.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(trace_dir))
            self._tracing = True

    def record(self, step: int, durations: Dict[str, float]) -> None:
        if not self.enabled_at(step):
            return
        self.observations.append({"step": step, **durations})

    def end_step(self, step: int) -> None:
        c = self.config
        last = c.profile_start_at_step + c.profile_steps - 1
        if step == last:
            if self._tracing:
                jax.profiler.stop_trace()
                self._tracing = False
            self.flush()

    def close(self) -> None:
        """Abort-safe drain: stop an active XLA trace and flush whatever
        the window collected so far. A run that dies mid-window
        (NonFiniteLossError, SIGTERM drain, watchdog stall) previously
        lost EVERY observation and left the trace running; the trainer
        calls this from its ``finally`` so partial observations land.
        Idempotent — flush rewrites the same JSON on a clean exit."""
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            except RuntimeError as e:
                logger.warning(f"could not stop in-flight XLA trace: {e!r}")
            self._tracing = False
        self.flush()

    def flush(self) -> None:
        if self.config.profiler_output is None or not self.observations:
            return
        out = Path(self.config.profiler_output)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(self.observations, indent=2))
        logger.info(f"profiler: wrote {len(self.observations)} observations to {out}")
