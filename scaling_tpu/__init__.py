"""scaling_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of Aleph Alpha's
``scaling`` library (reference: marcobellagente93/scaling): 4-axis
parallelism (data x tensor x pipeline x context — ring or ulysses) over
one ``jax.sharding.Mesh``, Megatron-style sequence parallelism, ZeRO-1/3
optimizer-state (and FSDP param) sharding, mixture-of-experts with expert parallelism,
muP width-transferable hyperparameters, mixed precision with dynamic
loss scaling, activation rematerialisation, layout-independent npz or
orbax/tensorstore checkpoints, multi-host training over
``jax.distributed``, and a transformer suite (GQA, RoPE, SwiGLU,
sequence packing, local attention, LoRA/adapter/bitfit/softprompt
fine-tuning, batched KV-cached and tensor-parallel inference).

Layout:
  scaling_tpu.config     pydantic config base (yaml/json, templates)
  scaling_tpu.topology   4-axis device layout -> jax.sharding.Mesh
  scaling_tpu.data       memory-mapped datasets, deterministic loaders
  scaling_tpu.nn         functional layers + parameter metadata
  scaling_tpu.parallel   collectives, sharding rules, pipeline engine
  scaling_tpu.ops        Pallas TPU kernels (flash attention, fused norms)
  scaling_tpu.optimizer  AdamW w/ fp32 master, ZeRO-1/3, loss scaler, LR
  scaling_tpu.trainer    generic train loop + checkpoint orchestration
  scaling_tpu.models     model suites (transformer)
  scaling_tpu.determined optional Determined AI cluster glue
"""

__version__ = "0.4.0"
