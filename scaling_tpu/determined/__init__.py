"""Determined AI cluster glue (reference: ``core/determined/`` and
``core/trainer/trainer.py:317-553``).

TPU-first redesign: Determined is OPTIONAL infrastructure, not a trainer
dependency. The capability set the reference's glue provided — preemption
polling, metric reporting, checkpoint hand-off, latest-checkpoint
discovery — maps onto hooks the trainer already exposes (SIGTERM
save-and-exit, metric hooks, checkpoint hooks, a load-dir override). This
module wires a Determined core context into those hooks when, and only
when, the SDK is importable AND the process runs inside a Determined task;
everywhere else ``detect()`` returns None and training proceeds exactly as
before. The reference's Determined-side checkpoint GC
(``delete_preempted_checkpoints_determined``) is intentionally replaced by
the trainer's own stale-checkpoint GC, which runs on any cluster.
"""

from __future__ import annotations

import contextlib
import importlib
from pathlib import Path
from typing import Any, Iterator, Optional

from ..logging import logger

__all__ = ["DeterminedGlue"]


def _import_sdk():
    try:
        return importlib.import_module("determined")
    except ImportError:
        return None


class DeterminedGlue:
    """One live Determined core context, adapted to trainer hooks."""

    def __init__(self, det: Any, core_context: Any):
        self._det = det
        self._ctx = core_context
        # det.core.init() returns a context-manager Context; keep it open
        # for the training run and close it in close()
        self._core = (
            core_context.__enter__() if hasattr(core_context, "__enter__")
            else core_context
        )

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def detect(cls) -> Optional["DeterminedGlue"]:
        """A glue instance when running inside a Determined task, else None
        (SDK missing, or installed but no cluster info — e.g. local runs)."""
        det = _import_sdk()
        if det is None:
            return None
        try:
            if det.get_cluster_info() is None:
                return None
            core_context = det.core.init()
        except Exception as e:  # a broken cluster env must not kill training
            logger.warning(f"determined detected but init failed: {e}")
            return None
        logger.info("running under Determined: preemption polling, metric "
                    "reporting and checkpoint hand-off active")
        return cls(det, core_context)

    def close(self) -> None:
        if hasattr(self._ctx, "__exit__"):
            self._ctx.__exit__(None, None, None)

    # ------------------------------------------------------------ adapters
    def should_preempt(self) -> bool:
        try:
            return bool(self._core.preempt.should_preempt())
        except Exception as e:
            # polled after EVERY step: a transient master error must not
            # kill a training run that was healthy moments ago (the real
            # preemption signal will come back on a later poll)
            logger.warning(f"determined preempt poll failed: {e}")
            return False

    def report_metrics(self, metrics: dict, step: int) -> None:
        try:
            numeric = {}
            for k, v in metrics.items():
                # hasattr(__float__) admits multi-element arrays whose
                # float() raises; the conversion stays inside the guard
                if isinstance(v, (int, float)) or hasattr(v, "__float__"):
                    try:
                        numeric[k] = float(v)
                    except (TypeError, ValueError):
                        continue
            self._core.train.report_training_metrics(
                steps_completed=int(step), metrics=numeric
            )
        except Exception as e:  # metrics must never abort a step
            logger.warning(f"determined metric report failed: {e}")

    def upload_checkpoint(self, step_dir: Path | str, step: int) -> None:
        """Hand a finished on-disk checkpoint to Determined's storage
        (reference: ``determined_save_checkpoint``, trainer.py:356-414 —
        there the save happens INTO determined storage; here the trainer's
        own save stays canonical and determined receives a copy, so the
        same checkpoint works on and off the cluster).

        Multi-host: the orbax backend writes each host's shards to that
        host's own ``save_dir``, so every process uploads with
        ``shard=True`` and Determined merges. If the installed SDK lacks
        sharded upload, process 0 uploads alone — complete only when
        ``save_dir`` is a shared filesystem, so that fallback warns."""
        import jax

        metadata = {"steps_completed": int(step)}
        try:
            if jax.process_count() > 1:
                try:
                    self._core.checkpoint.upload(
                        str(step_dir), metadata=metadata, shard=True
                    )
                    return
                except TypeError:
                    if jax.process_index() != 0:
                        return
                    logger.warning(
                        "determined SDK lacks sharded upload; uploading from "
                        "process 0 only — the checkpoint is complete only if "
                        "save_dir is a shared filesystem"
                    )
            self._core.checkpoint.upload(str(step_dir), metadata=metadata)
        except Exception as e:
            logger.warning(f"determined checkpoint upload failed: {e}")

    @contextlib.contextmanager
    def latest_checkpoint(self) -> Iterator[Optional[Path]]:
        """Download path of the experiment's latest checkpoint, or None on
        a fresh start (reference: trainer.py:416-428)."""
        info = self._det.get_cluster_info()
        latest = getattr(info, "latest_checkpoint", None) if info else None
        if latest is None:
            yield None
            return
        with self._core.checkpoint.restore_path(latest) as path:
            yield Path(path)

    # ------------------------------------------------------------ wiring
    def attach(self, trainer: Any) -> None:
        """Plug this context into the trainer's generic hook points.

        Preemption is polled on EVERY process (Determined expects all
        workers to call should_preempt). Metric reporting happens once
        per job, from process 0. Checkpoint upload runs on every process:
        multi-host saves are per-host shards (see upload_checkpoint), and
        single-process runs upload exactly once anyway."""
        import jax

        trainer.external_preemption = self.should_preempt
        trainer.checkpoint_hooks.append(self.upload_checkpoint)
        if jax.process_index() == 0:
            trainer.metrics_hooks.append(self.report_metrics)
