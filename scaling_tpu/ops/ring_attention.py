"""Ring attention: context parallelism over the ``context`` mesh axis.

A capability beyond the reference, which bounds trained context by
per-device memory (SURVEY §5: "no ring attention, context parallelism,
blockwise attention, or Ulysses"). Design (Ring Attention with Blockwise
Transformers, Liu et al. 2023, expressed TPU-natively):

- activations are sharded along the sequence dim over the ``context`` axis;
- each device keeps its Q shard resident and computes attention against one
  K/V block at a time, merging with the online-softmax recurrence;
- K/V blocks (with their segment ids) rotate around the ring via
  ``lax.ppermute`` — ICI neighbour exchange — inside a ``lax.scan``;
- causal masking uses absolute sequence indices derived from each block's
  ring offset, so packing (segment ids) and causality behave exactly like
  the single-device path;
- within each ring step the K/V block is consumed in CHUNKS with the same
  online-softmax recurrence, so the materialized score tile is
  (s_loc x chunk), never (s_loc x s_loc);
- the backward pass is a CUSTOM VJP (the flash-attention recipe, not
  autodiff of the forward scan): forward saves only the output and the
  per-query logsumexp, and the gradient runs a second ring pass that
  recomputes each (s_loc x chunk) probability tile from them, with dK/dV
  accumulators rotating alongside their K/V blocks. Autodiff of the scan
  would stack per-chunk residuals — O(s_loc^2) per layer — exactly the
  memory the chunking removes.

Peak memory per device, forward AND backward: O(s/cp) for
Q/K/V/O/dQ/dK/dV + one rotating K/V (+dK/dV) block + one (s_loc x chunk)
score tile — sequence length scales linearly with the ring size.
"""

from __future__ import annotations

import functools
import warnings
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..topology.topology import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS

_NEG = -1e9
_DEFAULT_KV_CHUNK = 1024


def _kv_chunk(s_loc: int, requested: Optional[int] = None) -> int:
    """Largest divisor of ``s_loc`` at most the requested chunk (default
    _DEFAULT_KV_CHUNK): the score tile is (s_loc x chunk), so the chunk
    bounds per-step memory while the divisor constraint keeps the inner
    scan shape static. When the best divisor is a sliver (< 128 — e.g. a
    prime s_loc), one full tile wins: an s_loc-step scan of 1-wide
    einsums would blow up compile and step time by orders of magnitude
    for a memory bound nobody asked for."""
    cap = min(requested or _DEFAULT_KV_CHUNK, s_loc)
    for c in range(cap, 0, -1):
        if s_loc % c == 0:
            if c >= min(128, cap):
                return c
            break
    if s_loc > cap:
        # the memory bound the chunking exists for is silently gone: the
        # score tile regresses to (s_loc x s_loc). Long-context configs
        # must hear about it — pick a per-device sequence length with a
        # divisor in [128, chunk] to restore the bound.
        warnings.warn(
            f"ring attention: per-device sequence length {s_loc} has no "
            f"divisor in [{min(128, cap)}, {cap}]; falling back to one "
            f"full ({s_loc} x {s_loc}) score tile per step, losing the "
            f"chunked memory bound"
        )
    return s_loc


def _chunk_mask(seg_q, seg_c, q_pos, k_pos_c, causal):
    """(b, s_q, chunk) bool — packing + causality for one K/V chunk."""
    allowed = seg_q[:, :, None] == seg_c[:, None, :]
    if causal:
        allowed = allowed & (k_pos_c[None, None, :] <= q_pos[None, :, None])
    return allowed


def _split_chunks(x, n_chunks, chunk):
    """(b, s_loc, ...) -> (n_chunks, b, chunk, ...) for scan xs."""
    b = x.shape[0]
    return x.reshape(b, n_chunks, chunk, *x.shape[2:]).swapaxes(0, 1)


def _ring_fwd_pass(q, k, v, seg, axis_name, causal, sm_scale, kv_chunk):
    """Blockwise forward: returns (out, lse) with lse = m + log(l), the
    only residuals the backward needs."""
    ring = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_loc, n, d = q.shape
    n_kv = k.shape[2]
    g = n // n_kv  # query heads per kv head; rotating unrepeated K/V keeps
    # the ring's ICI traffic at 1/g of the repeated layout
    chunk = _kv_chunk(s_loc, kv_chunk)
    n_chunks = s_loc // chunk

    q_pos = my_idx * s_loc + jnp.arange(s_loc)
    qf = q.astype(jnp.float32).reshape(b, s_loc, n_kv, g, d) * sm_scale

    def step(carry, _):
        m, l, acc, k_blk, v_blk, seg_blk, owner = carry
        k_pos0 = owner * s_loc

        def inner(c2, xs):
            m, l, acc = c2
            k_c, v_c, seg_c, ci = xs
            k_pos_c = k_pos0 + ci * chunk + jnp.arange(chunk)
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k_c.astype(jnp.float32))
            allowed = _chunk_mask(seg, seg_c, q_pos, k_pos_c, causal)
            masked = allowed[:, None, None, :, :]
            s = jnp.where(masked, s, _NEG)
            m_new = jnp.maximum(m, s.max(axis=-1))  # (b, h, g, sq)
            # explicit zeroing: for a fully-masked chunk s == m_new == _NEG
            # and exp(0) would be 1 — the mask, not the exp, kills them
            p = jnp.exp(s - m_new[..., None]) * masked
            correction = jnp.exp(m - m_new)
            l_new = l * correction + p.sum(axis=-1)
            acc_new = (
                acc * jnp.moveaxis(correction, 3, 1)[..., None]
                + jnp.einsum("bhgqk,bkhd->bqhgd", p, v_c.astype(jnp.float32))
            )
            return (m_new, l_new, acc_new), None

        (m, l, acc), _ = jax.lax.scan(
            inner,
            (m, l, acc),
            (
                _split_chunks(k_blk, n_chunks, chunk),
                _split_chunks(v_blk, n_chunks, chunk),
                _split_chunks(seg_blk, n_chunks, chunk),
                jnp.arange(n_chunks),
            ),
        )
        # rotate the K/V block to the next ring neighbour
        perm = [(i, (i + 1) % ring) for i in range(ring)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
        owner = jax.lax.ppermute(owner, axis_name, perm)
        return (m, l, acc, k_blk, v_blk, seg_blk, owner), None

    m0 = jnp.full((b, n_kv, g, s_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, n_kv, g, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, s_loc, n_kv, g, d), jnp.float32)
    carry = (m0, l0, acc0, k, v, seg, my_idx)
    (m, l, acc, *_), _ = jax.lax.scan(step, carry, None, length=ring)
    l_safe = jnp.maximum(l, 1e-20)
    out = acc / jnp.moveaxis(l_safe, 3, 1)[..., None]
    lse = m + jnp.log(l_safe)  # (b, h, g, sq)
    return out.reshape(b, s_loc, n, d).astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_core(q, k, v, seg, axis_name, causal, sm_scale, kv_chunk):
    out, _ = _ring_fwd_pass(q, k, v, seg, axis_name, causal, sm_scale, kv_chunk)
    return out


def _ring_core_fwd(q, k, v, seg, axis_name, causal, sm_scale, kv_chunk):
    out, lse = _ring_fwd_pass(q, k, v, seg, axis_name, causal, sm_scale, kv_chunk)
    return out, (q, k, v, seg, out, lse)


def _ring_core_bwd(axis_name, causal, sm_scale, kv_chunk, res, dout):
    """Second ring pass: probability tiles recompute from (q, k_blk, lse);
    dK/dV accumulators rotate WITH their K/V blocks, so after a full cycle
    every block arrives home carrying every device's contribution.

    Flash backward identities (P the normalized probs):
      dV_j  = sum_i P_ij dO_i
      dP_ij = dO_i · V_j
      dS_ij = P_ij (dP_ij - delta_i),  delta_i = dO_i · O_i
      dQ_i  = sm_scale * sum_j dS_ij K_j ;  dK_j = sum_i dS_ij Q_i*sm_scale
    """
    q, k, v, seg, out, lse = res
    ring = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_loc, n, d = q.shape
    n_kv = k.shape[2]
    g = n // n_kv
    chunk = _kv_chunk(s_loc, kv_chunk)
    n_chunks = s_loc // chunk

    q_pos = my_idx * s_loc + jnp.arange(s_loc)
    qf = q.astype(jnp.float32).reshape(b, s_loc, n_kv, g, d) * sm_scale
    do = dout.astype(jnp.float32).reshape(b, s_loc, n_kv, g, d)
    of = out.astype(jnp.float32).reshape(b, s_loc, n_kv, g, d)
    # delta_i = rowsum(dO * O), laid out like lse: (b, h, g, sq)
    delta = jnp.moveaxis(jnp.sum(do * of, axis=-1), 1, 3)

    def step(carry, _):
        dq, k_blk, v_blk, dk_blk, dv_blk, seg_blk, owner = carry
        k_pos0 = owner * s_loc

        def inner(c2, xs):
            dq = c2
            k_c, v_c, seg_c, ci = xs
            k_pos_c = k_pos0 + ci * chunk + jnp.arange(chunk)
            s = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k_c.astype(jnp.float32))
            allowed = _chunk_mask(seg, seg_c, q_pos, k_pos_c, causal)
            masked = allowed[:, None, None, :, :]
            # lse is a true per-query constant, so P normalizes directly;
            # fully-masked rows have lse = NEG + log(eps) — the mask wins
            p = jnp.exp(jnp.where(masked, s, _NEG) - lse[..., None]) * masked
            dp = jnp.einsum("bqhgd,bkhd->bhgqk", do, v_c.astype(jnp.float32))
            ds = p * (dp - delta[..., None])
            dq = dq + jnp.einsum("bhgqk,bkhd->bqhgd", ds, k_c.astype(jnp.float32))
            dk_c = jnp.einsum("bhgqk,bqhgd->bkhd", ds, qf)
            dv_c = jnp.einsum("bhgqk,bqhgd->bkhd", p, do)
            return dq, (dk_c, dv_c)

        dq, (dk_cs, dv_cs) = jax.lax.scan(
            inner,
            dq,
            (
                _split_chunks(k_blk, n_chunks, chunk),
                _split_chunks(v_blk, n_chunks, chunk),
                _split_chunks(seg_blk, n_chunks, chunk),
                jnp.arange(n_chunks),
            ),
        )
        # (n_chunks, b, chunk, h, d) -> (b, s_loc, h, d)
        dk_blk = dk_blk + dk_cs.swapaxes(0, 1).reshape(b, s_loc, n_kv, d)
        dv_blk = dv_blk + dv_cs.swapaxes(0, 1).reshape(b, s_loc, n_kv, d)
        perm = [(i, (i + 1) % ring) for i in range(ring)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
        seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
        owner = jax.lax.ppermute(owner, axis_name, perm)
        return (dq, k_blk, v_blk, dk_blk, dv_blk, seg_blk, owner), None

    dq0 = jnp.zeros((b, s_loc, n_kv, g, d), jnp.float32)
    dkv0 = jnp.zeros((b, s_loc, n_kv, d), jnp.float32)
    carry = (dq0, k, v, dkv0, dkv0, seg, my_idx)
    (dq, _, _, dk, dv, *_), _ = jax.lax.scan(step, carry, None, length=ring)
    dq = (dq * sm_scale).reshape(b, s_loc, n, d).astype(q.dtype)
    dseg = np.zeros(seg.shape, dtype=jax.dtypes.float0)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), dseg


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def _ring_attention_local(
    q: jax.Array,  # (b, s_loc, n_loc, d) — this device's shards
    k: jax.Array,  # (b, s_loc, n_kv_loc, d) — UNREPEATED kv heads (GQA)
    v: jax.Array,
    seg: jax.Array,  # (b, s_loc) int32 packed-doc ids
    *,
    axis_name: str,
    causal: bool,
    sm_scale: float,
    kv_chunk: Optional[int] = None,
) -> jax.Array:
    return _ring_core(q, k, v, seg, axis_name, causal, sm_scale, kv_chunk)


def ring_attention(
    q: jax.Array,  # (b, s, n, d) GLOBAL logical shapes, context-sharded on s
    k: jax.Array,
    v: jax.Array,
    segment_ids: Optional[jax.Array],
    mesh: Mesh,
    causal: bool = True,
    sm_scale: float = 1.0,
    kv_chunk: Optional[int] = None,
) -> jax.Array:
    """shard_map entry: shards q/k/v over (data, context, model) and runs the
    ring. Requires seq divisible by the context axis size. ``kv_chunk``
    (STATIC — part of the trace, not a baked-in global) caps the inner
    score-tile width; default _DEFAULT_KV_CHUNK."""
    from ..parallel.sharding import shard_map

    if segment_ids is None:
        segment_ids = jnp.zeros(q.shape[:2], jnp.int32)

    qkv_spec = P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS, None)
    seg_spec = P(DATA_AXIS, CONTEXT_AXIS)

    fn = shard_map(
        partial(
            _ring_attention_local,
            axis_name=CONTEXT_AXIS,
            causal=causal,
            sm_scale=sm_scale,
            kv_chunk=kv_chunk,
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, segment_ids)
