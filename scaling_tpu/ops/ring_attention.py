"""Ring attention: context parallelism over the ``context`` mesh axis.

A capability beyond the reference, which bounds trained context by
per-device memory (SURVEY §5: "no ring attention, context parallelism,
blockwise attention, or Ulysses"). Design (Ring Attention with Blockwise
Transformers, Liu et al. 2023, expressed TPU-natively):

- activations are sharded along the sequence dim over the ``context`` axis;
- each device keeps its Q shard resident and computes attention against one
  K/V block at a time, merging with the online-softmax recurrence;
- K/V blocks (with their segment ids) rotate around the ring via
  ``lax.ppermute`` — ICI neighbour exchange — inside a ``lax.scan``;
- causal masking uses absolute sequence indices derived from each block's
  ring offset, so packing (segment ids) and causality behave exactly like
  the single-device path;
- ``jax.grad`` differentiates through scan + ppermute (the transpose of a
  rotation is the reverse rotation), giving the backward ring for free;
  ``jax.checkpoint`` on the per-block step bounds residual memory.

Peak memory per device: O(s/cp) for Q/K/V/O + one rotating K/V block —
sequence length scales linearly with the ring size.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..topology.topology import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS

_NEG = -1e9


def _ring_attention_local(
    q: jax.Array,  # (b, s_loc, n_loc, d) — this device's shards
    k: jax.Array,  # (b, s_loc, n_kv_loc, d) — UNREPEATED kv heads (GQA)
    v: jax.Array,
    seg: jax.Array,  # (b, s_loc) int32 packed-doc ids
    *,
    axis_name: str,
    causal: bool,
    sm_scale: float,
) -> jax.Array:
    ring = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s_loc, n, d = q.shape
    n_kv = k.shape[2]
    g = n // n_kv  # query heads per kv head; rotating unrepeated K/V keeps
    # the ring's ICI traffic at 1/g of the repeated layout

    # absolute sequence indices of this device's queries
    q_pos = my_idx * s_loc + jnp.arange(s_loc)  # (s_loc,)

    qf = q.astype(jnp.float32).reshape(b, s_loc, n_kv, g, d) * sm_scale

    def block_scores_mask(k_owner, seg_k):
        k_pos = k_owner * s_loc + jnp.arange(s_loc)
        allowed = seg[:, :, None] == seg_k[:, None, :]  # (b, s_q, s_k)
        if causal:
            allowed = allowed & (k_pos[None, None, :] <= q_pos[None, :, None])
        return allowed

    def step(carry, _):
        m, l, acc, k_blk, v_blk, seg_blk, owner = carry
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k_blk.astype(jnp.float32))
        allowed = block_scores_mask(owner, seg_blk)  # (b, sq, sk)
        masked = allowed[:, None, None, :, :]
        s = jnp.where(masked, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))  # (b, h, g, sq)
        # explicit zeroing: for a fully-masked block s == m_new == _NEG and
        # exp(0) would be 1 — the mask, not the exp, must kill those terms
        p = jnp.exp(s - m_new[..., None]) * masked
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        acc_new = (
            acc * jnp.moveaxis(correction, 3, 1)[..., None]
            + jnp.einsum("bhgqk,bkhd->bqhgd", p, v_blk.astype(jnp.float32))
        )
        # rotate the K/V block to the next ring neighbour
        perm = [(i, (i + 1) % ring) for i in range(ring)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        seg_blk = jax.lax.ppermute(seg_blk, axis_name, perm)
        owner = jax.lax.ppermute(owner, axis_name, perm)
        return (m_new, l_new, acc_new, k_blk, v_blk, seg_blk, owner), None

    m0 = jnp.full((b, n_kv, g, s_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b, n_kv, g, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, s_loc, n_kv, g, d), jnp.float32)
    carry = (m0, l0, acc0, k, v, seg, my_idx)
    (m, l, acc, *_), _ = jax.lax.scan(
        jax.checkpoint(step), carry, None, length=ring
    )
    out = acc / jnp.maximum(jnp.moveaxis(l, 3, 1), 1e-20)[..., None]
    return out.reshape(b, s_loc, n, d).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # (b, s, n, d) GLOBAL logical shapes, context-sharded on s
    k: jax.Array,
    v: jax.Array,
    segment_ids: Optional[jax.Array],
    mesh: Mesh,
    causal: bool = True,
    sm_scale: float = 1.0,
) -> jax.Array:
    """shard_map entry: shards q/k/v over (data, context, model) and runs the
    ring. Requires seq divisible by the context axis size."""
    from jax import shard_map

    if segment_ids is None:
        segment_ids = jnp.zeros(q.shape[:2], jnp.int32)

    qkv_spec = P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS, None)
    seg_spec = P(DATA_AXIS, CONTEXT_AXIS)

    fn = shard_map(
        partial(
            _ring_attention_local,
            axis_name=CONTEXT_AXIS,
            causal=causal,
            sm_scale=sm_scale,
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, segment_ids)
