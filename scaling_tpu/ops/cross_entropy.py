"""Cross-entropy from logits with a memory-lean custom VJP.

Autodiff of ``log_softmax -> gather`` keeps an fp32 ``(b, s, vocab)``
residual (the log-probabilities) alive from forward to backward — at the
bench shape (mbs 8, seq 2048, vocab 32k) that is ~2 GB of HBM doing
nothing but waiting. The closed-form gradient needs none of it:

    d loss / d logits = softmax(logits) - onehot(targets)

so the VJP here saves only the ORIGINAL low-precision logits (which the
lm-head already materialized) plus a ``(b, s)`` fp32 logsumexp, and
recomputes the softmax inside the backward. The cotangent is produced in
the logits' own dtype (bf16 in mixed precision), halving the backward
buffer too. Forward math is identical (logsumexp - target logit == the
gathered log-softmax), in fp32 either way.

(reference analogue: model.py:43-76 computes plain torch cross entropy;
the memory shape of torch autograd is the same residual problem.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.custom_vjp
def cross_entropy_from_logits(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Per-token cross entropy, fp32 ``targets.shape`` output."""
    loss, _ = _fwd(logits, targets)
    return loss


def _compute(logits, targets):
    x = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(x, axis=-1)
    target_logit = jnp.take_along_axis(
        x, targets.astype(jnp.int32)[..., None], axis=-1
    )[..., 0]
    return lse - target_logit, lse


def _fwd(logits, targets):
    loss, lse = _compute(logits, targets)
    # residuals: the logits AT THEIR ORIGINAL dtype (no fp32 copy kept
    # alive) + the (b, s) logsumexp; the fp32 softmax never outlives the
    # backward computation itself
    return loss, (logits, targets.astype(jnp.int32), lse)


def _bwd(res, g):
    logits, targets, lse = res
    x = logits.astype(jnp.float32)
    p = jnp.exp(x - lse[..., None])
    onehot = jax.nn.one_hot(targets, logits.shape[-1], dtype=jnp.float32)
    dlogits = (p - onehot) * g.astype(jnp.float32)[..., None]
    # cotangent in the primal dtype: bf16 logits get a bf16 gradient
    # buffer (autodiff of the fp32-upcast path would carry fp32 here and
    # cast at the matmul — same arithmetic, twice the bytes)
    return dlogits.astype(logits.dtype), None


cross_entropy_from_logits.defvjp(_fwd, _bwd)
