"""Fused RMSNorm on TPU (Pallas).

The reference's ``fused`` LayerNormOptimizationType selects flash-attn's
CUDA fused rms_norm (reference: src/scaling/core/nn/norm/rms_norm.py:11-14,55,
layernorm_config.py). This is the TPU-native equivalent: one VMEM pass for
the forward (fp32 statistics computed in-register, bf16 in/out) and one for
the backward, with the weight gradient accumulated across the sequential
TPU grid instead of a separate reduction kernel.

Formulas (x, g row vectors, w the gain, r = rsqrt(mean(x^2) + eps)):
  y  = x * r * w
  gw = g * w
  dx = r * gw - x * r^3 * mean(gw * x)
  dw = sum_rows(g * x * r)

Off-TPU the layer keeps the plain XLA path; interpreter-mode testing opts
in via ``force_rms_interpret`` (same pattern as ops/flash_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_DEFAULT_BLOCK_ROWS = 256

_FORCE_INTERPRET = False


class force_rms_interpret:
    """Context manager: run the fused RMSNorm in interpreter mode and make
    ``rms_norm_fused_supported`` report True off-TPU (tests)."""

    def __enter__(self):
        global _FORCE_INTERPRET
        self._saved = _FORCE_INTERPRET
        _FORCE_INTERPRET = True
        return self

    def __exit__(self, *exc):
        global _FORCE_INTERPRET
        _FORCE_INTERPRET = self._saved
        return False


def rms_norm_fused_supported(dim: int, platform: Optional[str] = None) -> bool:
    """Lane-aligned hidden dim on a real TPU (or forced interpreter mode)."""
    if dim % _LANES != 0:
        return False
    if _FORCE_INTERPRET:
        return True
    return (platform or jax.default_backend()) == "tpu"


def _block_rows(n: int) -> int:
    b = min(_DEFAULT_BLOCK_ROWS, n)
    while b > 8 and n % b != 0:
        b //= 2
    return b if n % b == 0 else 1


def _fwd_kernel(eps, x_ref, w_ref, y_ref):
    x = x_ref[:].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    y = x * r * w_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)


def _bwd_kernel(eps, x_ref, w_ref, g_ref, dx_ref, dw_ref):
    # r is recomputed rather than saved: a 1-D (n,) rstd residual blocked
    # (br,) trips Mosaic's layout verifier on real TPUs (XLA tiles the full
    # array, Mosaic the block — "XLA layout {0:T(512)} does not match
    # Mosaic layout {0:T(256)}"), and one fused mean-of-squares per row
    # block is cheaper than the HBM round-trip anyway
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    gw = g * w
    mean_gwx = jnp.mean(gw * x, axis=-1, keepdims=True)
    dx = r * gw - x * (r**3) * mean_gwx
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # dw accumulates across the sequential TPU grid
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    dw_ref[:] += jnp.sum(g * x * r, axis=0).astype(dw_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_fused(x: jax.Array, w: jax.Array, eps: float = 1e-5) -> jax.Array:
    """y = x * rsqrt(mean(x^2, -1) + eps) * w over the last dim, fused."""
    return _rms_fwd_impl(x, w, eps)


def _rows(x: jax.Array) -> jax.Array:
    return x.reshape(-1, x.shape[-1])


def _rms_fwd_impl(x: jax.Array, w: jax.Array, eps: float):
    orig_shape = x.shape
    x2 = _rows(x)
    n, d = x2.shape
    br = _block_rows(n)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, eps),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
        interpret=_FORCE_INTERPRET,
    )(x2, w)
    return y.reshape(orig_shape)


def _rms_fwd(x, w, eps):
    return _rms_fwd_impl(x, w, eps), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    orig_shape = x.shape
    x2, g2 = _rows(x), _rows(g)
    n, d = x2.shape
    br = _block_rows(n)
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, eps),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((br, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0)),
            # every grid step maps the same (d,) block: sequential accumulate
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((d,), jnp.float32),
        ],
        interpret=_FORCE_INTERPRET,
    )(x2, w, g2)
    return dx.reshape(orig_shape), dw.astype(w.dtype)


rms_norm_fused.defvjp(_rms_fwd, _rms_bwd)


def rms_norm_fused_shardable(mesh, x_shape) -> bool:
    """True when the kernel can be shard_map-partitioned on this mesh.

    The norm is row-independent, so a (b, s, h) activation partitions over
    batch (data axis) and sequence (context and model axes — the model-axis
    split IS sequence parallelism, matching where SP puts the norm anyway;
    reference: the SP layout notes in nn/norm.py). Not applicable inside a
    spatial pipeline (operands there are stage-local, same restriction as
    ops/flash_attention.py:_tp_shardable) or when dims don't divide."""
    from ..topology.topology import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS, PIPE_AXIS

    if len(x_shape) != 3:
        return False
    names = mesh.axis_names
    if PIPE_AXIS in names and mesh.shape[PIPE_AXIS] > 1:
        return False
    dp = mesh.shape[DATA_AXIS] if DATA_AXIS in names else 1
    seq_div = 1
    for a in (CONTEXT_AXIS, MODEL_AXIS):
        if a in names:
            seq_div *= mesh.shape[a]
    b, s, _ = x_shape
    return b % max(dp, 1) == 0 and s % seq_div == 0


def rms_norm_fused_sharded(
    x: jax.Array, w: jax.Array, eps: float, mesh
) -> jax.Array:
    """shard_map'd fused RMSNorm: every device runs the Pallas kernel on its
    local rows with the replicated gain; shard_map's transpose inserts the
    psum that reduces the per-shard weight grads (the manual analogue of
    GSPMD's backward collective for the XLA path)."""
    from ..parallel.sharding import shard_map
    from jax.sharding import PartitionSpec as P

    from ..topology.topology import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS

    assert rms_norm_fused_shardable(mesh, x.shape)
    names = mesh.axis_names
    seq_axes = tuple(
        a for a in (CONTEXT_AXIS, MODEL_AXIS) if a in names and mesh.shape[a] > 1
    )
    spec = P(
        DATA_AXIS if DATA_AXIS in names and mesh.shape[DATA_AXIS] > 1 else None,
        seq_axes if seq_axes else None,
        None,
    )
    return shard_map(
        lambda xx, ww: rms_norm_fused(xx, ww, eps),
        mesh=mesh,
        in_specs=(spec, P()),
        out_specs=spec,
        check_vma=False,
    )(x, w)
