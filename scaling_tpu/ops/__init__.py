from .flash_attention import flash_attention_fused, flash_attention_supported

__all__ = ["flash_attention_fused", "flash_attention_supported"]
