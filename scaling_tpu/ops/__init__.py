from .flash_attention import flash_attention_fused, flash_attention_supported
from .ring_attention import ring_attention
from .rms_norm import rms_norm_fused, rms_norm_fused_supported
from .ulysses_attention import ulysses_attention

__all__ = [
    "flash_attention_fused",
    "flash_attention_supported",
    "ring_attention",
    "rms_norm_fused",
    "rms_norm_fused_supported",
    "ulysses_attention",
]
