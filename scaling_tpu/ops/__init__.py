from .flash_attention import flash_attention_fused, flash_attention_supported
from .rms_norm import rms_norm_fused, rms_norm_fused_supported

__all__ = [
    "flash_attention_fused",
    "flash_attention_supported",
    "rms_norm_fused",
    "rms_norm_fused_supported",
]
