"""Ulysses-style context parallelism: all-to-all over the ``context`` axis.

A capability beyond the reference (SURVEY §2.4: "Ulysses (attention head
all-to-all): absent — no all_to_all calls in repo"). The complementary
design to ``ops/ring_attention.py``:

- ring: every device keeps its sequence shard of Q resident and K/V blocks
  rotate — O(s/cp) activation memory, cp ppermute hops per layer;
- ulysses (DeepSpeed-Ulysses, Jacobs et al. 2023): one all-to-all trades
  the sequence shard for a head shard, each device then runs ordinary
  full-sequence attention for n/cp of the heads, and a second all-to-all
  restores sequence sharding — two collective hops per layer regardless of
  cp, but O(s^2) scores for the local heads.

Ring favours very long sequences (blockwise memory); ulysses favours
moderate sequences with enough heads (fewer, larger collectives that ride
ICI well). Both are selectable per run via
``topology.context_parallel_variant`` — the variant changes only the
attention internals, so loss parity with the single-device path holds for
either (tests/core/test_nn/test_ulysses_attention.py,
tests/transformer/test_training_context_parallel.py).

GQA stays unrepeated through the exchange: K/V travel with their n_kv/cp
head shard and the grouped-query einsum consumes them directly, so the
all-to-all moves 2·s·(n_kv/cp)·d elements, not the repeated 2·s·(n/cp)·d.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..topology.topology import CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS

_NEG = -1e9


def _ulysses_local(
    q: jax.Array,  # (b, s_loc, n_loc, d) — this device's shards
    k: jax.Array,  # (b, s_loc, n_kv_loc, d) — UNREPEATED kv heads
    v: jax.Array,
    seg: jax.Array,  # (b, s_loc) int32 packed-doc ids
    *,
    axis_name: str,
    causal: bool,
    sm_scale: float,
) -> jax.Array:
    cp = jax.lax.psum(1, axis_name)
    b, s_loc, n, d = q.shape
    n_kv = k.shape[2]
    assert n % cp == 0, (
        f"ulysses needs local query heads ({n}) divisible by the context "
        f"axis ({cp}); lower cp or use the ring variant"
    )
    assert n_kv % cp == 0, (
        f"ulysses needs local kv heads ({n_kv}) divisible by the context "
        f"axis ({cp}); the caller repeats kv minimally to make this hold"
    )

    # all-to-all #1: scatter heads over the axis, gather the full sequence
    # (device i already holds sequence chunk i, so tiled concat along the
    # sequence axis reassembles global order)
    qg = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    seg_full = jax.lax.all_gather(seg, axis_name, axis=1, tiled=True)  # (b, s)

    s = s_loc * cp
    nh = n // cp
    n_kv_h = n_kv // cp
    g = nh // n_kv_h

    from .flash_attention import flash_attention_supported

    if causal and flash_attention_supported(s, d):
        # after the exchange each device holds the FULL sequence for its
        # head shard — ordinary causal attention, which is exactly the
        # splash kernel's job: O(s·block) score tiles instead of the
        # O(s^2) einsum below, and the same GQA-unrepeated contract
        from .flash_attention import flash_attention_fused

        out = flash_attention_fused(
            qg, kg, vg, seg_full, causal=True, sm_scale=sm_scale
        ).astype(q.dtype)
    else:
        # XLA fallback (non-causal, off-TPU, or unaligned shapes):
        # grouped-query attention with a stable softmax in f32
        qf = qg.astype(jnp.float32).reshape(b, s, n_kv_h, g, d) * sm_scale
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qf, kg.astype(jnp.float32))
        allowed = seg_full[:, :, None] == seg_full[:, None, :]  # (b, s_q, s_k)
        if causal:
            pos = jnp.arange(s)
            allowed = allowed & (pos[None, None, :] <= pos[None, :, None])
        masked = allowed[:, None, None, :, :]
        scores = jnp.where(masked, scores, _NEG)
        m = scores.max(axis=-1, keepdims=True)
        # fully-masked rows: exp(_NEG - _NEG) would be 1 — the mask kills them
        p = jnp.exp(scores - m) * masked
        l = jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-20)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", p / l, vg.astype(jnp.float32))
        out = out.reshape(b, s, nh, d).astype(q.dtype)

    # all-to-all #2: scatter the sequence back, gather this shard's heads
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,  # (b, s, n, d) GLOBAL logical shapes, context-sharded on s
    k: jax.Array,
    v: jax.Array,
    segment_ids: Optional[jax.Array],
    mesh: Mesh,
    causal: bool = True,
    sm_scale: float = 1.0,
) -> jax.Array:
    """shard_map entry mirroring ``ring_attention``'s contract: shards
    q/k/v over (data, context, model) and runs the head exchange."""
    from ..parallel.sharding import shard_map

    if segment_ids is None:
        segment_ids = jnp.zeros(q.shape[:2], jnp.int32)

    qkv_spec = P(DATA_AXIS, CONTEXT_AXIS, MODEL_AXIS, None)
    seg_spec = P(DATA_AXIS, CONTEXT_AXIS)

    fn = shard_map(
        partial(
            _ulysses_local,
            axis_name=CONTEXT_AXIS,
            causal=causal,
            sm_scale=sm_scale,
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, segment_ids)
