"""Fused flash attention on TPU (Pallas splash-attention kernel).

Replaces the reference's flash-attn CUDA dependency
(reference: src/scaling/core/nn/attention/attention.py:29-36,204-259,
requirements/gpu_optimization.txt). The reference imports the flash-attn
package; the TPU-native equivalent is the splash-attention Pallas kernel
that ships with jax (jax.experimental.pallas.ops.tpu.splash_attention),
driven through this wrapper, which:

- feeds GQA **unrepeated**: q keeps all heads, k/v keep only the kv heads
  (the kernel groups queries internally) — preserving the KV bandwidth and
  memory win that is the point of grouped-query attention, where the
  reference's flash path repeats KV to full head count;
- maps the framework's (batch, seq, heads, head_dim) layout and packed-doc
  ``segment_ids`` (= the reference's ``cumulative_seq_lengths``,
  attention.py:245-258) onto the kernel's (heads, seq, head_dim) +
  SegmentIds API via vmap over batch;
- runs in interpreter mode off-TPU so the flash path stays testable on the
  CPU mesh harness.

Block sizes default to 1024/1024 (fastest fwd+bwd in the v5e micro-sweep;
2048-wide blocks exceed VMEM), snap down to sequence-length divisors, and
can be overridden via ``SCALING_TPU_FLASH_BLOCK_Q`` /
``SCALING_TPU_FLASH_BLOCK_KV``.

Local-window heads are fused too (per-head LocalMask in the splash mask
set). Unsupported cases (KV cache decode, attention-score manipulation,
probability dropout, non-causal) stay on the XLA path in
``nn/attention.py`` — mirroring the reference's flash/torch kernel
switch (masked_softmax_config.py:8-37).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

_MIN_BLOCK = 128


def _block_sizes():
    # 1024/1024 won the v5e fwd+bwd micro-sweep at seq 2048 (8.68ms vs 8.99
    # for 512/512; 2048-wide blocks exceed VMEM and fail to compile)
    q = int(os.environ.get("SCALING_TPU_FLASH_BLOCK_Q", "1024"))
    kv = int(os.environ.get("SCALING_TPU_FLASH_BLOCK_KV", "1024"))
    return q, kv


def flash_attention_supported(
    seq_len: int, head_dim: int, platform: Optional[str] = None
) -> bool:
    """The splash kernel needs lane-aligned shapes and a real TPU.

    Off-TPU the layer falls back to the XLA path (the reference likewise
    skips flash-attn without a GPU); interpreter-mode testing opts in via
    ``force_flash_interpret()`` around the whole computation.
    """
    if seq_len % _MIN_BLOCK != 0 or head_dim < 64:
        return False
    if _FORCE_INTERPRET:
        return True
    return (platform or jax.default_backend()) == "tpu"


_FORCE_INTERPRET = False


class force_flash_interpret:
    """Context manager: run the splash kernel in interpreter mode and make
    ``flash_attention_supported`` report True off-TPU (tests).

    The kernel is built with ``interpret=True`` directly rather than via
    ``pltpu.force_tpu_interpret_mode`` — the latter's randomized grid
    execution mishandles vmap-extended grids (dimension_semantics stays at
    the kernel's 3 entries while the grid grows a batch dim)."""

    def __enter__(self):
        global _FORCE_INTERPRET
        self._saved = _FORCE_INTERPRET
        _FORCE_INTERPRET = True
        return self

    def __exit__(self, *exc):
        global _FORCE_INTERPRET
        _FORCE_INTERPRET = self._saved
        return False


def _snap_block(block: int, seq_len: int) -> int:
    """Largest multiple of 128 that divides seq_len and is <= block.

    The splash kernel needs block sizes dividing the sequence length; the
    128-alignment gate in ``flash_attention_supported`` guarantees this
    terminates (at 128 in the worst case)."""
    b = min(block, seq_len)
    b -= b % _MIN_BLOCK
    while b > _MIN_BLOCK and seq_len % b != 0:
        b -= _MIN_BLOCK
    return max(b, _MIN_BLOCK)


@functools.lru_cache(maxsize=32)
def _make_kernel(num_q_heads: int, seq_len: int, block_q: int, block_kv: int,
                 interpret: bool, num_local_heads: int = 0,
                 local_window: Optional[int] = None):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    bq = _snap_block(block_q, seq_len)
    bkv = _snap_block(block_kv, seq_len)
    # mixed-head masks: leading heads are fully causal, the trailing
    # num_local_heads attend within a backward window (the reference's
    # local-attention heads ride its flash sliding window,
    # attention.py:204-259); masks are per Q head, so GQA grouping is
    # unaffected
    shape = (seq_len, seq_len)
    head_masks = [
        sm.CausalMask(shape) for _ in range(num_q_heads - num_local_heads)
    ] + [
        sm.LocalMask(shape, window_size=(local_window, 0), offset=0)
        for _ in range(num_local_heads)
    ]
    mask = sm.MultiHeadMask(head_masks)
    sizes = sk.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=bkv,
        block_q_dkv=bq, block_kv_dkv=bkv, block_kv_dkv_compute=bkv,
        block_q_dq=bq, block_kv_dq=bkv,
    )
    return sk.make_splash_mha(
        mask=mask, block_sizes=sizes, head_shards=1, q_seq_shards=1,
        interpret=interpret,
    )


def _tp_shardable(mesh, b: int, n: int, n_kv: int, num_local_heads: int) -> bool:
    """True when the kernel can be shard_map-partitioned over (data, model):
    uniform causal masks (no local heads), heads and batch divisible, and no
    pipe axis in play (inside the spatial pipeline the operands are already
    stage-local and shard_map's replication assumption would be wrong)."""
    from ..topology.topology import DATA_AXIS, MODEL_AXIS, PIPE_AXIS

    if num_local_heads > 0:
        return False
    names = mesh.axis_names
    if MODEL_AXIS not in names or mesh.shape[MODEL_AXIS] <= 1:
        return False
    if PIPE_AXIS in names and mesh.shape[PIPE_AXIS] > 1:
        return False
    mp = mesh.shape[MODEL_AXIS]
    dp = mesh.shape[DATA_AXIS] if DATA_AXIS in names else 1
    return n % mp == 0 and n_kv % mp == 0 and b % max(dp, 1) == 0


def flash_attention_fused(
    q: jax.Array,  # (b, s, n, d)
    k: jax.Array,  # (b, s, n_kv, d)  — UNREPEATED kv heads (GQA-native)
    v: jax.Array,  # (b, s, n_kv, d)
    segment_ids: Optional[jax.Array] = None,  # (b, s) int32 packed-doc ids
    causal: bool = True,
    sm_scale: float = 1.0,
    num_local_heads: int = 0,
    local_window: Optional[int] = None,
    mesh=None,
) -> jax.Array:
    """Block-wise causal attention, O(s) memory; returns (b, s, n, d).

    The trailing ``num_local_heads`` query heads attend only within
    ``local_window`` tokens back (mixed local/global heads)."""
    assert causal, "the flash path is causal-only; XLA handles the rest"
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
    )

    b, s, n, d = q.shape
    assert q.shape[1] == k.shape[1] and k.shape[2:] == v.shape[2:]
    block_q, block_kv = _block_sizes()
    # construct (and cache) the kernel outside the enclosing jit trace —
    # its mask-info constants must be concrete, not tracers
    with jax.ensure_compile_time_eval():
        kernel = _make_kernel(
            n, s, block_q, block_kv, _FORCE_INTERPRET,
            num_local_heads, local_window,
        )

    qt = jnp.swapaxes(q, 1, 2) * sm_scale  # (b, n, s, d) pre-scaled
    kt = jnp.swapaxes(k, 1, 2)  # (b, n_kv, s, d)
    vt = jnp.swapaxes(v, 1, 2)
    seg_i32 = (
        segment_ids.astype(jnp.int32)
        if segment_ids is not None
        else jnp.zeros((b, s), jnp.int32)
    )

    def run_local(qq, kk, vv, seg):
        def one(qi, ki, vi, si):
            return kernel(qi, ki, vi, segment_ids=sk.SegmentIds(q=si, kv=si))

        return jax.vmap(one)(qq, kk, vv, seg)

    if mesh is not None and _tp_shardable(mesh, b, n, k.shape[2], num_local_heads):
        # partition the kernel itself: pallas custom calls are opaque to
        # GSPMD, which would otherwise gather heads to every device. With
        # uniform causal masks each model shard runs an identical kernel on
        # its contiguous slice of q (and kv) heads; batch splits over data.
        from ..parallel.sharding import shard_map
        from jax.sharding import PartitionSpec as P

        from ..topology.topology import DATA_AXIS, MODEL_AXIS

        mp = mesh.shape[MODEL_AXIS]
        with jax.ensure_compile_time_eval():
            shard_kernel = _make_kernel(
                n // mp, s, block_q, block_kv, _FORCE_INTERPRET, 0, None
            )

        def run_shard(qq, kk, vv, seg):
            def one(qi, ki, vi, si):
                return shard_kernel(
                    qi, ki, vi, segment_ids=sk.SegmentIds(q=si, kv=si)
                )

            return jax.vmap(one)(qq, kk, vv, seg)

        qkv_spec = P(DATA_AXIS, MODEL_AXIS, None, None)
        out = shard_map(
            run_shard,
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, P(DATA_AXIS, None)),
            out_specs=qkv_spec,
            check_vma=False,
        )(qt, kt, vt, seg_i32)
    else:
        out = run_local(qt, kt, vt, seg_i32)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # (b, s, n, d)
