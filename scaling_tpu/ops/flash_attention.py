"""Fused flash attention on TPU (Pallas).

Replaces the reference's flash-attn CUDA dependency
(reference: src/scaling/core/nn/attention/attention.py:29-36,204-259,
requirements/gpu_optimization.txt). The reference imports the flash-attn
package; the TPU-native equivalent is the block-wise Pallas kernel that
ships with jax (jax.experimental.pallas.ops.tpu.flash_attention) driven
through this wrapper, which:

- maps the framework's (batch, seq, heads, head_dim) layout and packed-doc
  ``segment_ids`` (= the reference's ``cumulative_seq_lengths``,
  attention.py:245-258) onto the kernel's (b, h, s, d) + SegmentIds API;
- picks legal block sizes for short sequences;
- runs the kernel in interpreter mode off-TPU so the flash path stays
  testable on the CPU mesh harness.

Unsupported cases (KV cache decode, attention-score manipulation,
probability dropout, local-window heads) stay on the XLA path in
``nn/attention.py`` — mirroring the reference's flash/torch kernel switch
(masked_softmax_config.py:8-37).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_MIN_BLOCK = 128


def flash_attention_supported(
    seq_len: int, head_dim: int, platform: Optional[str] = None
) -> bool:
    """The Pallas kernel needs MXU-aligned sequence blocks and a real TPU.

    Off-TPU the layer falls back to the XLA path (the reference likewise
    skips flash-attn without a GPU); interpreter-mode testing opts in via
    ``pltpu.force_tpu_interpret_mode()`` around the whole computation.
    """
    if (platform or jax.default_backend()) != "tpu":
        return False
    return seq_len % _MIN_BLOCK == 0 and head_dim >= 64


def flash_attention_fused(
    q: jax.Array,  # (b, s, n, d)
    k: jax.Array,  # (b, s, n, d)  — kv heads already repeated for GQA
    v: jax.Array,  # (b, s, n, d)
    segment_ids: Optional[jax.Array] = None,  # (b, s) int32 packed-doc ids
    causal: bool = True,
    sm_scale: float = 1.0,
) -> jax.Array:
    """Block-wise attention, O(s) memory; returns (b, s, n, d)."""
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    b, s, n, d = q.shape
    qt = jnp.swapaxes(q, 1, 2)  # (b, n, s, d)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    seg = None
    if segment_ids is not None:
        seg_i32 = segment_ids.astype(jnp.int32)
        seg = fa.SegmentIds(q=seg_i32, kv=seg_i32)

    block = min(512, s)
    sizes = fa.BlockSizes(
        block_q=block,
        block_k_major=block,
        block_k=block,
        block_b=1,
        block_q_major_dkv=block,
        block_k_major_dkv=block,
        block_k_dkv=block,
        block_q_dkv=block,
        block_k_major_dq=block,
        block_k_dq=block,
        block_q_dq=block,
    )

    def run():
        return fa.flash_attention(
            qt, kt, vt, segment_ids=seg, causal=causal,
            sm_scale=sm_scale, block_sizes=sizes,
        )

    if jax.default_backend() != "tpu":
        from jax.experimental.pallas import tpu as pltpu

        with pltpu.force_tpu_interpret_mode():
            out = run()
    else:
        out = run()
    return jnp.swapaxes(out, 1, 2)  # back to (b, s, n, d)
