from .trainer import BaseTrainer, TrainerConfig

__all__ = ["BaseTrainer", "TrainerConfig"]
