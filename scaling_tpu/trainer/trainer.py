"""Generic training loop + checkpoint orchestration.

(reference: src/scaling/core/trainer/trainer.py:33-558). ``run_training``
drives: jitted train step -> periodic save -> periodic eval -> rank-0 metric
logging. Checkpoint directories follow the reference layout:
``save_dir/global_step{N}/`` with model/optimizer/context artifacts plus a
``latest`` pointer file, so tooling built around reference checkpoints keeps
working.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zipfile
from enum import Enum
from pathlib import Path
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import Field, model_validator

from ..checkpoint import (
    AsyncCheckpointWriter,
    load_model_checkpoint,
    load_optimizer_checkpoint,
    save_model_checkpoint,
    save_optimizer_checkpoint,
)
from ..config import BaseConfig
from ..context import BaseContext
from ..data import DataLoader
from ..logging import logger
from ..obs import StepTelemetry, span
from ..optimizer.optimizer import Optimizer, OptimizerState
from ..parallel.parallel_module import (
    EvaluationStepOutput,
    ParallelModule,
    TrainStepOutput,
)
from ..resilience import (
    CheckpointCommit,
    NonFiniteGuard,
    NonFiniteLossError,
    StepStallWatchdog,
    get_fault_plan,
    retry_io,
)
from ..resilience.controlplane import (
    ABORT_FLAG,
    ENV_NUM_HOSTS,
    PREEMPT_FLAG,
    STALL_FLAG,
    ControlPlane,
    JobAborted,
    straggler_table,
)
from ..resilience.manifest import CheckpointCorruptionError, read_manifest
from ..resilience.meshmeta import (
    build_mesh_meta,
    param_record,
    read_mesh_meta,
    write_mesh_meta,
)
from ..resilience.reshard import (
    fire_reshard_point,
    rescale_consumed_samples,
    reshard_plan,
)
from ..resilience.restore import checkpoint_candidates, verify_checkpoint

# disk-corruption error types the load fallback may skip past; everything
# else (shape/config mismatches, OOMs, assertion errors) aborts the resume
_CORRUPT_LOAD_ERRORS = (
    zipfile.BadZipFile,
    EOFError,
    OSError,
    CheckpointCorruptionError,
    json.JSONDecodeError,
)


class CheckpointBackend(Enum):
    NPZ = "npz"
    ORBAX = "orbax"


class TrainerConfig(BaseConfig):
    save_dir: Optional[str] = Field(None, description="directory for saving checkpoints")
    save_interval: Optional[int] = Field(
        None,
        description="save a checkpoint every 'save_interval' steps to save_dir, "
        "iff save_dir is defined",
    )
    load_dir: Optional[str] = Field(None, description="directory for loading checkpoints")
    train_iterations: Optional[int] = Field(None, description="train for this number of iterations")
    assert_checkpoint_loaded: bool = Field(
        True, description="error out if a checkpoint could not be loaded"
    )
    load_optimizer_states: bool = Field(
        True, description="load optimizer states on checkpoint load"
    )
    delete_past_optimizer_states: bool = Field(
        True,
        description="Deletes optimizer states on the last n-1 checkpoints right "
        "after saving the nth checkpoint",
    )
    load_context: bool = Field(
        True,
        description="load context state, i.e. train iterations, consumed train "
        "and eval samples on checkpoint load",
    )
    allowed_missing_keys_in_checkpoint: Optional[List[str]] = Field(
        None,
        description="list of parameter name regexes that may not be present in an "
        "existing checkpoint (e.g. fresh adapters)",
    )
    allowed_unexpected_keys_in_checkpoint: Optional[List[str]] = Field(
        None,
        description="list of parameter name regexes that may be present in an "
        "existing checkpoint but not be loaded",
    )
    ignore_keys_in_checkpoint: Optional[List[str]] = Field(
        None,
        description="list of parameter name regexes for which pretrained weights "
        "are not loaded (reinitialise parts of a model)",
    )
    merge_lora_after_loading_checkpoint: bool = Field(
        False, description="merge LoRa weights after loading"
    )
    seed: int = Field(42, description="")
    log_interval: int = Field(
        1,
        description="fetch and log step metrics every n steps. Intermediate "
        "steps skip the device-to-host sync entirely, so consecutive steps "
        "chain on-device and host/tunnel latency leaves the critical path "
        "(the reference logs every step; 1 keeps that behavior). Steps "
        "inside an active profiler window always sync so recorded step "
        "times stay honest",
        ge=1,
    )
    eval_iterations: int = Field(0, description="number of eval micro batches per eval pass")
    eval_interval: Optional[int] = Field(None, description="evaluate every n train steps")
    dataloader_num_workers: int = Field(0, description="kept for config parity")
    dataloader_pin_memory: bool = Field(True, description="kept for config parity")
    dataloader_prefetch_factor: Optional[int] = Field(
        None,
        description="prefetch up to this many micro-batch stacks on a "
        "background thread, overlapping host-side batch assembly with the "
        "device step; None/0 loads synchronously. Resume exactness is "
        "unaffected: the stream is a pure function of (seed, "
        "consumed_samples) and prefetched-but-unconsumed batches are "
        "rebuilt on restart",
        ge=0,
    )
    save_checkpoint_async: bool = Field(
        False,
        description="write checkpoint files on a background thread; the train "
        "loop only blocks for the device-to-host gather",
    )
    strict_checkpoint_load: bool = Field(
        False,
        description="fail on the FIRST checkpoint that flunks integrity "
        "verification instead of falling back to the newest older valid "
        "one — for runs where silently resuming from an earlier step "
        "would invalidate the experiment",
    )
    multihost_shared_save_dir: bool = Field(
        False,
        description="multi-host supervision: save_dir is ONE tree shared "
        "by every host (orbax on shared storage) — only host 0 advances "
        "`latest`, after the cross-host commit barrier. False means "
        "per-host shard dirs where every host owns its own pointer. "
        "Only read when a control plane is attached",
    )
    max_consecutive_nonfinite: Optional[int] = Field(
        None,
        description="non-finite policy budget: tolerate up to this many "
        "CONSECUTIVE overflow/NaN steps (the loss scaler already turns "
        "each into a no-op update), then save a checkpoint and abort "
        "with a diagnosis. None disables. Only fetched steps are "
        "observed — with log_interval > 1 the streak is counted at "
        "fetch granularity",
        ge=0,
    )
    step_timeout_seconds: Optional[float] = Field(
        None,
        description="step-stall watchdog: if a train-loop iteration "
        "makes no progress for this long, dump every thread's stack "
        "(hung collective / wedged storage forensics) and flag "
        "preemption so the loop saves-and-exits at the next safe "
        "point. None disables",
        gt=0,
    )
    io_retry_attempts: int = Field(
        3,
        description="bounded retry for transient dataloader read "
        "failures (exponential backoff; checkpoint writes retry with "
        "the same default independently)",
        ge=1,
    )
    io_retry_backoff_seconds: float = Field(
        0.05, description="base backoff delay for dataloader read retries",
        ge=0,
    )
    deep_checkpoint_verification: bool = Field(
        True,
        description="verify crc32 digests of every manifest-listed file "
        "before restoring (catches bit rot / torn writes). False checks "
        "existence+size only — for very large checkpoints on slow "
        "shared storage where a full read per restore is prohibitive",
    )
    checkpoint_backend: CheckpointBackend = Field(
        CheckpointBackend.NPZ,
        description="'npz': layout-independent per-layer files, host-gathered "
        "(the golden format; supports non-strict PEFT loading). 'orbax': "
        "tensorstore-backed sharded save/restore — every host writes only "
        "its own shards and restore re-shards to the current mesh, the "
        "multi-host-scale path (requires exact key match; checkpoints keep "
        "the same per-layer canonical tree, so pp/mp relayouts still load)",
    )

    @model_validator(mode="after")
    def _validate_backend(self):
        if (
            self.checkpoint_backend == CheckpointBackend.ORBAX
            and self.save_checkpoint_async
        ):
            raise ValueError(
                "save_checkpoint_async is not supported with the orbax "
                "backend yet: its tensorstore write is synchronous, which "
                "would silently break the async contract — disable one"
            )
        return self


class BaseTrainer:
    """Wires module/optimizer/datasets; owns the train loop."""

    def __init__(
        self,
        config: TrainerConfig,
        context: BaseContext,
        parallel_module: ParallelModule,
        optimizer: Optimizer,
        loss_function: Callable,
        dataset: Any = None,
        dataset_evaluation: Any = None,
        metrics_aggregation_fn: Optional[Callable] = None,
        batch_to_model_input: Callable = lambda b: b,
        profiler: Any = None,
    ):
        self.profiler = profiler
        self.config = config
        self.context = context
        self.module = parallel_module
        self.optimizer = optimizer
        self.loss_function = loss_function
        self.dataset = dataset
        self.dataset_evaluation = dataset_evaluation
        self.batch_to_model_input = batch_to_model_input
        self.topology = context.topology

        self.params: Any = None
        self.opt_state: Optional[OptimizerState] = None
        # log_interval bookkeeping: steps dispatched since the last
        # device->host fetch, and the wall clock of that fetch (for
        # amortized per-step durations)
        self._unfetched_steps = 0
        self._last_fetch_wall: Optional[float] = None
        # bookkeeping from the last load_checkpoint: which model keys were
        # actually taken from the checkpoint (None = no checkpoint loaded)
        # and whether optimizer moments survived the load — startup splices
        # (pretrained CLIP) gate on these
        self.restored_model_keys: Optional[set] = None
        self.optimizer_states_loaded: bool = False
        self._ckpt_writer: Optional[AsyncCheckpointWriter] = None
        self._prefetch_queue: Any = None
        self._prefetch_thread: Any = None
        self._prefetch_stop: Any = None
        self._train_step = None
        self._eval_step = None
        self.dataloader: Optional[DataLoader] = None
        self.dataloader_evaluation: Optional[DataLoader] = None
        # generic cluster hook points (Determined glue attaches here; any
        # scheduler integration can): an extra preemption predicate polled
        # every step, metric sinks called after logging, and checkpoint
        # sinks called with each finished step dir
        self.external_preemption: Optional[Callable[[], bool]] = None
        self.metrics_hooks: List[Callable[[dict, int], None]] = []
        self.checkpoint_hooks: List[Callable[[Path, int], None]] = []
        self._preempted = False
        # per-step telemetry (docs/OBSERVABILITY.md): hardware gauges,
        # step-time EMA, and — once configure() declared the model's
        # FLOPs-per-token — achieved-TFLOPs/MFU; flushed to the metrics
        # JSONL sink on every fetched step. Host-side only by contract.
        self.telemetry = StepTelemetry()
        # multi-host supervision (attach_control_plane): out-of-band
        # heartbeats/barriers/flags beside the XLA collectives
        self._control_plane: Optional[ControlPlane] = None
        self._cp_first_checkin = True
        self._cp_step_barrier = True
        self._cp_barrier_timeout = 300.0
        self._cp_peer_stale = 60.0
        self._cp_latest_leader = True
        self._cp_prev_commit_step: Optional[int] = None
        self._last_saved_step: Optional[int] = None
        self._nonfinite_guard: Optional[NonFiniteGuard] = (
            NonFiniteGuard(config.max_consecutive_nonfinite)
            if config.max_consecutive_nonfinite is not None
            else None
        )

    # ------------------------------------------------------------ lifecycle
    def initialize(
        self, load_checkpoint: bool = True, load_dir: Optional[Path | str] = None
    ) -> None:
        self.context.initialize(self.config.seed)
        key = self.context.rng.key("model_init")
        params = self.module.init_params(key)
        params = jax.tree.map(
            lambda p: p.astype(self.module.compute_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating)
            else p,
            params,
        )
        opt_cfg = self.optimizer.config
        fsdp = opt_cfg.zero and opt_cfg.zero_stage == 3
        self.params = self.module.shard_params(params, fsdp_data_axis=fsdp)
        self.opt_state = self.optimizer.init_state(self.params)

        loaded = False
        load_dir = load_dir or self.config.load_dir
        if load_checkpoint and load_dir is not None:
            loaded = self.load_checkpoint(load_dir)
            if self.config.assert_checkpoint_loaded and not loaded:
                raise AssertionError(
                    f"could not load checkpoint from {load_dir}"
                )

        self._build_dataloaders()
        self._train_step = self.module.build_train_step(self.optimizer, self.loss_function)
        self._eval_step = self.module.build_eval_step(self.loss_function)
        if (self.config.dataloader_prefetch_factor or 0) > 0 and self.dataloader is not None:
            self._start_prefetch(self.config.dataloader_prefetch_factor)

    def _start_prefetch(self, depth: int) -> None:
        """Fill a bounded queue of ready micro-batch stacks off-thread.

        The worker runs for the trainer's lifetime (daemon thread): stopping
        mid-stream would desynchronize the dataloader's internal cursor from
        ``consumed_samples`` by discarding already-assembled batches. Every
        already-queued batch is consumed in order by later steps, so
        back-to-back run_training calls see the exact synchronous stream.
        """
        import queue
        import threading

        q = queue.Queue(maxsize=depth)
        stop = threading.Event()
        self._prefetch_queue = q
        self._prefetch_stop = stop

        def worker():
            # closure locals: stop_prefetch may null the attributes while a
            # slow assemble is still in flight
            while not stop.is_set():
                try:
                    item = self._assemble_micro_batches()
                except BaseException as e:  # surfaced on the consumer side
                    item = e
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        break
                    except queue.Full:
                        continue
                if isinstance(item, BaseException):
                    if stop.is_set():
                        logger.warning(
                            f"batch prefetch error during shutdown: {item!r}"
                        )
                    return

        self._prefetch_thread = threading.Thread(
            target=worker, name="batch-prefetch", daemon=True
        )
        self._prefetch_thread.start()

    def stop_prefetch(self) -> None:
        """Explicit shutdown (tests / trainer teardown); discards any
        batches still in the queue, so only call when this trainer object
        will not train further."""
        if self._prefetch_stop is not None:
            self._prefetch_stop.set()
        if self._prefetch_thread is not None:
            self._prefetch_thread.join(timeout=5)
        self._prefetch_queue = None
        self._prefetch_thread = None
        self._prefetch_stop = None

    # Deliberately lock-free: ``dataloader`` is assigned here BEFORE
    # ``_start_prefetch`` spawns the worker (thread-start happens-before
    # publishes it) and never reassigned while the worker is live
    # (``stop_prefetch`` joins the thread first).
    # sta: lock(dataloader)
    def _build_dataloaders(self) -> None:
        if self.dataset is not None:
            self.dataloader = DataLoader(
                seed=self.config.seed,
                consumed_samples=self.context.consumed_samples,
                dataset=self.dataset,
                topology=self.topology,
                retry_attempts=self.config.io_retry_attempts,
                retry_backoff=self.config.io_retry_backoff_seconds,
            )
        if self.dataset_evaluation is not None:
            self.dataloader_evaluation = DataLoader(
                seed=self.config.seed,
                consumed_samples=self.context.consumed_eval_samples,
                dataset=self.dataset_evaluation,
                topology=self.topology,
                retry_attempts=self.config.io_retry_attempts,
                retry_backoff=self.config.io_retry_backoff_seconds,
            )

    # ----------------------------------------------------------- train step
    def _assemble_micro_batches(self):
        """Stack grad-accum micro batches along a new leading axis."""
        gas = self.topology.gradient_accumulation_steps
        batches = [
            self.batch_to_model_input(next(self.dataloader)) for _ in range(gas)
        ]
        stacked = jax.tree.map(lambda *xs: np.stack(xs, axis=0), *batches)
        return self.module.shard_batch(stacked)

    def _next_micro_batches(self):
        if self._prefetch_queue is not None:
            item = self._prefetch_queue.get()
            if isinstance(item, BaseException):
                self.stop_prefetch()
                raise item
            return item
        return self._assemble_micro_batches()

    def train_step(self) -> TrainStepOutput:
        step_idx = self.context.iterations
        if (
            self.profiler is not None
            and self.profiler.enabled_at(step_idx)
            and self._unfetched_steps
        ):
            # the profiled window must open with a drained device queue or
            # its first step_time absorbs the unfetched backlog
            jax.block_until_ready(self.opt_state.step)  # sta: disable=STA010
            self._unfetched_steps = 0
            self._last_fetch_wall = time.time()
        if self.profiler is not None:
            self.profiler.begin_step(step_idx)
        start = time.time()
        with span("step.data", step=step_idx):
            micro_batches = self._next_micro_batches()
        t_data = time.time() - start
        dropout_key = self.context.rng.key("dropout", self.context.iterations)
        # dispatch-only span: without a drain it measures how long the
        # host took to hand XLA the fused step, the device work itself
        # shows up in step.sync — adding a drain here is exactly the
        # per-step sync log_interval exists to remove
        with span("step.fwdbwd", step=step_idx):
            self.params, self.opt_state, loss, metrics, opt_out = self._train_step(
                self.params, self.opt_state, micro_batches, dropout_key
            )
        if get_fault_plan().fire("step.nan_grads") == "nan":
            # emulate a transient hardware NaN burst for the non-finite
            # policy: poison only the OBSERVED loss (params stay clean,
            # so "skip and continue" semantics hold exactly)
            loss = jnp.asarray(float("nan"), jnp.float32)
        self.context.step()
        # profiler windows always sync (recorded step times must cover the
        # device work); otherwise log_interval decides whether this step
        # fetches or stays in flight so the next dispatch isn't gated on
        # host/tunnel latency
        profiling = self.profiler is not None and self.profiler.enabled_at(step_idx)
        # the run's last step always fetches: otherwise a train_iterations
        # that isn't a log_interval multiple ends with the tail steps'
        # metrics (including the final loss) never logged, their device
        # work drained only implicitly by checkpointing
        last_step = (
            self.config.train_iterations is not None
            and self.context.iterations >= self.config.train_iterations
        )
        fetch = profiling or last_step or (
            self.context.iterations % self.config.log_interval == 0
        )
        if not fetch:
            self._unfetched_steps += 1
            return TrainStepOutput(
                loss=loss,
                metrics=metrics,
                global_grad_norm=opt_out.global_grad_norm,
                learning_rates=opt_out.learning_rates,
                overflow=opt_out.overflow,
                no_overflow_steps=opt_out.no_overflow_steps,
                current_loss_scale=opt_out.current_loss_scale,
                step_duration=None,  # dispatch time would masquerade as step time
                fetched=False,
            )
        with span("step.sync", step=step_idx):
            # THE deliberate per-log-interval host sync, inside its own
            # measured span (docs/OBSERVABILITY.md step.sync)
            loss = float(loss)  # sta: disable=STA010
        # a fetch after unfetched steps drains their whole device backlog,
        # so this step's wall time covers several steps of device work;
        # report the amortized per-step time (what tokens/s and the TFLOPs
        # estimators divide by) instead of the ~interval-x drain time
        backlog = self._unfetched_steps
        self._unfetched_steps = 0
        now = time.time()
        if backlog and self._last_fetch_wall is not None:
            step_duration = (now - self._last_fetch_wall) / (backlog + 1)
        else:
            step_duration = now - start
        self._last_fetch_wall = now
        if self.profiler is not None:
            self.profiler.record(
                step_idx,
                {"data_load": t_data, "step_time": step_duration - t_data},
            )
            self.profiler.end_step(step_idx)
        return TrainStepOutput(
            loss=loss,
            metrics={k: float(v) for k, v in metrics.items()},
            global_grad_norm=_maybe_float(opt_out.global_grad_norm),
            learning_rates={k: float(v) for k, v in (opt_out.learning_rates or {}).items()},
            overflow=_maybe_bool(opt_out.overflow),
            no_overflow_steps=_maybe_int(opt_out.no_overflow_steps),
            current_loss_scale=_maybe_float(opt_out.current_loss_scale),
            step_duration=step_duration,
        )

    def eval_step(self) -> EvaluationStepOutput:
        with span("trainer.eval", step=self.context.iterations):
            return self._eval_step_inner()

    def _eval_step_inner(self) -> EvaluationStepOutput:
        start = time.time()
        assert self.dataloader_evaluation is not None, "no evaluation dataset"
        losses, metric_list = [], []
        for _ in range(max(self.config.eval_iterations, 1)):
            batch = self.batch_to_model_input(next(self.dataloader_evaluation))
            batch = self.module.shard_batch(batch, stacked=False)
            loss, metrics = self._eval_step(self.params, batch)
            losses.append(float(loss))
            metric_list.append({k: float(v) for k, v in metrics.items()})
            self.context.consumed_eval_samples += (
                self.topology.config.micro_batch_size
                * self.topology.config.data_parallel_size
            )
        mean_metrics = {
            k: float(np.mean([m[k] for m in metric_list])) for k in metric_list[0]
        } if metric_list else {}
        return EvaluationStepOutput(
            loss=float(np.mean(losses)),
            metrics=mean_metrics,
            step_duration=time.time() - start,
        )

    # ------------------------------------------------------- control plane
    def attach_control_plane(
        self,
        cp: ControlPlane,
        *,
        step_barrier: bool = True,
        barrier_timeout_s: float = 300.0,
        peer_stale_s: float = 60.0,
        shared_save_dir: bool = False,
    ) -> None:
        """Join a multi-host supervision control plane (docs/RESILIENCE.md).

        Per loop iteration this host then: publishes a heartbeat, obeys
        the supervisor's ``abort`` flag (exit fast instead of hanging in
        a collective whose peer is gone), broadcasts/observes the
        ``preempt`` flag (one host's SIGTERM becomes everyone's
        save-and-exit at the SAME step boundary), and — with
        ``step_barrier`` — rendezvouses at ``step-N`` so the preemption
        decision is taken in lockstep even when the step program itself
        would tolerate skew. ``save_checkpoint`` additionally enters the
        ``commit:step-N`` barrier between shard commit and the ``latest``
        advance. ``shared_save_dir=True`` means all hosts write one
        shared checkpoint tree (orbax on shared storage): only host 0
        advances ``latest``; with per-host shard dirs every host owns
        its own pointer, still gated on the same barrier."""
        if not step_barrier and cp.num_hosts > 1:
            # without the lockstep rendezvous nothing bounds step skew,
            # so a drain can end with hosts saving at different steps
            # and parking in commit barriers that never fill
            logger.warning(
                "attach_control_plane(step_barrier=False) on a "
                f"{cp.num_hosts}-host plane: coordinated preemption "
                "cannot guarantee a same-step boundary and commit "
                "barriers may time out during a drain"
            )
        self._control_plane = cp
        self._cp_step_barrier = step_barrier
        self._cp_barrier_timeout = barrier_timeout_s
        self._cp_peer_stale = peer_stale_s
        self._cp_latest_leader = (not shared_save_dir) or cp.host_id == 0

    def _control_plane_checkin(self) -> bool:
        """Top-of-iteration supervision protocol (see attach_control_plane).

        Returns True when this host must exit at the CURRENT boundary
        (its own preemption decided before arriving at the step barrier,
        or a peer's broadcast observed pre- or post-barrier). The
        boundary decision is only ever taken at those points: a local
        SIGTERM that lands while we are INSIDE the barrier wait comes
        too late — we already rendezvoused for the next step, and
        peers may already be parked at ITS barrier — so that host runs
        one more step and exits through the post-step path instead,
        where the broadcast-plus-arrival releases peers at the matching
        boundary. Flag-before-arrival ordering makes the released
        peer's post-barrier flag check reliable."""
        cp = self._control_plane
        if cp is None:
            return self._preempted
        step = self.context.iterations
        # the first iteration's step still pays the cold jit compile —
        # report "starting" so the supervisor applies the startup grace,
        # not the steady-state heartbeat timeout
        cp.heartbeat(step, status="starting" if self._cp_first_checkin
                     else "running")
        self._cp_first_checkin = False
        if cp.get_flag(ABORT_FLAG) is not None:
            logger.log_event("abort-observed", host=cp.host_id, step=step)
            raise JobAborted(
                "supervisor raised the abort flag: a peer host is gone, "
                "so barriers/collectives can never complete — exiting "
                "without a save (the last committed checkpoint stands)"
            )
        if not self._preempted and cp.get_flag(PREEMPT_FLAG) is not None:
            self._preempted = True
        if self._preempted:
            # exiting at THIS boundary: flag + arrival (idempotent, via
            # _broadcast_preempt) release any peer already parked inside
            # this step's barrier; skipping the wait ourselves is safe —
            # the save's commit barrier is the real rendezvous
            self._broadcast_preempt(step)
            return True
        if self._cp_step_barrier and cp.num_hosts > 1:
            cp.barrier(f"step-{step}", self._cp_barrier_timeout)
            if step >= 2 and cp.host_id == 0:
                # every host arrived at step-{step} for us to be here, so
                # none can ever wait on step-{step-2} again — unbounded
                # arrival state on long runs otherwise. One prune suffices;
                # all N hosts issuing it is N-1 wasted coordinator round
                # trips per step on the TCP backend
                cp.prune_barrier(f"step-{step - 2}")
            if cp.get_flag(PREEMPT_FLAG) is not None:
                # the broadcaster arrived at THIS barrier, so its exit
                # boundary is this one — join it
                self._preempted = True
                return True
        # a local signal that landed during the barrier wait is handled
        # post-step (see docstring), never here
        return False

    def _broadcast_preempt(self, step: int) -> None:
        """Make this host's preemption everyone's, without stranding a
        peer: set the preempt flag (once), then register arrival at this
        boundary's step barrier. Exit paths never re-enter the loop top,
        so a peer already parked inside ``step-N`` would otherwise wait
        out the full barrier timeout for an arrival that never comes.
        Flag-before-arrival ordering means a peer released by our
        arrival always observes the flag on its post-barrier check."""
        cp = self._control_plane
        if cp is None:
            return
        if cp.get_flag(PREEMPT_FLAG) is None:
            cp.set_flag(PREEMPT_FLAG, str(step))
            logger.log_event("preempt-broadcast", host=cp.host_id, step=step)
        if self._cp_step_barrier and cp.num_hosts > 1:
            cp.arrive(f"step-{step}")

    def _commit_barrier_and_latest(self, commit: CheckpointCommit) -> None:
        """Cross-host commit barrier: this host's shard is committed
        (manifest + rename done); ``latest`` may only advance once EVERY
        host has committed its shard for this step. A host killed in
        this window leaves peers timing out at the barrier — ``latest``
        stays at the previous step on every host, so restore can never
        assemble a mixed-step checkpoint."""
        cp = self._control_plane
        if cp is not None and cp.num_hosts > 1:
            get_fault_plan().fire("ckpt.commit_barrier", path=commit.final_dir)
            # the commit-barrier wait IS the per-host straggler signal:
            # the host that waits longest committed first, the one that
            # waits ~0 made everyone else wait (analyzer attributes this
            # offline from the span stream). Every host derives the SAME
            # trace id from the commit identity — no context crosses the
            # wire, yet obs trace reassembles one commit:step-N trace
            # spanning all hosts (per coordination epoch: a post-relaunch
            # re-save of the same step is a different incident)
            from ..obs import derive_trace_id, trace_context

            commit_trace = derive_trace_id(
                "ckpt-commit", commit.step,
                os.environ.get("SCALING_TPU_COORD_EPOCH", "0"),
            )
            with trace_context(commit_trace):
                with span("ckpt.commit_barrier", step=commit.step,
                          host=cp.host_id):
                    cp.barrier(
                        f"commit:step-{commit.step}",
                        self._cp_barrier_timeout,
                    )
            prev = self._cp_prev_commit_step
            if prev is not None and prev != commit.step and cp.host_id == 0:
                # every host passed THIS commit barrier, so none can ever
                # wait on the previous step's again; keep the current
                # one's arrivals sticky (a preemption re-save of the same
                # step must re-enter it instantly). Host 0 only — one
                # prune suffices
                cp.prune_barrier(f"commit:step-{prev}")
            self._cp_prev_commit_step = commit.step
        if self._cp_latest_leader:
            with span("ckpt.latest", step=commit.step):
                commit.update_latest()

    # ----------------------------------------------------------- preemption
    def install_preemption_handler(self) -> None:
        """Save-and-exit on SIGTERM — the TPU-pod equivalent of the
        reference's Determined preemption hook (reference:
        trainer.py:449-456): GKE spot/preemptible nodes deliver SIGTERM
        ahead of reclaim; the next run resumes from the saved step.

        Chains to any previously installed SIGTERM handler (launchers,
        log flushers, cluster agents) instead of silently discarding it.
        """
        import signal

        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            self._preempted = True
            if callable(prev):  # SIG_DFL/SIG_IGN are enum ints, skipped
                prev(signum, frame)

        self._preempted = False
        signal.signal(signal.SIGTERM, handler)

    # ----------------------------------------------------------- preemption
    def _preemption_requested(self) -> bool:
        if not self._preempted and self._control_plane is not None:
            # another host broadcast preemption since our last check
            if self._control_plane.get_flag(PREEMPT_FLAG) is not None:
                self._preempted = True
        return self._preempted or (
            self.external_preemption is not None and self.external_preemption()
        )

    def _preemption_exit(self) -> None:
        # a mid-step SIGTERM lands here WITHOUT passing another checkin:
        # broadcast (and release any peer parked at this boundary's step
        # barrier) before saving, or the commit barrier below would wait
        # on peers that never learned they must save
        self._broadcast_preempt(self.context.iterations)
        if (
            self.config.save_dir is not None
            and self._last_saved_step == self.context.iterations
        ):
            # the will_save path just saved this exact boundary (lockstep
            # peers all did the same, so no commit-barrier mismatch);
            # re-staging an identical checkpoint on the preemption
            # critical path can overrun a tight reclaim grace. Still
            # drain the async writer so that save is durably committed.
            self.finalize_checkpoints()
            logger.info(
                "preemption: boundary already checkpointed, exiting cleanly"
            )
        elif self.config.save_dir is not None:
            if self._control_plane is not None:
                # same head-of-window refresh as the regular will_save
                # path: the last heartbeat was at the loop-top checkin,
                # a whole step ago — without this, heartbeat_timeout
                # must budget step+save and the supervisor can declare
                # us hung (and SIGKILL us) mid-final-save
                self._control_plane.heartbeat(
                    self.context.iterations, status="running"
                )
            step_dir = self.save_checkpoint()
            self.finalize_checkpoints()
            self._run_checkpoint_hooks(step_dir)
            logger.info("preemption: checkpoint saved, exiting cleanly")
        if self._control_plane is not None:
            self._control_plane.heartbeat(
                self.context.iterations, status="preempted"
            )

    def _on_step_stall(self, step: int, elapsed: float) -> None:
        """Watchdog callback: the watchdog thread must not host-gather
        donated device buffers mid-step, so it requests a save at the
        next safe point — if the stalled step ever completes, the loop
        saves-and-exits via the preemption path.

        With a control plane attached, peer heartbeats turn the blind
        "no progress for Ns" into a verdict: a peer that stopped
        publishing is dead (the collective will never complete — the
        supervisor is about to tear us down), otherwise the stall is
        local (wedged storage, stuck data worker)."""
        verdict = "local-stall"
        dead: List[int] = []
        cp = self._control_plane
        if cp is not None:
            try:
                report = straggler_table(
                    cp.peer_heartbeats(), cp.num_hosts, self._cp_peer_stale
                )
            # ValueError included: a truncated TCP reply surfaces as
            # json.JSONDecodeError, and this watchdog-thread callback
            # must reach the save-and-exit request below no matter what
            except (OSError, RuntimeError, ValueError) as e:
                logger.warning(f"peer heartbeat read failed mid-stall: {e!r}")
            else:
                # our own heartbeat is necessarily stale mid-stall (the
                # main thread is stuck inside the step, not publishing),
                # so counting ourselves would turn every local stall
                # into a false "peer-host-dead"
                dead = [h for h in report.dead_hosts if h != cp.host_id]
                if dead:
                    verdict = "peer-host-dead"
                logger.error(
                    f"stall straggler table (stale after "
                    f"{self._cp_peer_stale}s):\n{report.render()}"
                )
        logger.log_event(
            "step-stall", step=step, elapsed_s=round(elapsed, 1),
            verdict=verdict, dead_hosts=dead,
            host=cp.host_id if cp is not None else 0,
        )
        logger.error(
            f"step stall after step {step} ({elapsed:.1f}s, {verdict}): "
            "requesting save-and-exit at the next loop boundary"
        )
        if cp is not None:
            try:
                # the drain below exits every host with code 0 — the
                # stall flag is what tells the supervisor this was NOT a
                # finished run, so it relaunches instead of reporting
                # success mid-training
                cp.set_flag(STALL_FLAG, str(step))
            except (OSError, RuntimeError, ValueError) as e:
                logger.warning(f"stall flag broadcast failed: {e!r}")
        self._preempted = True

    # ----------------------------------------------------------- train loop
    def run_training(self, log_metrics_fn: Optional[Callable] = None) -> None:
        assert self.config.train_iterations is not None
        topo = self.topology
        if topo is not None and topo.pipe_parallel_size > 1:
            # the obs report's pipeline section needs the schedule shape to
            # attribute span-measured step time against the predicted
            # bubble (docs/PIPELINE.md); one lifecycle event carries it
            logger.log_event(
                "pipeline-config",
                pp=topo.pipe_parallel_size,
                virtual=topo.pipe_virtual_size,
                token_slices=topo.pipe_token_slices,
                gas=topo.gradient_accumulation_steps,
            )
        # the auto-sharding tuner's predicted step time for this run's
        # layout (exported by `python -m scaling_tpu.tune` as
        # SCALING_TPU_TUNER_PREDICTION): logged into the SAME events
        # stream so `obs report` can score prediction vs span-measured
        # step time — the tuner's calibration loop (docs/TUNING.md)
        from ..tune import prediction_from_env

        prediction = prediction_from_env()
        if prediction is not None:
            logger.log_event("tuner-prediction", **prediction)
        watchdog = None
        if self.config.step_timeout_seconds is not None:
            # created here, ARMED by the loop after the first step
            # completes: the cold jit compile (minutes on big models)
            # must not read as a stall
            watchdog = StepStallWatchdog(
                self.config.step_timeout_seconds, on_stall=self._on_step_stall
            )
        try:
            self._run_training_loop(log_metrics_fn, watchdog)
        finally:
            if watchdog is not None:
                watchdog.stop()
            if self.profiler is not None:
                # abort paths (NonFiniteLossError, SIGTERM drain, stall)
                # must not lose a partially collected window or leave an
                # XLA trace running
                self.profiler.close()

    def _emit_step_metrics(
        self, output: TrainStepOutput, log_metrics_fn: Optional[Callable]
    ) -> None:
        if not output.fetched:
            # unfetched steps (log_interval > 1) carry in-flight device
            # arrays; touching them here would reintroduce the per-step
            # sync the knob exists to remove
            return
        metrics = {
            "loss": output.loss,
            **output.metrics,
            **(output.learning_rates or {}),
        }
        if output.global_grad_norm is not None:
            metrics["global_grad_norm"] = output.global_grad_norm
        if output.current_loss_scale is not None:
            metrics["loss_scale"] = output.current_loss_scale
        metrics["step_duration"] = output.step_duration
        if log_metrics_fn is not None:
            metrics = log_metrics_fn(self, output, metrics)
        try:
            # host-side gauges only (memory stats, EMA, MFU): adds no
            # device syncs — see tests/core/test_obs/test_step_path.py
            metrics.update(self.telemetry.on_step(
                self.context.iterations, output.step_duration
            ))
        except Exception as e:
            # telemetry must never abort a training step
            logger.warning(f"step telemetry update failed: {e!r}")
        logger.log_metrics(metrics, self.context.iterations)
        self.telemetry.flush(self.context.iterations)
        for hook in self.metrics_hooks:
            try:
                hook(metrics, self.context.iterations)
            except Exception as e:
                # reporting must never abort a training step
                logger.warning(f"metrics hook failed: {e}")

    def _run_training_loop(
        self, log_metrics_fn: Optional[Callable],
        watchdog: Optional[StepStallWatchdog] = None,
    ) -> None:
        watchdog_armed = False
        while self.context.iterations < self.config.train_iterations:
            if watchdog is not None and watchdog_armed:
                watchdog.beat(self.context.iterations)
            get_fault_plan().fire("signal.sigterm")
            get_fault_plan().fire("host.kill")
            get_fault_plan().fire("host.hang")
            # heartbeat + abort/preempt flags + lockstep barrier; raises
            # JobAborted when the supervisor is tearing this epoch down.
            # True = exit at this boundary: a SIGTERM that arrived during
            # the checkpoint/eval window (or a stall flag) must exit
            # without burning another full step. The external predicate
            # is NOT polled here — cluster glue (Determined) counts one
            # poll per completed step
            if self._control_plane_checkin():
                self._preemption_exit()
                return
            output = self.train_step()
            if watchdog is not None and not watchdog_armed:
                watchdog_armed = True
                watchdog.start()  # steady-state steps from here on
            if (
                self._preemption_requested()
                and self.context.iterations < self.config.train_iterations
            ):
                # the step that just completed is about to be saved by
                # the preemption exit — its metrics must reach the sinks
                # too (same contract as the non-finite abort below).
                # NOT at the final boundary: the run is complete, and a
                # drain here would save + enter a commit barrier that
                # peers who missed the flag (they exit 'done' without
                # another checkin) never arrive at — every host must
                # take the identical normal exit path instead
                self._emit_step_metrics(output, log_metrics_fn)
                self._preemption_exit()
                return
            will_save = (
                self.config.save_dir is not None
                and self.config.save_interval is not None
                and self.context.iterations % self.config.save_interval == 0
            )
            will_eval = (
                self.config.eval_interval is not None
                and self.dataset_evaluation is not None
                and self.context.iterations % self.config.eval_interval == 0
            )
            if (will_save or will_eval) and self._unfetched_steps:
                # checkpoint/eval sync the device anyway; draining FIRST
                # pins the unfetched backlog's device work inside the train
                # window, so the aux-time exclusion below can't swallow
                # real step time that would have drained during the aux work
                jax.block_until_ready(self.opt_state.step)  # sta: disable=STA010
            if (will_save or will_eval) and self._control_plane is not None:
                # the save/eval window publishes no step heartbeats (a
                # long eval can exceed heartbeat_timeout on its own);
                # restart the staleness clock here so the timeout only
                # has to budget for the window itself, not step+window
                self._control_plane.heartbeat(
                    self.context.iterations, status="running"
                )
            aux_start = time.time()
            if will_save:
                step_dir = self.save_checkpoint()
                self._run_checkpoint_hooks(step_dir)
            if will_eval:
                eval_out = self.eval_step()
                logger.log_metrics(
                    {"eval_loss": eval_out.loss, **{f"eval_{k}": v for k, v in eval_out.metrics.items()}},
                    self.context.iterations,
                )
            if (will_save or will_eval) and self._last_fetch_wall is not None:
                # the amortized step_duration divides (next fetch - last
                # fetch) by the backlog; checkpoint/eval wall time between
                # fetches is not train-step work and would inflate it
                self._last_fetch_wall += time.time() - aux_start
            self._emit_step_metrics(output, log_metrics_fn)
            if self._nonfinite_guard is not None and output.fetched:
                # after logging, so the aborting step's metrics still
                # reach the sinks. Fetched outputs only: unfetched steps
                # carry in-flight device arrays whose inspection would
                # force the sync log_interval exists to remove
                try:
                    self._nonfinite_guard.observe(
                        self.context.iterations, output.loss,
                        output.overflow, output.current_loss_scale,
                    )
                except NonFiniteLossError:
                    # budget exhausted: leave a resumable checkpoint
                    # behind, then surface the diagnosis
                    if self.config.save_dir is not None:
                        step_dir = self.save_checkpoint()
                        self.finalize_checkpoints()
                        self._run_checkpoint_hooks(step_dir)
                        logger.error(
                            f"non-finite abort: state saved to {step_dir}"
                        )
                    raise
        self.finalize_checkpoints()
        if self._control_plane is not None:
            # the supervisor's straggler table should read "done", not a
            # stale "running" that looks like a hang at shutdown
            self._control_plane.heartbeat(self.context.iterations, status="done")

    def _run_checkpoint_hooks(self, step_dir: Path) -> None:
        if not self.checkpoint_hooks:
            return
        if self._ckpt_writer is not None:
            # hooks must see a durable checkpoint, not an in-flight async
            # write — a torn copy must never leave the machine
            self._ckpt_writer.wait()
        for hook in self.checkpoint_hooks:
            try:
                hook(step_dir, self.context.iterations)
            except Exception as e:
                logger.warning(f"checkpoint hook failed: {e}")

    # ----------------------------------------------------------- checkpoint
    def finalize_checkpoints(self) -> None:
        """Block until pending async checkpoint writes are durable.

        Deliberately leaves the prefetch thread running: the trainer may
        train again (queued batches continue the exact stream); the daemon
        thread dies with the process."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.wait()

    def _config_fingerprint(self) -> Optional[str]:
        """Stable digest of the run config, stamped into the checkpoint
        manifest (restore logs a warning when it changes across a
        resume — legitimate for finetunes, suspicious otherwise)."""
        cfg = getattr(self.context, "config", None)
        if cfg is None or not hasattr(cfg, "model_dump"):
            return None
        import hashlib
        import json as _json

        blob = _json.dumps(cfg.model_dump(mode="json"), sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    # ------------------------------------------------- mesh metadata (elastic)
    def _num_hosts(self) -> int:
        """Host count of the pod writing/reading this checkpoint: the
        control plane when attached (supervised runs), the supervisor's
        env contract otherwise, falling back to the jax process count."""
        if self._control_plane is not None:
            return int(self._control_plane.num_hosts)
        env = os.environ.get(ENV_NUM_HOSTS)
        if env is not None:
            return int(env)
        return int(jax.process_count())

    def _current_topology_dict(self) -> dict:
        cfg = self.topology.config
        return {
            "world_size": cfg.world_size,
            "pipe_parallel_size": cfg.pipe_parallel_size,
            "data_parallel_size": cfg.data_parallel_size,
            "context_parallel_size": cfg.context_parallel_size,
            "model_parallel_size": cfg.model_parallel_size,
            "pipe_virtual_size": cfg.pipe_virtual_size,
            "pipe_token_slices": cfg.pipe_token_slices,
            "micro_batch_size": cfg.micro_batch_size,
            "gradient_accumulation_steps": cfg.gradient_accumulation_steps,
            "global_batch_size": cfg.global_batch_size,
            "num_hosts": self._num_hosts(),
        }

    def _param_records(self, params_view, metas) -> dict:
        """meta key -> global shape/dtype/sharding-spec record for
        MESH.json. The ckpt-view tree holds GLOBAL logical arrays (the
        stage stacking is already undone), so .shape here is the
        mesh-independent shape any reader reconstructs — no device sync
        (shape/dtype are host-side metadata)."""
        from ..nn.param import ParamMeta

        p_leaves = jax.tree.leaves(params_view)
        m_leaves = jax.tree.leaves(
            metas, is_leaf=lambda x: isinstance(x, ParamMeta)
        )
        return {
            m.key: param_record(
                p.shape, p.dtype, getattr(m, "partition_spec", ())
            )
            for p, m in zip(p_leaves, m_leaves)
        }

    def _mesh_meta(self, params_view, metas) -> dict:
        opt_cfg = self.optimizer.config
        zero_stage = (
            int(getattr(opt_cfg, "zero_stage", 1))
            if getattr(opt_cfg, "zero", False)
            else 0
        )
        return build_mesh_meta(
            topology=self._current_topology_dict(),
            params=self._param_records(params_view, metas),
            optimizer={
                "zero_stage": zero_stage,
                "fields": ["master", "exp_avg", "exp_avg_sq"],
                # on-disk optimizer leaves mirror the param tree as
                # GLOBAL arrays (ckpt_view gathers zero-partitioned
                # state), so a resharder re-slices them the same way
                "layout": "global-per-layer",
            },
            step=self.context.iterations,
        )

    def save_checkpoint(self, dir: Optional[Path | str] = None) -> Path:
        """Atomic commit protocol (docs/RESILIENCE.md): everything is
        written into a ``.tmp-global_stepN`` staging dir, checksummed
        into ``MANIFEST.json``, fsynced and atomically renamed onto
        ``global_stepN`` before ``latest`` moves — a kill at any instant
        leaves the previous committed checkpoint intact and loadable.

        Traced as ``trainer.save`` (on the async path this covers only
        the host gather + submit; the writer thread's own ``ckpt.*``
        spans carry the durable-write cost)."""
        with span("trainer.save", step=self.context.iterations):
            return self._save_checkpoint_inner(dir)

    def _save_checkpoint_inner(self, dir: Optional[Path | str] = None) -> Path:
        base = Path(dir or self.config.save_dir)
        base.mkdir(parents=True, exist_ok=True)
        writer = None
        if self.config.save_checkpoint_async:
            if self._ckpt_writer is None:
                self._ckpt_writer = AsyncCheckpointWriter()
            else:
                self._ckpt_writer.wait()  # never interleave two saves
            writer = self._ckpt_writer
        # AFTER the writer barrier: creating the commit sweeps stale
        # .tmp-* staging debris, which must never race a previous async
        # save's still-pending finalize
        commit = CheckpointCommit(
            base, self.context.iterations,
            config_fingerprint=self._config_fingerprint(),
        )
        stage_dir = commit.tmp_dir
        # checkpoint-view trees: stage-stacked pipeline bodies un-stack into
        # per-layer files so checkpoints are pipe-layout independent
        viewed_opt = self.opt_state._replace(
            master=self.module.ckpt_view(self.opt_state.master),
            exp_avg=self.module.ckpt_view(self.opt_state.exp_avg),
            exp_avg_sq=self.module.ckpt_view(self.opt_state.exp_avg_sq),
        )
        metas = self.module.ckpt_metas()
        params_view = self.module.ckpt_view(self.params)
        with span("ckpt.stage", step=self.context.iterations,
                  backend=self.config.checkpoint_backend.value):
            if self.config.checkpoint_backend == CheckpointBackend.ORBAX:
                self._save_orbax(stage_dir, viewed_opt, params_view)
            else:
                # checked here, not in config validation: jax.process_count()
                # initializes the backend as a side effect, which would break a
                # later jax.distributed.initialize() for configs built early
                if jax.process_count() > 1:
                    raise RuntimeError(
                        "the npz checkpoint backend host-gathers every array "
                        "and cannot run multi-process; set "
                        "trainer.checkpoint_backend: orbax for multi-host runs"
                    )
                save_model_checkpoint(
                    stage_dir, params_view, metas,
                    separate_file_for_parameters=getattr(
                        self.module, "separate_file_for_parameters", None
                    ),
                    writer=writer,
                    recorder=commit.record,
                )
                save_optimizer_checkpoint(
                    stage_dir, viewed_opt, metas, writer=writer,
                    recorder=commit.record,
                )
            # MESH.json (docs/RESILIENCE.md "Elastic resharding"): the
            # logical param tree + saving topology, staged with the rest
            # so the commit's manifest scan digests it — restore at a
            # different mesh shape verifies against it instead of
            # assuming the disk layout matches the current mesh
            write_mesh_meta(stage_dir, self._mesh_meta(params_view, metas))
            self.context.save_checkpoint(stage_dir)
            # full config travels with the weights so inference can rebuild
            # the architecture (reference: context.py:113-125 config.yml copy)
            cfg = getattr(self.context, "config", None)
            if cfg is not None and hasattr(cfg, "model_dump"):
                import yaml as _yaml

                (stage_dir / "config.yml").write_text(
                    _yaml.safe_dump(cfg.model_dump(mode="json"), sort_keys=False)
                )
                # tokenizer travels with the weights so inference needs
                # nothing else (reference: inference_model.py:70 vocab.json)
                vocab = getattr(
                    getattr(cfg, "transformer_architecture", None), "vocab_file", None
                )
                if vocab and Path(vocab).is_file():
                    shutil.copyfile(vocab, stage_dir / "vocab.json")
        step_dir = commit.final_dir
        if writer is None:
            commit.finalize()
            self._commit_barrier_and_latest(commit)
        else:
            # the single writer thread is FIFO: the manifest+rename, the
            # cross-host commit barrier, and then "latest" land only
            # after every npz of this save is durable
            writer.submit(commit.finalize)
            writer.submit(self._commit_barrier_and_latest, commit)
        logger.info(f"saved checkpoint {step_dir}")
        if self.config.delete_past_optimizer_states:
            if writer is None:
                self._prune_past_optimizer_states(base, step_dir)
            else:
                # AFTER the queued finalize+latest: pruning the previous
                # checkpoint's optimizer state before the new save is
                # committed would open a crash window with no optimizer
                # state anywhere on disk
                writer.submit(self._prune_past_optimizer_states, base, step_dir)
        self._last_saved_step = self.context.iterations
        return step_dir

    def _prune_past_optimizer_states(self, base: Path, step_dir: Path) -> None:
        for old in sorted(base.glob("global_step*")):
            if old == step_dir:
                continue
            removed = []
            for f in old.glob("optimizer_state_*"):
                f.unlink()
                removed.append(f.name)
            old_orbax_opt = old / "orbax" / "optimizer"
            if old_orbax_opt.is_dir():
                removed.extend(
                    p.relative_to(old).as_posix()
                    for p in old_orbax_opt.rglob("*") if p.is_file()
                )
                shutil.rmtree(old_orbax_opt)
            if removed:
                # keep the pruned checkpoint valid in the eyes of the
                # fallback scanner: its manifest must not list files
                # this deliberate pruning removed
                from ..resilience import prune_manifest_entries

                prune_manifest_entries(old, removed)

    def _save_orbax(self, step_dir: Path, viewed_opt: OptimizerState,
                    params_view=None) -> None:
        """Tensorstore-backed sharded save: every host writes only its own
        shards — no host gather, unlike the npz path (save trees are the
        same per-layer canonical views, so pp/mp relayouts still restore)."""
        from ..checkpoint.orbax_backend import save_orbax

        save_orbax(
            step_dir,
            params_view if params_view is not None
            else self.module.ckpt_view(self.params),
            {
                "step": viewed_opt.step,
                "master": viewed_opt.master,
                "exp_avg": viewed_opt.exp_avg,
                "exp_avg_sq": viewed_opt.exp_avg_sq,
                "loss_scaler": viewed_opt.loss_scaler._asdict(),
            },
        )

    def _restore_orbax_params(self, step_dir: Path, metas, restored_keys=None,
                              params_view=None):
        """Restore the param view tree, re-sharded to the CURRENT mesh
        layout (orbax reads each shard from tensorstore). Non-strict under
        the same allow-list regexes as the npz loader, so PEFT/LoRA loads
        work against orbax base checkpoints too."""
        from ..checkpoint.orbax_backend import restore_orbax_params

        return restore_orbax_params(
            step_dir,
            params_view if params_view is not None
            else self.module.ckpt_view(self.params),
            metas,
            allowed_missing_keys=self.config.allowed_missing_keys_in_checkpoint,
            allowed_unexpected_keys=self.config.allowed_unexpected_keys_in_checkpoint,
            ignore_keys=self.config.ignore_keys_in_checkpoint,
            restored_keys=restored_keys,
        )

    def _restore_orbax_opt(self, step_dir: Path) -> OptimizerState:
        """Restore the optimizer view trees (call only when the caller wants
        optimizer states — missing/mismatched trees raise and the caller
        re-derives fresh state, like the npz path)."""
        from ..checkpoint.orbax_backend import restore_orbax_opt

        restored = restore_orbax_opt(
            step_dir,
            {
                "step": self.opt_state.step,
                "master": self.module.ckpt_view(self.opt_state.master),
                "exp_avg": self.module.ckpt_view(self.opt_state.exp_avg),
                "exp_avg_sq": self.module.ckpt_view(self.opt_state.exp_avg_sq),
                "loss_scaler": self.opt_state.loss_scaler._asdict(),
            },
        )
        # scalars come back COMMITTED to whatever single device orbax used;
        # jit refuses to relocate committed arrays across the mesh, so hand
        # them back as host values (uncommitted — jit places them freely)
        return self.opt_state._replace(
            step=np.asarray(restored["step"]),
            master=restored["master"],
            exp_avg=restored["exp_avg"],
            exp_avg_sq=restored["exp_avg_sq"],
            loss_scaler=type(self.opt_state.loss_scaler)(
                **jax.tree.map(np.asarray, restored["loss_scaler"])
            ),
        )

    def load_checkpoint(self, dir: Optional[Path | str] = None) -> bool:
        """Verified restore with fallback: candidates are tried in
        preference order (a valid ``latest`` pointer first, then every
        ``global_step*`` newest-first); each must pass manifest
        verification and actually load — corrupt or torn ones are
        skipped with an exact reason, so a run resumes from the most
        recent VALID state instead of crashing on a rotten one.
        ``trainer.strict_checkpoint_load`` turns any skip into an error.
        """
        base = Path(dir or self.config.load_dir)
        strict = self.config.strict_checkpoint_load
        candidates = checkpoint_candidates(base)
        if not candidates:
            logger.warning(f"no checkpoint found at {base}")
            return False
        skipped: List[str] = []
        for step_dir in candidates:
            problems = verify_checkpoint(
                step_dir, deep=self.config.deep_checkpoint_verification
            )
            if problems:
                line = f"{step_dir.name}: {'; '.join(problems)}"
                if strict:
                    raise CheckpointCorruptionError(
                        f"checkpoint verification failed (strict mode): {line}"
                    )
                logger.warning(f"skipping invalid checkpoint {line}")
                skipped.append(line)
                continue
            try:
                # a TRANSIENT read error must not demote a checkpoint
                # that just passed verification — retry the (idempotent)
                # load before treating the OSError as corruption
                retry_io(
                    lambda d=step_dir: self._load_step_dir(d),
                    attempts=self.config.io_retry_attempts,
                    base_delay=self.config.io_retry_backoff_seconds,
                    retry_on=(OSError,),
                    what=f"checkpoint load {step_dir.name}",
                )
            except _CORRUPT_LOAD_ERRORS as e:
                # disk-level corruption the manifest could not vouch
                # against (legacy manifest-less checkpoints, torn orbax
                # trees). Config/shape mismatches, OOMs and assertion
                # errors are NOT in this tuple — those abort, falling
                # back would silently load the wrong science.
                line = f"{step_dir.name}: load failed ({type(e).__name__}: {e})"
                if strict:
                    raise
                logger.warning(f"skipping unreadable checkpoint {line}")
                skipped.append(line)
                continue
            if skipped:
                logger.warning(
                    f"resumed from {step_dir.name} after skipping "
                    f"{len(skipped)} checkpoint(s): " + " | ".join(skipped)
                )
            return True
        logger.warning(
            f"no valid checkpoint under {base}; skipped: " + " | ".join(skipped)
        )
        return False

    def _load_step_dir(self, step_dir: Path) -> None:
        manifest = read_manifest(step_dir)
        if manifest is not None and manifest.get("config_fingerprint"):
            current = self._config_fingerprint()
            if current is not None and current != manifest["config_fingerprint"]:
                logger.warning(
                    f"config fingerprint changed since {step_dir.name} was "
                    f"saved ({manifest['config_fingerprint']} -> {current}); "
                    "expected for finetunes/topology changes, suspicious "
                    "for a plain resume"
                )
        from ..checkpoint.orbax_backend import orbax_model_valid

        orbax_dir_present = (step_dir / "orbax").is_dir()
        orbax_backend = orbax_dir_present and orbax_model_valid(step_dir)
        if orbax_dir_present and not orbax_backend:
            # a crashed orbax save must not shadow valid npz files in the
            # same step dir (and must fail loudly when nothing else exists)
            if not list(step_dir.glob("model_state_layer_*.npz")):
                raise CheckpointCorruptionError(
                    f"{step_dir / 'orbax'} exists but holds no committed orbax "
                    "checkpoint (torn save?) and no npz files are present"
                )
            logger.warning(
                f"{step_dir / 'orbax'} is not a committed orbax checkpoint; "
                "falling back to the npz files in the same step dir"
            )
        metas = self.module.ckpt_metas()
        current_view = self.module.ckpt_view(self.params)
        # reshard-on-restore (docs/RESILIENCE.md "Elastic resharding"):
        # when the checkpoint's MESH.json topology differs from the
        # restoring one, pre-flight the logical param tree (a global-
        # shape disagreement is a different model — abort, never "fall
        # back"), then take the SAME per-layer global-array load below:
        # device_put against the current metas re-slices every leaf
        # (params AND zero-partitioned optimizer state) onto the new
        # mesh, with ckpt_view/ckpt_unview handling the vpp stacking.
        # Legacy checkpoints without MESH.json restore at the same
        # shape exactly as before (plan is None).
        plan = reshard_plan(
            read_mesh_meta(step_dir),
            self._current_topology_dict(),
            self._param_records(current_view, metas),
        )
        if plan is not None:
            fire_reshard_point(step_dir, plan)
            logger.log_event(
                "ckpt-reshard", step=manifest.get("step")
                if manifest is not None else None,
                **plan.event_fields(),
            )
        self.restored_model_keys = set()
        if orbax_backend:
            params_view = self._restore_orbax_params(
                step_dir, metas, restored_keys=self.restored_model_keys,
                params_view=current_view,
            )
        else:
            params_view = load_model_checkpoint(
                step_dir,
                current_view,
                metas,
                allowed_missing_keys=self.config.allowed_missing_keys_in_checkpoint,
                allowed_unexpected_keys=self.config.allowed_unexpected_keys_in_checkpoint,
                ignore_keys=self.config.ignore_keys_in_checkpoint,
                restored_keys=self.restored_model_keys,
            )
        self.params = self.module.ckpt_unview(params_view, self.params)
        merged_lora = False
        if self.config.merge_lora_after_loading_checkpoint:
            self.params = self.module.merge_lora_weights(self.params)
            merged_lora = True
            logger.info("merged LoRA deltas into base weights after load")
        optimizer_states_loaded = False
        # after a merge the checkpoint's fp32 masters are stale (they hold the
        # unmerged weights and nonzero lora_b — the first step would resurrect
        # the folded delta); re-derive instead, like the reference's
        # refresh_optimizer_after_model_change (trainer.py:87-92)
        if self.config.load_optimizer_states and not merged_lora:
            try:
                if orbax_backend:
                    loaded = self._restore_orbax_opt(step_dir)
                else:
                    viewed_current = self.opt_state._replace(
                        master=self.module.ckpt_view(self.opt_state.master),
                        exp_avg=self.module.ckpt_view(self.opt_state.exp_avg),
                        exp_avg_sq=self.module.ckpt_view(self.opt_state.exp_avg_sq),
                    )
                    loaded = load_optimizer_checkpoint(step_dir, viewed_current, metas)
                self.opt_state = loaded._replace(
                    master=self.module.ckpt_unview(loaded.master, self.opt_state.master),
                    exp_avg=self.module.ckpt_unview(loaded.exp_avg, self.opt_state.exp_avg),
                    exp_avg_sq=self.module.ckpt_unview(
                        loaded.exp_avg_sq, self.opt_state.exp_avg_sq
                    ),
                )
                optimizer_states_loaded = True
            except FileNotFoundError:
                logger.warning(f"optimizer states absent in {step_dir}")
            except Exception as e:
                # an orbax TREE MISMATCH (architecture/PEFT change) is the
                # same situation as absent npz files: fall back to fresh
                # state. Orbax surfaces mismatches through a zoo of types
                # (KeyError/ValueError/TypeError, AssertionError, its own
                # classes), so the orbax branch treats every non-I/O error
                # as a mismatch. I/O, memory and runtime errors are NOT
                # caught — a corrupt checkpoint or an HBM OOM mid-restore
                # (XLA's RESOURCE_EXHAUSTED is a RuntimeError subclass)
                # must abort, not silently reset Adam moments. The npz
                # path aborts on EVERY error, as before this fallback
                # existed.
                if isinstance(e, (OSError, MemoryError, RuntimeError)):
                    raise
                if not orbax_backend:
                    raise
                logger.warning(
                    f"orbax optimizer tree mismatch ({type(e).__name__}: {e}); "
                    "re-deriving fresh optimizer state"
                )
        self.optimizer_states_loaded = optimizer_states_loaded
        if not optimizer_states_loaded:
            # fp32 masters were copied from the random init; re-derive them
            # from the loaded params or the first step would revert the model
            self.opt_state = self.optimizer.init_state(self.params)
            logger.info("re-derived fresh optimizer state from loaded parameters")
        if self.config.load_context:
            self.context.load_checkpoint(step_dir)
            # the data cursor is a GLOBAL sample count, mesh-independent
            # by construction — but the new batch hierarchy's sampler
            # grid must divide it or micro-batch strides would split
            # mid-step (samples skipped/repeated). Validate at restore
            # time, where the error is actionable, not steps later.
            cfg = self.topology.config
            self.context.consumed_samples = rescale_consumed_samples(
                self.context.consumed_samples,
                micro_batch_size=cfg.micro_batch_size,
                data_parallel_size=cfg.data_parallel_size,
            )
            # the eval cursor advances by the OLD mbs*dp per eval
            # micro-batch, so it is legitimately not aligned to the new
            # grid after a reshard — floor-align it (a few re-seen eval
            # samples are harmless; hard-failing here would kill every
            # downsized relaunch at startup)
            self.context.consumed_eval_samples = rescale_consumed_samples(
                self.context.consumed_eval_samples,
                micro_batch_size=cfg.micro_batch_size,
                data_parallel_size=cfg.data_parallel_size,
                what="consumed_eval_samples",
                on_misaligned="floor",
            )
        logger.info(f"loaded checkpoint {step_dir}")


def _maybe_float(v):
    return None if v is None else float(v)


def _maybe_int(v):
    return None if v is None else int(v)


def _maybe_bool(v):
    return None if v is None else bool(v)
