"""Phase tracing: ``with obs.span("ckpt.commit", step=N): ...``.

Every span lands twice:

- as an observation in the default registry's ``span_seconds`` histogram
  (labelled by span name) — cheap, in-memory, flushed with the per-step
  registry snapshot;
- as a structured ``span`` event through :meth:`logger.log_event`, so
  the PR 4 supervision events and the new telemetry share ONE stream and
  the run-dir analyzer (``python -m scaling_tpu.obs report``) can
  attribute barrier waits and checkpoint commits per host without a
  second file format.

Spans nest (thread-local stack; the parent's name is recorded on the
child) and are exception-safe: a body that raises still emits the span,
marked ``ok=false`` with the exception type, and the exception
propagates untouched.

Device-drain semantics reuse :class:`SynchronizedTimer`'s contract
without forcing a sync: a span measures host wall time unless the caller
hands it device work via ``sp.wait_for(x)``, in which case the exit
drains ``x`` first so the measured time covers the device work. The
default is drain-free — the step path must not gain device syncs outside
profiler windows (unit-asserted).

No jax at module level; the drain imports it lazily.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator, Optional

from ..logging import logger
from .registry import get_registry

_local = threading.local()


def _stack() -> list:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = []
        _local.stack = stack
    return stack


class Span:
    """Handle yielded by :func:`span`; mutate it to enrich the record."""

    __slots__ = ("name", "fields", "_wait_for", "duration_s")

    def __init__(self, name: str, fields: dict):
        self.name = name
        self.fields = fields
        self._wait_for: Any = None
        self.duration_s: Optional[float] = None

    def wait_for(self, x: Any) -> Any:
        """Drain ``x`` (``jax.block_until_ready``) before the span closes,
        so the measured time covers its device work. Returns ``x``."""
        self._wait_for = x
        return x

    def annotate(self, **fields: Any) -> None:
        """Attach extra fields to the emitted span event."""
        self.fields.update(fields)


def current_span() -> Optional[Span]:
    stack = _stack()
    return stack[-1] if stack else None


@contextmanager
def span(name: str, *, step: Optional[int] = None, level: str = "debug",
         registry=None, **fields: Any) -> Iterator[Span]:
    """Trace one phase. ``level`` controls only the console mirror of the
    event (per-step phases default to ``debug`` so steady-state training
    does not quadruple its console output); the events file — when
    configured — receives every span regardless."""
    sp = Span(name, dict(fields))
    stack = _stack()
    parent = stack[-1].name if stack else None
    stack.append(sp)
    ok = True
    error: Optional[str] = None
    start = time.perf_counter()
    try:
        yield sp
        if sp._wait_for is not None:
            # drain INSIDE the measured window: the caller explicitly
            # asked for SynchronizedTimer semantics on this span —
            # opt-in via sp.wait_for(x), never the default
            import jax

            jax.block_until_ready(sp._wait_for)  # sta: disable=STA010
    except BaseException as e:
        ok = False
        error = type(e).__name__
        raise
    finally:
        duration = time.perf_counter() - start
        sp.duration_s = duration
        stack.pop()
        _emit(sp, parent, duration, ok, error, step, level, registry)


def _emit(sp: Span, parent: Optional[str], duration: float, ok: bool,
          error: Optional[str], step: Optional[int], level: str,
          registry) -> None:
    reg = registry if registry is not None else get_registry()
    reg.histogram("span_seconds", labels={"span": sp.name}).observe(duration)
    event_fields = dict(sp.fields)
    event_fields.update(span=sp.name, dur_s=round(duration, 6), ok=ok)
    if parent is not None:
        event_fields["parent"] = parent
    if step is not None:
        event_fields["step"] = step
    if error is not None:
        event_fields["error"] = error
    # host + relaunch epoch ride every span so the analyzer can attribute
    # per host AND per supervisor epoch — the same step gets re-saved and
    # the same barrier re-waited after a relaunch, and merging those
    # incidents would corrupt the arrived-last verdict
    for env_var, field in (("SCALING_TPU_HOST_ID", "host"),
                           ("SCALING_TPU_COORD_EPOCH", "epoch")):
        raw = os.environ.get(env_var)
        if raw is not None and field not in event_fields:
            try:
                event_fields[field] = int(raw)
            except ValueError:
                logger.warning(f"non-integer {env_var} {raw!r} ignored")
    # spans skip the per-record fsync: 3-4 of them land per training
    # step, and the durability contract belongs to lifecycle events
    logger.log_event("span", _level=level, _fsync=False, **event_fields)
